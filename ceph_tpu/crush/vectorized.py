"""Vectorized CRUSH on TPU: bulk PG->OSD mapping as one XLA launch.

The reference recomputes full-cluster mappings on host thread pools
(ParallelPGMapper, src/osd/OSDMapMapping.h:18; used by the balancer and
OSDMonitor's PrimeTempJob).  Here the whole job is one data-parallel
program over the PG axis: straw2 draws become gathers into the fixed-point
log tables plus an argmax, and the firstn/indep retry loops become bounded
`lax.while_loop`s with per-lane masks -- decision-identical to the scalar
mapper (ceph_tpu/crush/mapper.py), which is itself pinned to mapper.c.

Supported map shape for the fused path: uniform-depth straw2
hierarchies of ANY depth (root->osds up through root->row->rack->host->
osd and deeper) with the standard replicated (chooseleaf firstn) /
erasure (chooseleaf indep) rules, jewel tunables, and optional
choose_args weight-sets (the balancer's crush-compat overrides,
mapper.c:289-306).  Anything else falls back to the scalar engine.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import numpy as np

import jax

# straw2 draws are 64-bit fixed-point, which needs jax's x64 mode -- but
# flipping the PROCESS-GLOBAL flag at import time would change numeric
# promotion for every other jax user in an embedding process (importing
# ceph_tpu must be side-effect free).  The x64 requirement is scoped to
# the mapper entry points instead via the thread-local enable_x64
# context (the jit caches key on it, so fused-mapper traces always see
# x64 while the rest of the package traces unchanged).
from jax.experimental import enable_x64 as _enable_x64

import jax.numpy as jnp  # noqa: E402

from .ln import RH_LH_TBL, LL_TBL  # noqa: E402
from .types import (
    CrushMap,
    CRUSH_BUCKET_STRAW2,
    CRUSH_ITEM_NONE,
    CRUSH_RULE_TAKE,
    CRUSH_RULE_EMIT,
    CRUSH_RULE_CHOOSE_FIRSTN,
    CRUSH_RULE_CHOOSE_INDEP,
    CRUSH_RULE_CHOOSELEAF_FIRSTN,
    CRUSH_RULE_CHOOSELEAF_INDEP,
    CRUSH_RULE_SET_CHOOSE_TRIES,
    CRUSH_RULE_SET_CHOOSELEAF_TRIES,
)

# numpy constant: materializing a jnp.int64 here would require x64 at
# import time (exactly what this module must not demand)
S64_MIN = np.int64(-(2**63))
CRUSH_HASH_SEED = np.uint32(1315423911)


def _u32(v):
    return jnp.asarray(v, dtype=jnp.uint32)


def _mix(a, b, c):
    a = a - b; a = a - c; a = a ^ (c >> 13)
    b = b - c; b = b - a; b = b ^ (a << 8)
    c = c - a; c = c - b; c = c ^ (b >> 13)
    a = a - b; a = a - c; a = a ^ (c >> 12)
    b = b - c; b = b - a; b = b ^ (a << 16)
    c = c - a; c = c - b; c = c ^ (b >> 5)
    a = a - b; a = a - c; a = a ^ (c >> 3)
    b = b - c; b = b - a; b = b ^ (a << 10)
    c = c - a; c = c - b; c = c ^ (b >> 15)
    return a, b, c


def hash32_2_jnp(a, b):
    a, b = _u32(a), _u32(b)
    h = _u32(CRUSH_HASH_SEED) ^ a ^ b
    x = jnp.full_like(h, 231232)
    y = jnp.full_like(h, 1232)
    a, b, h = _mix(a, b, h)
    x, a, h = _mix(x, a, h)
    b, y, h = _mix(b, y, h)
    return h


def hash32_3_jnp(a, b, c):
    a, b, c = _u32(a), _u32(b), _u32(c)
    a, b, c = jnp.broadcast_arrays(a, b, c)
    h = _u32(CRUSH_HASH_SEED) ^ a ^ b ^ c
    x = jnp.full_like(h, 231232)
    y = jnp.full_like(h, 1232)
    a, b, h = _mix(a, b, h)
    c, x, h = _mix(c, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    return h


# keep the int64 log tables as NUMPY at module scope: a jnp.asarray
# here would run outside the enable_x64 scope and silently truncate to
# int32.  They become trace-time constants inside crush_ln_jnp, which
# only ever traces under x64.
_RH_LH_NP = np.asarray(RH_LH_TBL, np.int64)   # (258,)
_LL_NP = np.asarray(LL_TBL, np.int64)         # (256,)


def crush_ln_jnp(u):
    """Vector crush_ln over int32 u in [0, 0xffff] -> int64."""
    _RH_LH = jnp.asarray(_RH_LH_NP)
    _LL = jnp.asarray(_LL_NP)
    x = u.astype(jnp.int64) + 1
    need = (x & 0x18000) == 0
    masked = (x & 0x1FFFF).astype(jnp.int32)
    # bit_length via 31 - clz
    bl = 32 - jax.lax.clz(masked)
    bits = jnp.where(need, 16 - bl, 0).astype(jnp.int64)
    x = x << bits
    iexpon = (15 - bits).astype(jnp.int64)
    index1 = ((x >> 8) << 1).astype(jnp.int32)
    rh = _RH_LH[index1 - 256]
    lh = _RH_LH[index1 + 1 - 256]
    xl64 = (x * rh) >> 48
    index2 = (xl64 & 0xFF).astype(jnp.int32)
    ll = _LL[index2]
    return (iexpon << 44) + ((lh + ll) >> 4)


def straw2_draws(x, item_ids, r, weights):
    """Draw values for one bucket: shapes broadcast over (..., n_items).

    x: (...,) int32 lanes; item_ids/weights: (..., n) int32.
    Returns (..., n) int64 draws (S64_MIN where weight==0).
    """
    u = (hash32_3_jnp(x[..., None], item_ids, r[..., None])
         & np.uint32(0xFFFF)).astype(jnp.int32)
    ln = crush_ln_jnp(u) - jnp.int64(0x1000000000000)
    w = weights.astype(jnp.int64)
    draws = jax.lax.div(ln, jnp.maximum(w, 1))
    return jnp.where(w > 0, draws, S64_MIN)


def is_out_jnp(osd_weights, item, x):
    """Vector is_out (mapper.c:419-433): weight is 16.16 reweight."""
    w = osd_weights[item]
    h = hash32_2_jnp(x, item.astype(jnp.uint32)) & np.uint32(0xFFFF)
    probably_out = h.astype(jnp.int32) >= w
    return jnp.where(w >= 0x10000, False,
                     jnp.where(w == 0, True, probably_out))


@dataclass
class CompiledMap:
    """Flattened uniform-depth straw2 hierarchy for the fused path.

    Level l holds every bucket at distance l from the take root as
    padded tables; the choose phase descends them in lockstep (one
    straw2 draw + argmax per level per lane), exactly the recursive
    descent of mapper.c crush_choose_firstn/indep, but data-parallel
    over the lane axis.  Arbitrary depth (root->rack->host->osd and
    deeper) compiles; non-uniform leaf depth or non-straw2 buckets
    fall back to the scalar engine.

    child_ids carry the CRUSH item ids (what straw2 hashes);
    child_idx carry the row index into the NEXT level's tables (or the
    osd id at the last level).  weights are the bucket item weights,
    0-padded; cw holds the choose_args weight-set override per output
    position when the map has one (mapper.c get_choose_arg_weights).
    """

    n_levels: int                       # bucket levels (root = level 0)
    child_ids: list                     # [(B_l, N_l) int32]
    child_idx: list                     # [(B_l, N_l) int32]
    weights: list                       # [(B_l, N_l) int32]
    cw: list | None                     # [(P, B_l, N_l)] or None
    bucket_ids: list                    # [(B_l,) int32] crush ids per level
    max_devices: int
    leaf_parent_types: frozenset = frozenset()

    @classmethod
    def from_map(cls, crush_map: CrushMap, root_id: int,
                 choose_args: dict | None = None) -> "CompiledMap":
        levels: list[list] = [[crush_map.buckets[root_id]]]
        while True:
            cur = levels[-1]
            kinds = set()
            for b in cur:
                if b.alg != CRUSH_BUCKET_STRAW2:
                    raise ValueError("fused path requires straw2")
                for i in b.items:
                    kinds.add(i < 0)
            if kinds == {True}:
                levels.append([crush_map.buckets.get(i)
                               for b in cur for i in b.items])
                if any(b is None for b in levels[-1]):
                    raise ValueError("dangling bucket reference")
            elif kinds == {False}:
                break                   # this level's items are osds
            else:
                raise ValueError("mixed osd/bucket children "
                                 "unsupported by the fused path")
        # dense row index per bucket id per level
        idx_of = [{b.id: j for j, b in enumerate(lv)} for lv in levels]
        child_ids, child_idx, weights, cw, bids = [], [], [], [], []
        ca = choose_args if choose_args is not None else \
            getattr(crush_map, "choose_args", None)
        positions = 1
        if ca:
            for arg in ca.values():
                if arg.get("weight_set"):
                    positions = max(positions, len(arg["weight_set"]))
        for l, lv in enumerate(levels):
            maxn = max(b.size for b in lv)
            ids = np.zeros((len(lv), maxn), np.int32)
            idx = np.zeros((len(lv), maxn), np.int32)
            w = np.zeros((len(lv), maxn), np.int32)
            cwl = np.zeros((positions, len(lv), maxn), np.int32)
            for j, b in enumerate(lv):
                arg = (ca or {}).get(b.id) or {}
                hash_ids = arg.get("ids") or b.items
                ids[j, :b.size] = hash_ids
                ids[j, b.size:] = hash_ids[0] if b.size else 0
                w[j, :b.size] = b.item_weights
                ws = arg.get("weight_set")
                for pos in range(positions):
                    src = (ws[min(pos, len(ws) - 1)] if ws
                           else b.item_weights)
                    cwl[pos, j, :b.size] = src
                if l + 1 < len(levels):
                    idx[j, :b.size] = [idx_of[l + 1][i]
                                       for i in b.items]
                    idx[j, b.size:] = idx[j, 0] if b.size else 0
                else:
                    idx[j, :b.size] = b.items
                    idx[j, b.size:] = b.items[0] if b.size else 0
            child_ids.append(ids)
            child_idx.append(idx)
            weights.append(w)
            cw.append(cwl)
            bids.append(np.asarray([b.id for b in lv], np.int32))
        has_ca = bool(ca) and any(
            a.get("weight_set") or a.get("ids") for a in ca.values())
        return cls(len(levels), child_ids, child_idx, weights,
                   cw if has_ca else None, bids,
                   crush_map.max_devices,
                   frozenset(b.type for b in levels[-1]))


def _rule_shape(crush_map: CrushMap, ruleno: int):
    """Parse a rule into (root_id, firstn, leaf, choose_tries, leaf_tries)."""
    rule = crush_map.rules[ruleno]
    t = crush_map.tunables
    choose_tries = t.choose_total_tries + 1
    leaf_tries = 0
    root_id = None
    mode = None
    choose_type = 0
    for step in rule.steps:
        if step.op == CRUSH_RULE_SET_CHOOSE_TRIES:
            choose_tries = step.arg1
        elif step.op == CRUSH_RULE_SET_CHOOSELEAF_TRIES:
            leaf_tries = step.arg1
        elif step.op == CRUSH_RULE_TAKE:
            root_id = step.arg1
        elif step.op in (CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSELEAF_FIRSTN,
                         CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_CHOOSELEAF_INDEP):
            mode = step.op
            choose_type = step.arg2
    firstn = mode in (CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSELEAF_FIRSTN)
    leaf = mode in (CRUSH_RULE_CHOOSELEAF_FIRSTN, CRUSH_RULE_CHOOSELEAF_INDEP)
    return root_id, firstn, leaf, choose_tries, leaf_tries, choose_type


class VectorCrush:
    """Bulk mapper for one (map, rule) pair, any uniform depth."""

    def __init__(self, crush_map: CrushMap, ruleno: int,
                 choose_args: dict | None = None) -> None:
        (root_id, firstn, leaf, choose_tries, leaf_tries,
         choose_type) = _rule_shape(crush_map, ruleno)
        self.cm = CompiledMap.from_map(crush_map, root_id, choose_args)
        # chooseleaf picks buckets at the LAST bucket level then
        # recurses to an osd; plain choose must name the device level
        self.leaf = leaf
        if leaf:
            # only the tree under THIS rule's take root matters: a
            # second hierarchy's leaf parents must not veto the map
            if self.cm.leaf_parent_types != {choose_type}:
                raise ValueError(
                    "chooseleaf type must be the osd-parent level for "
                    "the fused path")
        elif choose_type != 0:
            raise ValueError("plain choose of a bucket type needs the "
                             "scalar engine")
        t = crush_map.tunables
        self.firstn = firstn
        self.choose_tries = choose_tries
        self.leaf_tries = leaf_tries
        self.vary_r = t.chooseleaf_vary_r
        self.stable = t.chooseleaf_stable
        self.descend_once = t.chooseleaf_descend_once
        if firstn:
            self.recurse_tries = (leaf_tries if leaf_tries
                                  else (1 if self.descend_once
                                        else choose_tries))
        else:
            self.recurse_tries = leaf_tries if leaf_tries else 1
        if not self.stable or self.vary_r != 1:
            # scalar fallback covers other tunable profiles
            raise ValueError("fused path implements jewel tunables")

    def _tables(self):
        cm = self.cm
        ids = [jnp.asarray(t) for t in cm.child_ids]
        idx = [jnp.asarray(t) for t in cm.child_idx]
        if cm.cw is not None:
            w = [jnp.asarray(t) for t in cm.cw]      # (P, B, N)
        else:
            w = [jnp.asarray(t)[None] for t in cm.weights]
        return ids, idx, w

    def _descend(self, ids, idx, w, xs, r, pos, upto: int):
        """Lockstep descent: levels 0..upto-1, one draw per level.
        Returns row indices into level ``upto``'s tables (or osd ids
        when upto == n_levels).  ``pos`` is the choose_args weight-set
        position -- a scalar, or a PER-LANE vector when lanes have
        placed different counts (firstn's outpos)."""
        L = xs.shape[0]
        cur = jnp.zeros((L,), jnp.int32)
        for l in range(upto):
            wl = w[l]
            p = jnp.clip(jnp.asarray(pos), 0, wl.shape[0] - 1)
            draws = straw2_draws(xs, ids[l][cur], r, wl[p, cur])
            j = jnp.argmax(draws, axis=-1)
            cur = idx[l][cur, j]
        return cur

    def _leaf_descend(self, ids, idx, w, xs, host_idx, sub_r, rep,
                      numrep, osd_weights, taken, pos):
        """chooseleaf recursion into the chosen last-level bucket:
        up to recurse_tries draws, rejecting out osds and (firstn)
        collisions with already-placed osds."""
        lvl = self.cm.n_levels - 1
        L = xs.shape[0]
        wl = w[lvl]
        pos = jnp.clip(jnp.asarray(pos), 0, wl.shape[0] - 1)

        def cond(st):
            ft, found, _ = st
            # one shared try counter: a still-searching lane's personal
            # ftotal equals the iteration count (it either found and
            # froze, or rejected every round so far)
            return jnp.any(~found) & (ft < self.recurse_tries)

        def body(st):
            ft, found, osd = st
            if self.firstn:
                # leaf recursion: numrep=1, rep'=0 (stable), so
                # r_leaf = sub_r + ftotal_leaf
                r_leaf = (sub_r + ft).astype(jnp.int32)
            else:
                r_leaf = (rep + sub_r + numrep * ft).astype(jnp.int32)
            draws = straw2_draws(xs, ids[lvl][host_idx], r_leaf,
                                 wl[pos, host_idx])
            j = jnp.argmax(draws, axis=-1)
            cand = idx[lvl][host_idx, j]
            bad = is_out_jnp(osd_weights, cand, xs)
            if taken is not None:
                for t in taken:
                    bad |= t == cand
            ok = ~found & ~bad
            osd = jnp.where(ok, cand, osd)
            return ft + 1, found | ok, osd

        init = (jnp.int32(0), jnp.zeros((L,), bool),
                jnp.full((L,), CRUSH_ITEM_NONE, jnp.int32))
        _, found, osd = jax.lax.while_loop(cond, body, init)
        return osd, found

    # -- firstn -------------------------------------------------------------
    @partial(jax.jit, static_argnames=("self", "numrep"))
    def map_firstn(self, xs: jnp.ndarray, numrep: int,
                   osd_weights: jnp.ndarray) -> jnp.ndarray:
        cm = self.cm
        ids, idx, w = self._tables()
        L = xs.shape[0]
        # chooseleaf targets the last bucket level; plain choose (no
        # leaf recursion) targets the device level
        bucket_levels = cm.n_levels - 1 if self.leaf else cm.n_levels
        out = jnp.full((L, numrep), CRUSH_ITEM_NONE, jnp.int32)
        out_sel = jnp.full((L, numrep), jnp.int32(2**31 - 1), jnp.int32)
        # per-lane count of PLACED replicas: the scalar engine's
        # outpos, which is the choose_args weight-set position (a lane
        # whose earlier slot exhausted its tries keeps drawing later
        # slots at the unadvanced position, exactly as mapper.c does)
        placed = jnp.zeros((L,), jnp.int32)

        for rep in range(numrep):
            def cond(state):
                ftotal, done, _, _ = state
                return jnp.any(~done & (ftotal < self.choose_tries))

            def body(state):
                ftotal, done, sel, osd = state
                r = (rep + ftotal).astype(jnp.int32)
                cand_sel = self._descend(ids, idx, w, xs, r, placed,
                                         bucket_levels)
                collide = jnp.zeros((L,), bool)
                for j in range(rep):
                    collide |= out_sel[:, j] == cand_sel
                if self.leaf:
                    # vary_r=1: sub_r = r >> 0 = r
                    cand_osd, found = self._leaf_descend(
                        ids, idx, w, xs, cand_sel, r, rep, numrep,
                        osd_weights,
                        [out[:, j] for j in range(rep)], placed)
                    reject = ~found
                else:
                    cand_osd = cand_sel
                    reject = is_out_jnp(osd_weights, cand_osd, xs)
                    for j in range(rep):
                        reject |= out[:, j] == cand_osd
                ok = ~done & ~collide & ~reject
                sel = jnp.where(ok, cand_sel, sel)
                osd = jnp.where(ok, cand_osd, osd)
                newdone = done | ok
                ftotal = jnp.where(~newdone, ftotal + 1, ftotal)
                return ftotal, newdone, sel, osd

            init = (jnp.zeros((L,), jnp.int32), jnp.zeros((L,), bool),
                    jnp.full((L,), 2**31 - 1, jnp.int32),
                    jnp.full((L,), CRUSH_ITEM_NONE, jnp.int32))
            ftotal, done, sel, osd = jax.lax.while_loop(cond, body, init)
            out = out.at[:, rep].set(
                jnp.where(done, osd, CRUSH_ITEM_NONE))
            out_sel = out_sel.at[:, rep].set(
                jnp.where(done, sel, 2**31 - 1))
            placed = placed + done.astype(jnp.int32)
        # scalar firstn COMPACTS (an exhausted slot leaves no hole):
        # shift placed entries left, NONE-pad the tail
        is_none = out == CRUSH_ITEM_NONE
        order = jnp.argsort(is_none, axis=1, stable=True)
        return jnp.take_along_axis(out, order, axis=1)

    # -- indep --------------------------------------------------------------
    @partial(jax.jit, static_argnames=("self", "numrep"))
    def map_indep(self, xs: jnp.ndarray, numrep: int,
                  osd_weights: jnp.ndarray) -> jnp.ndarray:
        cm = self.cm
        ids, idx, w = self._tables()
        L = xs.shape[0]
        UNDEF = jnp.int32(0x7FFFFFFE)
        bucket_levels = cm.n_levels - 1 if self.leaf else cm.n_levels

        def cond(state):
            ftotal, out_h, out_o = state
            return (ftotal < self.choose_tries) & jnp.any(out_h == UNDEF)

        def body(state):
            ftotal, out_h, out_o = state
            for rep in range(numrep):
                slot_undef = out_h[:, rep] == UNDEF
                r = (rep + numrep * ftotal).astype(jnp.int32)
                # weight-set position is the top call's OUTPOS (0),
                # not the replica slot (crush_choose_indep passes its
                # own outpos down); the leaf recursion's outpos IS the
                # slot, so _leaf_descend keeps rep
                cand_sel = self._descend(ids, idx, w, xs, r, 0,
                                         bucket_levels)
                collide = jnp.zeros((L,), bool)
                for j in range(numrep):
                    collide |= out_h[:, j] == cand_sel
                if self.leaf:
                    osd, found = self._leaf_descend(
                        ids, idx, w, xs, cand_sel, r, rep, numrep,
                        osd_weights, None, rep)
                else:
                    osd = cand_sel
                    found = ~is_out_jnp(osd_weights, osd, xs)
                ok = slot_undef & ~collide & found
                out_h = out_h.at[:, rep].set(
                    jnp.where(ok, cand_sel, out_h[:, rep]))
                out_o = out_o.at[:, rep].set(
                    jnp.where(ok, osd, out_o[:, rep]))
            return ftotal + 1, out_h, out_o

        init = (jnp.int32(0),
                jnp.full((L, numrep), UNDEF, jnp.int32),
                jnp.full((L, numrep), UNDEF, jnp.int32))
        _, out_h, out_o = jax.lax.while_loop(cond, body, init)
        return jnp.where(out_o == UNDEF, CRUSH_ITEM_NONE, out_o)

    def map_pgs(self, xs, numrep: int, osd_weights) -> np.ndarray:
        with _enable_x64():
            xs = jnp.asarray(xs, jnp.int32)
            w = jnp.asarray(osd_weights, jnp.int32)
            if self.firstn:
                # lint: disable=device-path-host-sync -- the single post-launch materialization of the bulk map
                return np.asarray(self.map_firstn(xs, numrep, w))
            # lint: disable=device-path-host-sync -- the single post-launch materialization of the bulk map
            return np.asarray(self.map_indep(xs, numrep, w))
