"""CRUSH map model: buckets, rules, tunables.

Data-model rendering of src/crush/crush.h: bucket algorithms
(crush.h:141-191), rule steps (crush.h:54-74), rule types (crush.h:97-100),
tunables (crush.h:374-395).  Weights are 16.16 fixed point throughout.
"""

from __future__ import annotations

from dataclasses import dataclass, field

CRUSH_BUCKET_UNIFORM = 1
CRUSH_BUCKET_LIST = 2
CRUSH_BUCKET_TREE = 3
CRUSH_BUCKET_STRAW = 4
CRUSH_BUCKET_STRAW2 = 5

CRUSH_ITEM_UNDEF = 0x7FFFFFFE
CRUSH_ITEM_NONE = 0x7FFFFFFF

CRUSH_HASH_RJENKINS1 = 0

# rule step ops
CRUSH_RULE_NOOP = 0
CRUSH_RULE_TAKE = 1
CRUSH_RULE_CHOOSE_FIRSTN = 2
CRUSH_RULE_CHOOSE_INDEP = 3
CRUSH_RULE_EMIT = 4
CRUSH_RULE_CHOOSELEAF_FIRSTN = 6
CRUSH_RULE_CHOOSELEAF_INDEP = 7
CRUSH_RULE_SET_CHOOSE_TRIES = 8
CRUSH_RULE_SET_CHOOSELEAF_TRIES = 9
CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES = 10
CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES = 11
CRUSH_RULE_SET_CHOOSELEAF_VARY_R = 12
CRUSH_RULE_SET_CHOOSELEAF_STABLE = 13

# rule types
CRUSH_RULE_TYPE_REPLICATED = 1
CRUSH_RULE_TYPE_ERASURE = 3


@dataclass
class Tunables:
    """Default == "jewel" profile (CrushWrapper.h set_tunables_jewel)."""

    choose_local_tries: int = 0
    choose_local_fallback_tries: int = 0
    choose_total_tries: int = 50
    chooseleaf_descend_once: int = 1
    chooseleaf_vary_r: int = 1
    chooseleaf_stable: int = 1
    straw_calc_version: int = 1


@dataclass
class Bucket:
    id: int                      # negative
    type: int                    # bucket type id (host=1, rack=2, ... by map)
    alg: int = CRUSH_BUCKET_STRAW2
    hash: int = CRUSH_HASH_RJENKINS1
    items: list[int] = field(default_factory=list)
    item_weights: list[int] = field(default_factory=list)  # 16.16 fixed
    # tree/list buckets carry derived node/sum weights, built lazily
    _tree_node_weights: list[int] | None = None
    _list_sum_weights: list[int] | None = None

    @property
    def size(self) -> int:
        return len(self.items)

    @property
    def weight(self) -> int:
        return sum(self.item_weights)


@dataclass
class RuleStep:
    op: int
    arg1: int = 0
    arg2: int = 0


@dataclass
class Rule:
    rule_id: int
    type: int = CRUSH_RULE_TYPE_REPLICATED
    steps: list[RuleStep] = field(default_factory=list)


class CrushMap:
    def __init__(self, tunables: Tunables | None = None) -> None:
        self.buckets: dict[int, Bucket] = {}    # id (negative) -> bucket
        self.rules: dict[int, Rule] = {}
        self.tunables = tunables or Tunables()
        self.max_devices = 0
        self.type_names: dict[int, str] = {0: "osd", 1: "host", 2: "rack",
                                           10: "root"}
        self.bucket_names: dict[int, str] = {}
        self.device_classes: dict[int, str] = {}
        # choose_args (CrushWrapper.h choose_args_map_t): bucket id ->
        # {"weight_set": [[w per item] per position], "ids": [...]}.
        # The balancer's crush-compat mode steers placement by writing
        # position-specific weight overrides here instead of touching
        # the real hierarchy weights (mapper.c:289-306).
        self.choose_args: dict[int, dict] = {}

    def add_bucket(self, bucket: Bucket, name: str | None = None) -> None:
        assert bucket.id < 0, "bucket ids are negative"
        self.buckets[bucket.id] = bucket
        if name:
            self.bucket_names[bucket.id] = name
        for item in bucket.items:
            if item >= 0:
                self.max_devices = max(self.max_devices, item + 1)

    def add_rule(self, rule: Rule) -> None:
        self.rules[rule.rule_id] = rule

    def bucket(self, item_id: int) -> Bucket | None:
        return self.buckets.get(item_id)

    def create_choose_args(self, positions: int) -> None:
        """Seed a weight-set for every straw2 bucket with its current
        weights at every position (CrushWrapper::create_choose_args) --
        the starting point the balancer then adjusts."""
        for bid, b in self.buckets.items():
            self.choose_args[bid] = {
                "weight_set": [list(b.item_weights)
                               for _ in range(positions)]}

    def choose_args_adjust_item_weight(self, item: int,
                                       weight: int | list[int]) -> None:
        """Set ``item``'s weight-set weight in every bucket that holds
        it, one value per position (CrushWrapper::
        choose_args_adjust_item_weight)."""
        for bid, b in self.buckets.items():
            if item not in b.items:
                continue
            arg = self.choose_args.get(bid)
            if arg is None:
                continue
            i = b.items.index(item)
            ws = arg["weight_set"]
            for pos, row in enumerate(ws):
                row[i] = (weight[min(pos, len(weight) - 1)]
                          if isinstance(weight, list) else weight)

    def name_to_id(self, name: str) -> int | None:
        for bid, n in self.bucket_names.items():
            if n == name:
                return bid
        return None

    def is_device(self, item_id: int) -> bool:
        return item_id >= 0

    def item_type(self, item_id: int) -> int:
        if item_id >= 0:
            return 0
        b = self.buckets.get(item_id)
        return b.type if b else -1
