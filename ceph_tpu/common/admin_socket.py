"""Per-daemon admin socket: live introspection over a unix socket.

src/common/admin_socket.cc analog: a daemon binds <dir>/<name>.asok;
clients send one JSON request line {"prefix": "...", ...} and read one
JSON reply — the `ceph daemon <name> <cmd>` transport.  Built-in
commands: help, version; daemons register the rest (perf dump, status,
config show/get/set, dump_ops_in_flight, ...).
"""

from __future__ import annotations

import asyncio
import json
import os
from typing import Awaitable, Callable

Handler = Callable[[dict], Awaitable[object]]


class AdminSocket:
    def __init__(self, path: str) -> None:
        self.path = path
        self._server: asyncio.AbstractServer | None = None
        self._handlers: dict[str, tuple[str, Handler]] = {}
        self.register("help", "list supported commands", self._h_help)
        self.register("version", "framework version", self._h_version)

    def register(self, prefix: str, desc: str, handler: Handler) -> None:
        self._handlers[prefix] = (desc, handler)

    async def start(self) -> str:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        if os.path.exists(self.path):
            os.unlink(self.path)
        self._server = await asyncio.start_unix_server(
            self._on_client, path=self.path)
        return self.path

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 1.0)
            except asyncio.TimeoutError:
                pass
        if os.path.exists(self.path):
            os.unlink(self.path)

    async def _on_client(self, reader, writer) -> None:
        try:
            line = await asyncio.wait_for(reader.readline(), 10)
            req = json.loads(line or b"{}")
            prefix = req.get("prefix", "help")
            entry = self._handlers.get(prefix)
            if entry is None:
                reply = {"error": f"unknown command {prefix!r}; "
                                  f"try 'help'"}
            else:
                try:
                    reply = {"ok": True,
                             "result": await entry[1](req)}
                except Exception as e:
                    reply = {"error": str(e)}
            writer.write(json.dumps(reply, default=str).encode() + b"\n")
            await writer.drain()
        except (asyncio.TimeoutError, json.JSONDecodeError,
                ConnectionError, OSError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _h_help(self, req: dict) -> dict:
        return {p: desc for p, (desc, _) in sorted(self._handlers.items())}

    async def _h_version(self, req: dict) -> dict:
        return {"name": "ceph-tpu", "version": "0.1"}


async def admin_command(path: str, prefix: str, **kwargs) -> object:
    """Client side (`ceph daemon` analog): one command, one reply."""
    reader, writer = await asyncio.open_unix_connection(path)
    try:
        req = {"prefix": prefix, **kwargs}
        writer.write(json.dumps(req).encode() + b"\n")
        await writer.drain()
        line = await asyncio.wait_for(reader.readline(), 10)
        if not line:
            raise RuntimeError(
                f"daemon at {path} closed connection without replying")
        reply = json.loads(line)
    finally:
        writer.close()
    if "error" in reply:
        raise RuntimeError(reply["error"])
    return reply["result"]
