"""Typed per-daemon performance counters.

src/common/perf_counters.cc analog: plain counters (u64), gauges,
time-averages (sum+count pairs, the avgcount scheme), and fixed-bucket
histograms; collections are dumped as JSON via the admin socket
(`perf dump`) and scraped by the mgr analog.
"""

from __future__ import annotations

import bisect
import threading
import time


class PerfCounters:
    """One component's counter set (e.g. 'osd', 'paxos', 'messenger')."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {}
        self._gauges: dict[str, float] = {}
        self._avgs: dict[str, tuple[float, int]] = {}   # sum, count
        self._hists: dict[str, tuple[list[float], list[int]]] = {}
        self._hist_sums: dict[str, tuple[float, int]] = {}

    def inc(self, key: str, by: int = 1) -> None:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + by

    def get(self, key: str, default: int = 0) -> int:
        """One plain counter's value without a full dump() (chaos/test
        assertions read single counters in tight loops)."""
        with self._lock:
            return self._counters.get(key, default)

    def set_gauge(self, key: str, value: float) -> None:
        with self._lock:
            self._gauges[key] = value

    def tinc(self, key: str, seconds: float) -> None:
        """Time-average sample (avgcount scheme)."""
        with self._lock:
            s, c = self._avgs.get(key, (0.0, 0))
            self._avgs[key] = (s + seconds, c + 1)

    def time(self, key: str):
        """Context manager timing a block into tinc(key)."""
        return _Timer(self, key)

    def hist_register(self, key: str, buckets: list[float]) -> None:
        with self._lock:
            self._hists[key] = (list(buckets), [0] * (len(buckets) + 1))
            self._hist_sums[key] = (0.0, 0)

    def hist_sample(self, key: str, value: float) -> None:
        with self._lock:
            buckets, counts = self._hists[key]
            counts[bisect.bisect_right(buckets, value)] += 1
            s, c = self._hist_sums[key]
            self._hist_sums[key] = (s + value, c + 1)

    def dump(self) -> dict:
        with self._lock:
            out: dict = dict(self._counters)
            out.update({k: v for k, v in self._gauges.items()})
            for k, (s, c) in self._avgs.items():
                out[k] = {"avgcount": c, "sum": s,
                          "avg": (s / c if c else 0.0)}
            for k, (buckets, counts) in self._hists.items():
                s, c = self._hist_sums[k]
                # avg alongside the buckets: a scraper reading mean
                # occupancy (e.g. stripes-per-batch) should not have
                # to re-derive it from bucket midpoints
                out[k] = {"buckets": buckets, "counts": counts,
                          "count": c, "sum": s,
                          "avg": (s / c if c else 0.0)}
            return out


class _Timer:
    def __init__(self, pc: PerfCounters, key: str) -> None:
        self.pc, self.key = pc, key

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.pc.tinc(self.key, time.perf_counter() - self.t0)
        return False


class PerfCountersCollection:
    """All counter sets of one daemon (PerfCountersCollection analog)."""

    def __init__(self) -> None:
        self._sets: dict[str, PerfCounters] = {}

    def create(self, name: str) -> PerfCounters:
        pc = self._sets.get(name)
        if pc is None:
            pc = self._sets[name] = PerfCounters(name)
        return pc

    def adopt(self, pc: PerfCounters) -> PerfCounters:
        """Register an externally-owned counter set under its own name
        (e.g. the OSDMap's placement_cache counters, which live and
        die with the map object) so dump() and get() cover it."""
        self._sets[pc.name] = pc
        return pc

    def get(self, name: str) -> PerfCounters | None:
        return self._sets.get(name)

    def dump(self) -> dict:
        return {name: pc.dump() for name, pc in sorted(self._sets.items())}
