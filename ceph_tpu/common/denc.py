"""denc: versioned, bounded binary encoding (src/include/denc.h analog).

The reference serializes every persistent/wire type with a tiny
discipline that buys decades of compat:

  ENCODE_START(v, compat, bl)  -> struct_v u8 | struct_compat u8 | len u32
  ...fixed-width LE fields...
  ENCODE_FINISH                -> patches len

  DECODE_START(v, p)  -> fails if struct_compat > the code's version,
  DECODE_FINISH       -> skips unread trailing bytes (a NEWER encoder's
                         extra fields are silently ignored)

That skip-unknown-tail is the entire forward-compat story: old code
reads new encodings (up to struct_compat), new code reads old ones
(version checks gate new fields).  This module renders the same
contract in Python; byte-stability is enforced by the committed corpus
under tests/fixtures/corpus (the ceph-object-corpus discipline,
checked by tools/dencoder.py the way ceph-dencoder does).
"""

from __future__ import annotations

import struct


class DencError(Exception):
    pass


class IncompatibleVersion(DencError):
    pass


def denc_bytes(obj) -> bytes:
    """Encode one denc-capable object (has .denc(enc)) to bytes."""
    enc = Encoder()
    obj.denc(enc)
    return enc.bytes()


class Encoder:
    def __init__(self) -> None:
        self.buf = bytearray()
        self._starts: list[int] = []

    # -- primitives (fixed-width little-endian, like denc) ------------------
    def u8(self, v: int) -> "Encoder":
        self.buf.append(v & 0xFF)
        return self

    def u16(self, v: int) -> "Encoder":
        self.buf += struct.pack("<H", v & 0xFFFF)
        return self

    def u32(self, v: int) -> "Encoder":
        self.buf += struct.pack("<I", v & 0xFFFFFFFF)
        return self

    def u64(self, v: int) -> "Encoder":
        self.buf += struct.pack("<Q", v & 0xFFFFFFFFFFFFFFFF)
        return self

    def i64(self, v: int) -> "Encoder":
        self.buf += struct.pack("<q", v)
        return self

    def f64(self, v: float) -> "Encoder":
        self.buf += struct.pack("<d", v)
        return self

    def boolean(self, v: bool) -> "Encoder":
        return self.u8(1 if v else 0)

    def blob(self, v: bytes) -> "Encoder":
        self.u32(len(v))
        self.buf += v
        return self

    def string(self, v: str) -> "Encoder":
        return self.blob(v.encode("utf-8"))

    def list(self, items, fn) -> "Encoder":
        self.u32(len(items))
        for it in items:
            fn(self, it)
        return self

    def map(self, d, kfn, vfn) -> "Encoder":
        self.u32(len(d))
        for k in sorted(d):        # deterministic byte output
            kfn(self, k)
            vfn(self, d[k])
        return self

    def optional(self, v, fn) -> "Encoder":
        self.boolean(v is not None)
        if v is not None:
            fn(self, v)
        return self

    # -- versioned envelope --------------------------------------------------
    def start(self, v: int, compat: int) -> "Encoder":
        """ENCODE_START: version byte, compat byte, length placeholder."""
        self.u8(v).u8(compat)
        self._starts.append(len(self.buf))
        self.u32(0)
        return self

    def finish(self) -> "Encoder":
        """ENCODE_FINISH: patch the length of the innermost envelope."""
        at = self._starts.pop()
        ln = len(self.buf) - at - 4
        self.buf[at:at + 4] = struct.pack("<I", ln)
        return self

    def bytes(self) -> bytes:
        if self._starts:
            raise DencError("unbalanced start/finish")
        return bytes(self.buf)


class Decoder:
    def __init__(self, data: bytes, pos: int = 0) -> None:
        self.data = memoryview(data)
        self.pos = pos
        self._ends: list[int] = []

    def _take(self, n: int) -> memoryview:
        end = self._ends[-1] if self._ends else len(self.data)
        if self.pos + n > end:
            raise DencError(
                f"decode past end ({self.pos}+{n} > {end})")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def u8(self) -> int:
        return self._take(1)[0]

    def u16(self) -> int:
        return struct.unpack("<H", self._take(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self._take(8))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self._take(8))[0]

    def boolean(self) -> bool:
        return self.u8() != 0

    def blob(self) -> bytes:
        return bytes(self._take(self.u32()))

    def string(self) -> str:
        return self.blob().decode("utf-8")

    def list(self, fn) -> list:
        return [fn(self) for _ in range(self.u32())]

    def map(self, kfn, vfn) -> dict:
        return {kfn(self): vfn(self) for _ in range(self.u32())}

    def optional(self, fn):
        return fn(self) if self.boolean() else None

    # -- versioned envelope --------------------------------------------------
    def start(self, supported: int) -> int:
        """DECODE_START: returns struct_v; raises when the encoder
        declared compat above what this code supports."""
        v = self.u8()
        compat = self.u8()
        ln = self.u32()
        if compat > supported:
            raise IncompatibleVersion(
                f"encoding requires v>={compat}, code supports "
                f"{supported}")
        if ln > self.remaining():
            # an envelope may never claim bytes beyond its parent (or
            # the buffer): a lying length would let reads walk into
            # sibling data instead of failing
            raise DencError(
                f"envelope length {ln} exceeds remaining "
                f"{self.remaining()}")
        self._ends.append(self.pos + ln)
        return v

    def finish(self) -> None:
        """DECODE_FINISH: skip unread tail (newer encoder's fields)."""
        self.pos = self._ends.pop()

    def remaining(self) -> int:
        end = self._ends[-1] if self._ends else len(self.data)
        return end - self.pos
