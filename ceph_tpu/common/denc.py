"""denc: versioned, bounded binary encoding (src/include/denc.h analog).

The reference serializes every persistent/wire type with a tiny
discipline that buys decades of compat:

  ENCODE_START(v, compat, bl)  -> struct_v u8 | struct_compat u8 | len u32
  ...fixed-width LE fields...
  ENCODE_FINISH                -> patches len

  DECODE_START(v, p)  -> fails if struct_compat > the code's version,
  DECODE_FINISH       -> skips unread trailing bytes (a NEWER encoder's
                         extra fields are silently ignored)

That skip-unknown-tail is the entire forward-compat story: old code
reads new encodings (up to struct_compat), new code reads old ones
(version checks gate new fields).  This module renders the same
contract in Python; byte-stability is enforced by the committed corpus
under tests/fixtures/corpus (the ceph-object-corpus discipline,
checked by tools/dencoder.py the way ceph-dencoder does).
"""

from __future__ import annotations

import struct


_FAST = None
_FAST_TRIED = False


def _fast():
    """The C value codec (byte-identical; see native/denc_value.cc)."""
    global _FAST, _FAST_TRIED
    if not _FAST_TRIED:
        _FAST_TRIED = True
        from ..native import get_dencfast
        _FAST = get_dencfast()
    return _FAST


class DencError(ValueError):
    # subclasses ValueError: the messenger read loops treat any
    # ValueError as a framing error (close/reconnect), and a malformed
    # denc envelope must take that path exactly as bad JSON used to
    pass


class IncompatibleVersion(DencError):
    pass


def denc_bytes(obj) -> bytes:
    """Encode one denc-capable object (has .denc(enc)) to bytes."""
    enc = Encoder()
    obj.denc(enc)
    return enc.bytes()


class Encoder:
    def __init__(self) -> None:
        self.buf = bytearray()
        self._starts: list[int] = []

    # -- primitives (fixed-width little-endian, like denc) ------------------
    def u8(self, v: int) -> "Encoder":
        self.buf.append(v & 0xFF)
        return self

    def u16(self, v: int) -> "Encoder":
        self.buf += struct.pack("<H", v & 0xFFFF)
        return self

    def u32(self, v: int) -> "Encoder":
        self.buf += struct.pack("<I", v & 0xFFFFFFFF)
        return self

    def u64(self, v: int) -> "Encoder":
        self.buf += struct.pack("<Q", v & 0xFFFFFFFFFFFFFFFF)
        return self

    def i64(self, v: int) -> "Encoder":
        self.buf += struct.pack("<q", v)
        return self

    def f64(self, v: float) -> "Encoder":
        self.buf += struct.pack("<d", v)
        return self

    def boolean(self, v: bool) -> "Encoder":
        return self.u8(1 if v else 0)

    def blob(self, v: bytes) -> "Encoder":
        self.u32(len(v))
        self.buf += v
        return self

    def string(self, v: str) -> "Encoder":
        return self.blob(v.encode("utf-8"))

    def list(self, items, fn) -> "Encoder":
        self.u32(len(items))
        for it in items:
            fn(self, it)
        return self

    def map(self, d, kfn, vfn) -> "Encoder":
        self.u32(len(d))
        for k in sorted(d):        # deterministic byte output
            kfn(self, k)
            vfn(self, d[k])
        return self

    def optional(self, v, fn) -> "Encoder":
        self.boolean(v is not None)
        if v is not None:
            fn(self, v)
        return self

    # -- generic tagged value (JSON data model, binary bytes) ---------------
    def value(self, v) -> "Encoder":
        """Tagged encoding of an arbitrary JSON-shaped value: the wire
        meta's replacement for json.dumps.  Deliberately mirrors
        JSON's semantics so the switch is invisible to message
        handlers: dict keys coerce to strings, tuples become lists.
        Raises DencError on types JSON could not carry either.

        The hot path runs in C (native/denc_value.cc, byte-identical
        format); this Python body is the reference implementation and
        the fallback for exact-type mismatches (e.g. int subclasses)
        and toolchain-less environments."""
        fast = _fast()
        if fast is not None:
            try:
                self.buf += fast.encode_value(v)
                return self
            except TypeError:
                pass        # subclass or foreign type: reference path
            except ValueError as e:
                raise DencError(str(e)) from e   # e.g. depth limit
        return self._value_py(v)

    def _value_py(self, v, depth: int = 0) -> "Encoder":
        if depth > 200:
            # same cap as the C codec: hosts with and without the
            # toolchain must agree on what is encodable
            raise DencError("value nesting too deep")
        if v is None:
            self.u8(0)
        elif v is True:
            self.u8(1)
        elif v is False:
            self.u8(2)
        elif isinstance(v, int):
            if -(1 << 63) <= v < (1 << 63):
                self.u8(3)
                self.i64(v)
            else:                        # python bignum: decimal text
                self.u8(9)
                self.string(str(v))
        elif isinstance(v, float):
            self.u8(4)
            self.f64(v)
        elif isinstance(v, str):
            self.u8(5)
            self.string(v)
        elif isinstance(v, (bytes, bytearray, memoryview)):
            self.u8(6)
            self.blob(bytes(v))
        elif isinstance(v, (list, tuple)):
            self.u8(7)
            self.u32(len(v))
            for it in v:
                self._value_py(it, depth + 1)
        elif isinstance(v, dict):
            self.u8(8)
            self.u32(len(v))
            for k, vv in v.items():      # insertion order, like JSON
                if not isinstance(k, str):
                    k = str(k)           # json.dumps key coercion
                self.string(k)
                self._value_py(vv, depth + 1)
        else:
            raise DencError(
                f"unencodable value type {type(v).__name__}")
        return self

    # -- versioned envelope --------------------------------------------------
    def start(self, v: int, compat: int) -> "Encoder":
        """ENCODE_START: version byte, compat byte, length placeholder."""
        self.u8(v).u8(compat)
        self._starts.append(len(self.buf))
        self.u32(0)
        return self

    def finish(self) -> "Encoder":
        """ENCODE_FINISH: patch the length of the innermost envelope."""
        at = self._starts.pop()
        ln = len(self.buf) - at - 4
        self.buf[at:at + 4] = struct.pack("<I", ln)
        return self

    def bytes(self) -> bytes:
        if self._starts:
            raise DencError("unbalanced start/finish")
        return bytes(self.buf)


class Decoder:
    def __init__(self, data: bytes, pos: int = 0) -> None:
        self.data = memoryview(data)
        self.pos = pos
        self._ends: list[int] = []

    def _take(self, n: int) -> memoryview:
        end = self._ends[-1] if self._ends else len(self.data)
        if self.pos + n > end:
            raise DencError(
                f"decode past end ({self.pos}+{n} > {end})")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def u8(self) -> int:
        return self._take(1)[0]

    def u16(self) -> int:
        return struct.unpack("<H", self._take(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self._take(8))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self._take(8))[0]

    def boolean(self) -> bool:
        return self.u8() != 0

    def blob(self) -> bytes:
        return bytes(self._take(self.u32()))

    def string(self) -> str:
        return self.blob().decode("utf-8")

    def list(self, fn) -> list:
        return [fn(self) for _ in range(self.u32())]

    def map(self, kfn, vfn) -> dict:
        return {kfn(self): vfn(self) for _ in range(self.u32())}

    def optional(self, fn):
        return fn(self) if self.boolean() else None

    # -- generic tagged value ------------------------------------------------
    def value(self):
        fast = _fast()
        if fast is not None:
            end = self._ends[-1] if self._ends else len(self.data)
            try:
                obj, pos = fast.decode_value(self.data, self.pos)
            except ValueError as e:
                raise DencError(str(e)) from e
            if pos > end:
                raise DencError("value ran past envelope end")
            self.pos = pos
            return obj
        return self._value_py()

    def _value_py(self, depth: int = 0):
        if depth > 200:
            # parity with the C codec, and a RecursionError from a
            # hostile deep payload would not be a ValueError (the
            # framing-error class the read loop handles)
            raise DencError("value nesting too deep")
        tag = self.u8()
        if tag == 0:
            return None
        if tag == 1:
            return True
        if tag == 2:
            return False
        if tag == 3:
            return self.i64()
        if tag == 4:
            return self.f64()
        if tag == 5:
            return self.string()
        if tag == 6:
            return self.blob()
        if tag == 7:
            return [self._value_py(depth + 1)
                    for _ in range(self.u32())]
        if tag == 8:
            return {self.string(): self._value_py(depth + 1)
                    for _ in range(self.u32())}
        if tag == 9:
            return int(self.string())
        raise DencError(f"bad value tag {tag}")

    # -- versioned envelope --------------------------------------------------
    def start(self, supported: int) -> int:
        """DECODE_START: returns struct_v; raises when the encoder
        declared compat above what this code supports."""
        v = self.u8()
        compat = self.u8()
        ln = self.u32()
        if compat > supported:
            raise IncompatibleVersion(
                f"encoding requires v>={compat}, code supports "
                f"{supported}")
        if ln > self.remaining():
            # an envelope may never claim bytes beyond its parent (or
            # the buffer): a lying length would let reads walk into
            # sibling data instead of failing
            raise DencError(
                f"envelope length {ln} exceeds remaining "
                f"{self.remaining()}")
        self._ends.append(self.pos + ln)
        return v

    def finish(self) -> None:
        """DECODE_FINISH: skip unread tail (newer encoder's fields)."""
        self.pos = self._ends.pop()

    def remaining(self) -> int:
        end = self._ends[-1] if self._ends else len(self.data)
        return end - self.pos
