"""cephx-style ticket auth with rotating service keys.

The round-3 review's finding: a static pre-shared key means a leaked
key is forever.  This is the reference protocol's shape compressed
(src/auth/cephx/): clients prove knowledge of their ENTITY key to the
mon and receive a TICKET -- a service-key-encrypted blob carrying a
fresh session key and an expiry -- plus the session key encrypted
under their own key.  Services never learn entity keys; they validate
tickets with ROTATING service secrets (current + previous generation,
src/auth/RotatingKeyRing.h), so a stolen service key ages out in two
rotations and a stolen ticket dies at its expiry.

AES-GCM does the sealing (the reference uses AES-CBC+hmac; GCM is the
modern equivalent of seal-with-integrity).  Entity keys are the hex
strings the mon's AuthMonitor db already stores.

`cryptography` is an OPTIONAL dependency: when the wheel is absent
(minimal CI images, the TPU pod base image), sealing falls back to a
stdlib AEAD -- a SHA-256 counter-mode keystream with an encrypt-then-
HMAC tag.  Same API, same blob framing (nonce || ciphertext+tag), so
the protocol shape and every failure mode (tamper, wrong key, expiry)
stay testable without the wheel.  It is NOT AES-GCM and makes no
side-channel claims; production deployments install `cryptography`
(`have_aesgcm()` says which path is live).
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import time

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:                    # pragma: no cover - env detail
    AESGCM = None


def have_aesgcm() -> bool:
    """True when the real AES-GCM backend (`cryptography`) is live."""
    return AESGCM is not None


class _StreamAEAD:
    """Stdlib fallback with the AESGCM call shape: encrypt-then-MAC
    over a SHA-256 keystream.  Tag covers nonce, AAD, and ciphertext;
    constant-time compare on open."""

    _TAG = 16

    def __init__(self, key: bytes) -> None:
        self._key = key

    def _keystream(self, nonce: bytes, n: int) -> bytes:
        out = bytearray()
        ctr = 0
        while len(out) < n:
            out += hashlib.sha256(
                self._key + nonce + ctr.to_bytes(8, "big")).digest()
            ctr += 1
        return bytes(out[:n])

    def _tag(self, nonce: bytes, ct: bytes, aad: bytes) -> bytes:
        return hmac.new(self._key, nonce + aad + ct,
                        hashlib.sha256).digest()[:self._TAG]

    def encrypt(self, nonce: bytes, data: bytes,
                aad: bytes | None) -> bytes:
        aad = aad or b""
        ct = bytes(a ^ b for a, b in
                   zip(data, self._keystream(nonce, len(data))))
        return ct + self._tag(nonce, ct, aad)

    def decrypt(self, nonce: bytes, blob: bytes,
                aad: bytes | None) -> bytes:
        aad = aad or b""
        if len(blob) < self._TAG:
            raise ValueError("sealed blob truncated")
        ct, tag = blob[:-self._TAG], blob[-self._TAG:]
        if not hmac.compare_digest(self._tag(nonce, ct, aad), tag):
            raise ValueError("seal authentication failed")
        return bytes(a ^ b for a, b in
                     zip(ct, self._keystream(nonce, len(ct))))


def _aes(key_material: bytes):
    key = hashlib.sha256(key_material).digest()
    if AESGCM is not None:
        return AESGCM(key)
    return _StreamAEAD(key)


def seal(key_material: bytes, obj: dict) -> str:
    nonce = os.urandom(12)
    ct = _aes(key_material).encrypt(nonce,
                                    json.dumps(obj).encode(), b"")
    return (nonce + ct).hex()


def unseal(key_material: bytes, blob_hex: str) -> dict:
    raw = bytes.fromhex(blob_hex)
    out = _aes(key_material).decrypt(raw[:12], raw[12:], b"")
    return json.loads(out)


class CephxError(Exception):
    pass


class RotatingKeys:
    """Two live generations of one service's secret; the older one
    keeps in-flight tickets valid across a rotation."""

    def __init__(self, ttl: float = 3600.0) -> None:
        self.ttl = ttl
        self.gen = 0
        self.keys: dict[int, dict] = {}
        self._rotate(time.time())

    def _rotate(self, now: float) -> None:
        self.gen += 1
        self.keys[self.gen] = {"key": os.urandom(32).hex(),
                               "created": now}
        for g in [g for g in self.keys if g < self.gen - 1]:
            del self.keys[g]

    def rotate_if_due(self, now: float | None = None) -> bool:
        now = time.time() if now is None else now
        if now - self.keys[self.gen]["created"] >= self.ttl:
            self._rotate(now)
            return True
        return False

    def current(self) -> tuple[int, bytes]:
        return self.gen, bytes.fromhex(self.keys[self.gen]["key"])

    def lookup(self, gen: int) -> bytes:
        entry = self.keys.get(gen)
        if entry is None:
            raise CephxError(f"service key generation {gen} retired")
        return bytes.fromhex(entry["key"])

    def to_dict(self) -> dict:
        return {"gen": self.gen,
                "keys": {str(g): dict(e)
                         for g, e in self.keys.items()}}

    @classmethod
    def from_dict(cls, d: dict, ttl: float = 3600.0) -> "RotatingKeys":
        rk = cls.__new__(cls)
        rk.ttl = ttl
        rk.gen = int(d["gen"])
        rk.keys = {int(g): dict(e) for g, e in d["keys"].items()}
        return rk


class CephxAuthority:
    """Mon-side ticket issuer (CephxServiceHandler)."""

    def __init__(self, ttl: float = 3600.0,
                 ticket_ttl: float = 600.0) -> None:
        self.ttl = ttl
        self.ticket_ttl = ticket_ttl
        self.rotating: dict[str, RotatingKeys] = {}

    def service_keys(self, service: str) -> RotatingKeys:
        rk = self.rotating.get(service)
        if rk is None:
            rk = self.rotating[service] = RotatingKeys(self.ttl)
        rk.rotate_if_due()
        return rk

    def verify_entity_proof(self, entity_key_hex: str, nonce: str,
                            proof: str) -> None:
        want = hmac.new(bytes.fromhex(entity_key_hex),
                        bytes.fromhex(nonce),
                        hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, proof):
            raise CephxError("entity proof mismatch")

    def issue_ticket(self, entity: str, entity_key_hex: str,
                     service: str,
                     now: float | None = None) -> dict:
        """Package for the client: the service-sealed ticket (opaque
        to the client) + the session key sealed under the CLIENT's
        entity key."""
        now = time.time() if now is None else now
        rk = self.service_keys(service)
        gen, skey = rk.current()
        session_key = os.urandom(32).hex()
        expires = now + self.ticket_ttl
        ticket = seal(skey, {"entity": entity,
                             "session_key": session_key,
                             "expires": expires, "gen": gen})
        for_client = seal(bytes.fromhex(entity_key_hex),
                          {"session_key": session_key,
                           "expires": expires})
        return {"service": service, "gen": gen, "ticket": ticket,
                "session": for_client, "expires": expires}


async def _auth_rpc(msgr, mon_addr, entity: str, key_hex: str,
                    service: str, req_type: str, reply_type: str,
                    mon_name: str, timeout: float) -> dict:
    """One authenticated mon round trip shared by ticket and rotating-
    key fetches: prove the entity key over a fresh nonce, correlate
    the reply by tid."""
    import asyncio
    from ..msg import Message
    q: asyncio.Queue = asyncio.Queue()
    tid = os.urandom(8).hex()

    async def d(conn, msg):
        if msg.type == reply_type and msg.data.get("tid") == tid:
            await q.put(msg.data)

    msgr.add_dispatcher(d)
    try:
        nonce = os.urandom(16).hex()
        proof = hmac.new(bytes.fromhex(key_hex),
                         bytes.fromhex(nonce),
                         hashlib.sha256).hexdigest()
        await msgr.send(tuple(mon_addr), mon_name,
                        Message(req_type,
                                {"entity": entity, "service": service,
                                 "nonce": nonce, "proof": proof,
                                 "tid": tid}))
        pkg = await asyncio.wait_for(q.get(), timeout)
    finally:
        msgr.dispatchers.remove(d)
    if pkg.get("err"):
        raise CephxError(pkg["err"])
    return pkg


async def fetch_ticket(msgr, mon_addr, entity: str, key_hex: str,
                       service: str, mon_name: str = "mon.0",
                       timeout: float = 10.0) -> dict:
    """Client side (CephxClientHandler): prove the entity key to the
    mon, receive a ticket package, unseal the session key, and install
    the ticket on the messenger so connections to `service` daemons
    authenticate with it instead of the PSK."""
    pkg = await _auth_rpc(msgr, mon_addr, entity, key_hex, service,
                          "auth_get_ticket", "auth_ticket_reply",
                          mon_name, timeout)
    sess = unseal(bytes.fromhex(key_hex), pkg["session"])
    ticket = {"gen": pkg["gen"], "ticket": pkg["ticket"],
              "session_key": sess["session_key"],
              "expires": sess["expires"]}
    msgr.tickets[service] = ticket
    return ticket


async def fetch_rotating(msgr, mon_addr, entity: str, key_hex: str,
                         service: str, mon_name: str = "mon.0",
                         timeout: float = 10.0) -> RotatingKeys:
    """Daemon side: fetch the rotating validation keys for the
    daemon's own service class (sealed under its entity key)."""
    pkg = await _auth_rpc(msgr, mon_addr, entity, key_hex, service,
                          "auth_rotating", "auth_rotating_reply",
                          mon_name, timeout)
    return RotatingKeys.from_dict(unseal(bytes.fromhex(key_hex),
                                         pkg["sealed"]))


def install_validator(msgr, holder: dict) -> None:
    """Install a messenger ticket validator reading the CURRENT keys
    from `holder["rk"]` (a mutable cell, so refreshes take effect
    without re-installing).  Returns {entity, session_key bytes} so
    the handshake can bind the connection's claimed name to the
    ticket's entity (no cross-entity impersonation)."""
    def validator(gen: int, blob_hex: str) -> dict:
        info = validate_ticket(holder["rk"], gen, blob_hex)
        return {"entity": info["entity"],
                "session_key": bytes.fromhex(info["session_key"])}
    msgr.ticket_validator = validator


def validate_ticket(rotating: RotatingKeys, gen: int, ticket_hex: str,
                    now: float | None = None) -> dict:
    """Service side: unseal with the rotating key of that generation;
    reject expired tickets.  Returns {entity, session_key, expires}."""
    now = time.time() if now is None else now
    try:
        ticket = unseal(rotating.lookup(int(gen)), ticket_hex)
    except CephxError:
        raise
    except Exception as e:
        raise CephxError(f"ticket unseal failed: {type(e).__name__}") \
            from e
    if ticket["expires"] < now:
        raise CephxError("ticket expired")
    return ticket
