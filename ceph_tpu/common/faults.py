"""Deterministic cluster-level fault injection for the messenger.

Where common/throttle.py's FaultInjector arms named SITES inside one
process (EIO on a store read, a socket drop mid-send), this module
injects at the MESSAGE level between daemons: drop, delay or duplicate
messages matched by peer name and/or message type, and partition whole
name groups from each other -- the qa/tasks thrasher's network side
(mon_thrash / msgr-failures) in library form.

Determinism is the point: every decision is drawn from ONE seeded RNG
in message-arrival order, so a chaos run that found a bug replays the
same drop/delay schedule from the same seed (fault_injector.h keeps
its injection deterministic for the same reason).  tools/chaos.py
drives clusters with one of these per daemon; tests pin the
schedule-reproducibility in tests/test_fault_injection.py.

Straggler mode: ``straggler()`` arms per-peer HEAVY-TAIL delay
profiles (seeded lognormal / pareto draws from a per-(seed, peer) RNG
stream, so each peer's delay sequence replays independently of
cross-peer message ordering) -- the induced-straggler workload the
hedged-read engine (osd/hedged_gather.py) and ``bench.py
--straggler`` measure against.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field


SEND = "send"
RECV = "recv"
BOTH = "both"

# heavy-tail delay distributions a rule may draw from ("fixed" = the
# classic constant `delay`)
DISTRIBUTIONS = ("fixed", "lognormal", "pareto")


def _match_name(pattern: str | None, name: str) -> bool:
    """None matches everything; "osd." prefix-matches every OSD;
    "osd.3" matches exactly (prefix match would alias osd.30)."""
    if pattern is None:
        return True
    if pattern.endswith("."):
        return name.startswith(pattern)
    return name == pattern


@dataclass
class FaultRule:
    """One armed fault: `action` on messages matching peer/mtype.

    Delay rules may carry a heavy-tail DISTRIBUTION instead of the
    fixed ``delay``: ``dist="lognormal"`` (params ``mu``/``sigma`` of
    the underlying normal) or ``dist="pareto"`` (params ``scale``/
    ``alpha``; alpha <= 1 has infinite mean -- the true straggler
    regime).  ``cap`` bounds any sample so a test's worst case stays
    finite.  Distribution rules draw from a PER-PEER seeded RNG stream
    (see MessageFaultInjector), so each peer's delay sequence is a
    deterministic function of (seed, peer) alone.
    """

    action: str                      # "drop" | "delay" | "dup"
    peer: str | None = None          # peer name or "svc." prefix
    mtype: str | None = None         # message type, None = any
    direction: str = BOTH            # send / recv / both
    probability: float = 1.0
    count: int | None = None         # remaining firings; None = forever
    delay: float = 0.05              # seconds, for "delay" dist=fixed
    dist: str = "fixed"              # fixed | lognormal | pareto
    dist_params: dict = field(default_factory=dict)
    fired: int = 0

    def __post_init__(self) -> None:
        if self.dist not in DISTRIBUTIONS:
            raise ValueError(f"unknown delay distribution {self.dist!r}")

    def matches(self, direction: str, peer: str, mtype: str) -> bool:
        if self.count is not None and self.count <= 0:
            return False
        if self.direction != BOTH and self.direction != direction:
            return False
        return _match_name(self.peer, peer) and (
            self.mtype is None or self.mtype == mtype)

    def sample_delay(self, rng: random.Random) -> float:
        """One delay draw (seconds) from this rule's distribution."""
        p = self.dist_params
        if self.dist == "lognormal":
            v = rng.lognormvariate(
                p.get("mu", math.log(max(self.delay, 1e-9))),
                p.get("sigma", 1.0))
        elif self.dist == "pareto":
            v = p.get("scale", self.delay) * rng.paretovariate(
                p.get("alpha", 1.5))
        else:
            return self.delay
        cap = p.get("cap")
        return min(v, cap) if cap is not None else v


@dataclass
class FaultDecision:
    drop: bool = False
    delay: float = 0.0
    copies: int = 1                  # >1 = duplicate delivery


class MessageFaultInjector:
    """Seeded, rule-driven message mangling for one endpoint.

    One instance is threaded into a Messenger (and from there consulted
    on every app-level send and every delivered message).  All
    endpoints of a test cluster may share one instance -- decisions
    stay deterministic because the event loop serializes the calls.
    """

    def __init__(self, seed: int = 0, perf=None) -> None:
        self.seed = seed
        self._rng = random.Random(seed)
        self.rules: list[FaultRule] = []
        # symmetric partitions: (group_a, group_b) name patterns
        self.partitions: list[tuple[str, str]] = []
        self.stats: dict[str, int] = {}
        self.perf = perf             # optional PerfCounters sink
        # per-peer RNG streams for distribution-backed delay rules:
        # each peer's delay sequence is seeded by (seed, peer) ALONE,
        # so reordering traffic across peers -- or adding an unrelated
        # straggler profile -- cannot shift another peer's schedule
        self._peer_rngs: dict[str, random.Random] = {}

    # -- arming --------------------------------------------------------------
    def add_rule(self, rule: FaultRule) -> FaultRule:
        self.rules.append(rule)
        return rule

    def drop(self, *, peer: str | None = None, mtype: str | None = None,
             direction: str = BOTH, probability: float = 1.0,
             count: int | None = None) -> FaultRule:
        return self.add_rule(FaultRule("drop", peer, mtype, direction,
                                       probability, count))

    def delay(self, seconds: float, *, peer: str | None = None,
              mtype: str | None = None, direction: str = BOTH,
              probability: float = 1.0,
              count: int | None = None) -> FaultRule:
        return self.add_rule(FaultRule("delay", peer, mtype, direction,
                                       probability, count,
                                       delay=seconds))

    def straggler(self, peer: str, *, dist: str = "lognormal",
                  mtype: str | None = None, direction: str = RECV,
                  probability: float = 1.0, count: int | None = None,
                  **params) -> FaultRule:
        """Arm a heavy-tail per-peer straggler profile.

        ``dist="lognormal"`` takes mu/sigma (seconds of the underlying
        normal's exp); ``dist="pareto"`` takes scale/alpha; both honor
        ``cap``.  Defaults to RECV so the delay lands in the receiver's
        dispatch task (a SEND delay would serialize the whole
        connection behind the sleep and stall unrelated traffic --
        stragglers are slow, not head-of-line-blocking).  Same seed ->
        same per-peer delay sequence: the draw comes from the peer's
        own RNG stream, so a chaos run's straggler schedule replays
        exactly."""
        return self.add_rule(FaultRule(
            "delay", peer, mtype, direction, probability, count,
            dist=dist, dist_params=dict(params)))

    def duplicate(self, *, peer: str | None = None,
                  mtype: str | None = None, direction: str = BOTH,
                  probability: float = 1.0,
                  count: int | None = None) -> FaultRule:
        return self.add_rule(FaultRule("dup", peer, mtype, direction,
                                       probability, count))

    def partition(self, a: str, b: str) -> None:
        """Drop EVERYTHING between name groups a and b (both
        directions; "osd." partitions every OSD from b)."""
        self.partitions.append((a, b))

    def heal(self, a: str | None = None, b: str | None = None) -> None:
        """Remove partitions (all of them when called bare)."""
        if a is None:
            self.partitions.clear()
        else:
            self.partitions = [p for p in self.partitions
                               if p != (a, b) and p != (b, a)]

    def clear(self) -> None:
        self.rules.clear()
        self.partitions.clear()

    # -- the decision point --------------------------------------------------
    def _count(self, key: str) -> None:
        self.stats[key] = self.stats.get(key, 0) + 1
        if self.perf is not None:
            self.perf.inc(key)

    def _partitioned(self, local: str, peer: str) -> bool:
        for a, b in self.partitions:
            if (_match_name(a, local) and _match_name(b, peer)) or \
                    (_match_name(b, local) and _match_name(a, peer)):
                return True
        return False

    def _peer_rng(self, peer: str) -> random.Random:
        rng = self._peer_rngs.get(peer)
        if rng is None:
            rng = self._peer_rngs[peer] = random.Random(
                f"{self.seed}:straggler:{peer}")
        return rng

    def decide(self, direction: str, local: str, peer: str,
               mtype: str) -> FaultDecision:
        """One deterministic decision for one message traversal."""
        if self._partitioned(local, peer):
            self._count("partition_dropped")
            return FaultDecision(drop=True)
        out = FaultDecision()
        for rule in self.rules:
            if not rule.matches(direction, peer, mtype):
                continue
            # the RNG is consumed ONLY for matching rules with p < 1 so
            # unrelated traffic cannot shift the schedule of the flow
            # under test; distribution-backed rules draw EVERYTHING
            # (probability and delay) from the peer's own stream so the
            # per-peer sequence is independent of cross-peer ordering
            dist_rule = (rule.action == "delay"
                         and rule.dist != "fixed")
            draw = self._peer_rng(peer) if dist_rule else self._rng
            if rule.probability < 1.0 and \
                    draw.random() >= rule.probability:
                continue
            rule.fired += 1
            if rule.count is not None:
                rule.count -= 1
            if rule.action == "drop":
                self._count("dropped")
                out.drop = True
                return out
            if rule.action == "delay":
                self._count("delayed")
                if dist_rule:
                    self._count("straggler_delays")
                    out.delay += rule.sample_delay(
                        self._peer_rng(peer))
                else:
                    out.delay += rule.delay
            elif rule.action == "dup":
                self._count("duplicated")
                out.copies += 1
        return out

    def on_send(self, local: str, peer: str,
                mtype: str) -> FaultDecision:
        return self.decide(SEND, local, peer, mtype)

    def on_recv(self, local: str, peer: str,
                mtype: str) -> FaultDecision:
        return self.decide(RECV, local, peer, mtype)
