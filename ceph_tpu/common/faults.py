"""Deterministic cluster-level fault injection for the messenger.

Where common/throttle.py's FaultInjector arms named SITES inside one
process (EIO on a store read, a socket drop mid-send), this module
injects at the MESSAGE level between daemons: drop, delay or duplicate
messages matched by peer name and/or message type, and partition whole
name groups from each other -- the qa/tasks thrasher's network side
(mon_thrash / msgr-failures) in library form.

Determinism is the point: every decision is drawn from ONE seeded RNG
in message-arrival order, so a chaos run that found a bug replays the
same drop/delay schedule from the same seed (fault_injector.h keeps
its injection deterministic for the same reason).  tools/chaos.py
drives clusters with one of these per daemon; tests pin the
schedule-reproducibility in tests/test_fault_injection.py.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field


SEND = "send"
RECV = "recv"
BOTH = "both"


def _match_name(pattern: str | None, name: str) -> bool:
    """None matches everything; "osd." prefix-matches every OSD;
    "osd.3" matches exactly (prefix match would alias osd.30)."""
    if pattern is None:
        return True
    if pattern.endswith("."):
        return name.startswith(pattern)
    return name == pattern


@dataclass
class FaultRule:
    """One armed fault: `action` on messages matching peer/mtype."""

    action: str                      # "drop" | "delay" | "dup"
    peer: str | None = None          # peer name or "svc." prefix
    mtype: str | None = None         # message type, None = any
    direction: str = BOTH            # send / recv / both
    probability: float = 1.0
    count: int | None = None         # remaining firings; None = forever
    delay: float = 0.05              # seconds, for "delay"
    fired: int = 0

    def matches(self, direction: str, peer: str, mtype: str) -> bool:
        if self.count is not None and self.count <= 0:
            return False
        if self.direction != BOTH and self.direction != direction:
            return False
        return _match_name(self.peer, peer) and (
            self.mtype is None or self.mtype == mtype)


@dataclass
class FaultDecision:
    drop: bool = False
    delay: float = 0.0
    copies: int = 1                  # >1 = duplicate delivery


class MessageFaultInjector:
    """Seeded, rule-driven message mangling for one endpoint.

    One instance is threaded into a Messenger (and from there consulted
    on every app-level send and every delivered message).  All
    endpoints of a test cluster may share one instance -- decisions
    stay deterministic because the event loop serializes the calls.
    """

    def __init__(self, seed: int = 0, perf=None) -> None:
        self.seed = seed
        self._rng = random.Random(seed)
        self.rules: list[FaultRule] = []
        # symmetric partitions: (group_a, group_b) name patterns
        self.partitions: list[tuple[str, str]] = []
        self.stats: dict[str, int] = {}
        self.perf = perf             # optional PerfCounters sink

    # -- arming --------------------------------------------------------------
    def add_rule(self, rule: FaultRule) -> FaultRule:
        self.rules.append(rule)
        return rule

    def drop(self, *, peer: str | None = None, mtype: str | None = None,
             direction: str = BOTH, probability: float = 1.0,
             count: int | None = None) -> FaultRule:
        return self.add_rule(FaultRule("drop", peer, mtype, direction,
                                       probability, count))

    def delay(self, seconds: float, *, peer: str | None = None,
              mtype: str | None = None, direction: str = BOTH,
              probability: float = 1.0,
              count: int | None = None) -> FaultRule:
        return self.add_rule(FaultRule("delay", peer, mtype, direction,
                                       probability, count,
                                       delay=seconds))

    def duplicate(self, *, peer: str | None = None,
                  mtype: str | None = None, direction: str = BOTH,
                  probability: float = 1.0,
                  count: int | None = None) -> FaultRule:
        return self.add_rule(FaultRule("dup", peer, mtype, direction,
                                       probability, count))

    def partition(self, a: str, b: str) -> None:
        """Drop EVERYTHING between name groups a and b (both
        directions; "osd." partitions every OSD from b)."""
        self.partitions.append((a, b))

    def heal(self, a: str | None = None, b: str | None = None) -> None:
        """Remove partitions (all of them when called bare)."""
        if a is None:
            self.partitions.clear()
        else:
            self.partitions = [p for p in self.partitions
                               if p != (a, b) and p != (b, a)]

    def clear(self) -> None:
        self.rules.clear()
        self.partitions.clear()

    # -- the decision point --------------------------------------------------
    def _count(self, key: str) -> None:
        self.stats[key] = self.stats.get(key, 0) + 1
        if self.perf is not None:
            self.perf.inc(key)

    def _partitioned(self, local: str, peer: str) -> bool:
        for a, b in self.partitions:
            if (_match_name(a, local) and _match_name(b, peer)) or \
                    (_match_name(b, local) and _match_name(a, peer)):
                return True
        return False

    def decide(self, direction: str, local: str, peer: str,
               mtype: str) -> FaultDecision:
        """One deterministic decision for one message traversal."""
        if self._partitioned(local, peer):
            self._count("partition_dropped")
            return FaultDecision(drop=True)
        out = FaultDecision()
        for rule in self.rules:
            if not rule.matches(direction, peer, mtype):
                continue
            # the RNG is consumed ONLY for matching rules with p < 1 so
            # unrelated traffic cannot shift the schedule of the flow
            # under test
            if rule.probability < 1.0 and \
                    self._rng.random() >= rule.probability:
                continue
            rule.fired += 1
            if rule.count is not None:
                rule.count -= 1
            if rule.action == "drop":
                self._count("dropped")
                out.drop = True
                return out
            if rule.action == "delay":
                self._count("delayed")
                out.delay += rule.delay
            elif rule.action == "dup":
                self._count("duplicated")
                out.copies += 1
        return out

    def on_send(self, local: str, peer: str,
                mtype: str) -> FaultDecision:
        return self.decide(SEND, local, peer, mtype)

    def on_recv(self, local: str, peer: str,
                mtype: str) -> FaultDecision:
        return self.decide(RECV, local, peer, mtype)
