"""Typed configuration registry with change observers.

The src/common/options + ConfigProxy analog: options are declared in a
typed schema (name/type/level/default/min/max/enum/desc — the shape of
src/common/options/*.yaml.in), values layer defaults < file < env <
runtime overrides, and observers get notified on runtime changes
(md_config_obs_t, src/common/config_proxy.h:15-180).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Callable

OPT_INT = "int"
OPT_FLOAT = "float"
OPT_STR = "str"
OPT_BOOL = "bool"

LEVEL_BASIC = "basic"
LEVEL_ADVANCED = "advanced"
LEVEL_DEV = "dev"

_CASTERS = {
    OPT_INT: int,
    OPT_FLOAT: float,
    OPT_STR: str,
    OPT_BOOL: lambda v: (v if isinstance(v, bool)
                         else str(v).lower() in ("1", "true", "yes", "on")),
}


@dataclass
class Option:
    name: str
    type: str
    default: Any
    desc: str = ""
    level: str = LEVEL_ADVANCED
    min: float | None = None
    max: float | None = None
    enum_values: list[str] = field(default_factory=list)

    def cast(self, value: Any) -> Any:
        try:
            v = _CASTERS[self.type](value)
        except (TypeError, ValueError):
            raise ValueError(
                f"{self.name}={value!r} is not a valid {self.type}")
        if self.min is not None and v < self.min:
            raise ValueError(f"{self.name}={v} below min {self.min}")
        if self.max is not None and v > self.max:
            raise ValueError(f"{self.name}={v} above max {self.max}")
        if self.enum_values and v not in self.enum_values:
            raise ValueError(
                f"{self.name}={v!r} not in {self.enum_values}")
        return v


# the schema the daemons share (subset of the reference's option set,
# same names where the concept carries over)
DEFAULT_SCHEMA: list[Option] = [
    Option("osd_heartbeat_interval", OPT_FLOAT, 0.5,
           "seconds between peer pings", min=0.01),
    Option("osd_heartbeat_grace", OPT_FLOAT, 4.0,
           "seconds of silence before reporting a peer down", min=0.1),
    Option("osd_pool_default_size", OPT_INT, 3,
           "replica count for new pools", min=1),
    Option("osd_pool_default_min_size", OPT_INT, 2,
           "min replicas to accept writes", min=1),
    Option("osd_pool_default_pg_num", OPT_INT, 32,
           "pg count for new pools", min=1),
    Option("osd_recovery_max_active", OPT_INT, 3,
           "max concurrent recovery ops per OSD", min=1),
    Option("osd_client_op_priority", OPT_INT, 63, "client op priority"),
    Option("osd_scrub_interval", OPT_FLOAT, 60.0,
           "seconds between periodic scrubs", min=0.0),
    Option("mon_osd_min_down_reporters", OPT_INT, 2,
           "distinct reporters before marking an osd down", min=1),
    Option("mon_osd_down_out_interval", OPT_FLOAT, 600.0,
           "seconds down before auto-out", min=0.0),
    Option("mon_lease", OPT_FLOAT, 5.0, "paxos leader lease seconds"),
    Option("osd_erasure_code_plugins", OPT_STR, "tpu isa jerasure",
           "plugins preloaded at daemon start"),
    Option("osd_pool_default_erasure_code_profile", OPT_STR,
           "plugin=tpu k=2 m=1 technique=reed_sol_van",
           "default EC profile"),
    Option("osd_peering_retry_base", OPT_FLOAT, 0.5,
           "initial peering retry delay (doubles per attempt)",
           min=0.01),
    Option("osd_peering_retry_max", OPT_FLOAT, 8.0,
           "peering retry backoff ceiling in seconds", min=0.01),
    Option("osd_peering_retry_jitter", OPT_FLOAT, 0.25,
           "fraction of the delay randomized to de-synchronize "
           "retrying primaries", min=0.0, max=1.0),
    Option("osd_wait_acting_change_timeout", OPT_FLOAT, 10.0,
           "seconds to hold peering for a requested pg_temp override "
           "before serving the interval ourselves", min=0.1),
    Option("osd_ec_read_timeout", OPT_FLOAT, 5.0,
           "per-attempt deadline for an EC shard fetch fanout",
           min=0.1),
    Option("osd_ec_read_retries", OPT_INT, 3,
           "extra rounds a degraded shard gather may retry failed "
           "sources before erroring the read", min=0),
    Option("osd_ec_read_backoff", OPT_FLOAT, 0.25,
           "base backoff between shard-gather retry rounds", min=0.0),
    Option("osd_ec_hedge_enabled", OPT_BOOL, True,
           "straggler-tolerant EC gathers: request extra shards after "
           "the adaptive per-peer latency quantile and decode from "
           "the first sufficient set (osd/hedged_gather.py)"),
    Option("osd_ec_hedge_quantile", OPT_FLOAT, 0.9,
           "latency quantile of the candidate-peer cohort the hedge "
           "timer arms on", min=0.5, max=0.999),
    Option("osd_ec_hedge_delay_min", OPT_FLOAT, 0.002,
           "hedge delay floor in seconds (never hedge faster than "
           "this, however fast the cohort looks)", min=0.0),
    Option("osd_ec_hedge_delay_max", OPT_FLOAT, 1.0,
           "hedge delay ceiling in seconds; also the conservative "
           "delay while the peer EWMAs are cold", min=0.001),
    Option("osd_ec_hedge_max_extra", OPT_INT, 2,
           "max extra shards (h) one hedge fire may request", min=0),
    Option("osd_ec_hedge_min_samples", OPT_INT, 8,
           "sub-read samples before a peer's EWMA quantile estimate "
           "is trusted by the hedge timer", min=1),
    Option("osd_ec_hedge_ewma_alpha", OPT_FLOAT, 0.2,
           "EWMA smoothing factor for per-peer sub-read latency",
           min=0.001, max=1.0),
    Option("osd_max_backfills", OPT_INT, 2,
           "concurrent backfill reservations per OSD (local+remote)",
           min=1),
    Option("osd_max_scrubs", OPT_INT, 1,
           "concurrent scrub slots per OSD", min=1),
    Option("osd_client_message_size_cap", OPT_INT, 500 << 20,
           "in-flight client payload bytes before backpressure",
           min=1),
    Option("osd_op_complaint_time", OPT_FLOAT, 30.0,
           "seconds in flight before an op is complained about",
           min=0.1),
    Option("osd_scrub_auto_repair", OPT_BOOL, True,
           "repair scrub-detected inconsistencies automatically"),
    Option("osd_ec_batch_enabled", OPT_BOOL, True,
           "coalesce EC codec work across PGs into shared launches"),
    Option("osd_ec_batch_max", OPT_INT, 64,
           "max stripes per coalesced codec launch", min=1),
    Option("osd_ec_batch_timeout", OPT_FLOAT, 0.002,
           "seconds a partial codec batch waits before flushing",
           min=0.0),
    Option("osd_ec_batch_eager_flush", OPT_BOOL, True,
           "flush the codec batch when the event loop goes idle"),
    Option("osd_ec_mesh_enabled", OPT_BOOL, True,
           "launch coalesced EC batches through the sharded device "
           "mesh (stripe axis partitioned over all visible chips; "
           "single-device is a 1-device mesh on the same code path)"),
    Option("osd_ec_mesh_devices", OPT_INT, 0,
           "devices in the codec mesh (0 = all visible)", min=0),
    Option("osd_ec_mesh_donate", OPT_BOOL, True,
           "donate stripe buffers to mesh launches (consume the "
           "device copy in place instead of defensive-copying it)"),
    Option("osd_datapath_cache_enabled", OPT_BOOL, True,
           "keep hot shard buffers device-resident across encode -> "
           "commit -> read-verify -> scrub -> decode (the (object, "
           "shard) cache in os/device_cache.py)"),
    Option("osd_datapath_cache_bytes", OPT_INT, 64 << 20,
           "byte budget of the device-resident shard cache (LRU past "
           "it)", min=0),
    Option("osd_datapath_cache_entry_max", OPT_INT, 8 << 20,
           "largest single shard buffer the cache will hold (bigger "
           "shards always read through the store)", min=0),
    Option("osd_ec_repair_fragments_enabled", OPT_BOOL, True,
           "regenerating-code repair fragments: rebuild a lost shard "
           "from d beta-sized computed sub-chunks (one per helper) "
           "instead of k full chunks when the pool's codec supports "
           "it (the pmsr plugin); any fragment failure falls back to "
           "the full shard gather"),
    Option("osd_ec_rmw_delta_enabled", OPT_BOOL, True,
           "partial-stripe writes delta-update parity in place "
           "(parity' = parity XOR encode(delta)) instead of "
           "re-encoding whole stripes; unchanged data shards ship "
           "version-stamp-only sub-writes"),
    Option("osd_pipeline_enabled", OPT_BOOL, True,
           "pipeline the OSD write hot path: double-buffered codec "
           "launches, commits/flushes awaited outside the PG lock "
           "(per-(PG, object) ordering preserved), per-peer sub-op "
           "coalescing.  The kill switch: false restores the serial "
           "gather -> encode -> commit -> fan-out chain end to end"),
    Option("osd_pipeline_staging_depth", OPT_INT, 4,
           "marshaled codec batches parked between staging and "
           "launch; a flush finding the queue full launches inline "
           "(a counted stall), so this bounds parked host memory",
           min=1),
    Option("osd_pipeline_flush_window", OPT_FLOAT, 0.002,
           "seconds the per-peer sub-op coalescer waits for "
           "co-submitters before shipping one framed flush per peer "
           "(drains early when the event loop goes idle)", min=0.0),
    Option("osd_heartbeat_max_peers", OPT_INT, 10,
           "heartbeat fanout cap: PG peers + id-ring neighbors "
           "instead of the O(N^2) full mesh (0 = uncapped)", min=0),
    Option("mon_up_thru_batch_window", OPT_FLOAT, 0.05,
           "seconds the leader coalesces up_thru bumps before "
           "committing them as one epoch (per-PG epoch storms on "
           "pool create otherwise)", min=0.0),
    Option("auth_service_ticket_ttl", OPT_FLOAT, 3600.0,
           "cephx service ticket lifetime seconds", min=1.0),
    Option("auth_ticket_ttl", OPT_FLOAT, 600.0,
           "cephx auth ticket lifetime seconds", min=1.0),
    Option("prometheus_port", OPT_INT, 0,
           "mgr prometheus exporter port (0 = ephemeral)", min=0),
    Option("dashboard_enabled", OPT_BOOL, True,
           "serve the mgr dashboard"),
    Option("dashboard_port", OPT_INT, 0,
           "mgr dashboard port (0 = ephemeral)", min=0),
    Option("telemetry_on", OPT_BOOL, False,
           "enable the mgr telemetry module"),
    # -- loadgen (the cluster traffic harness, ceph_tpu/loadgen) ----------
    Option("loadgen_rados_handles", OPT_INT, 8,
           "Rados connections the client swarm multiplexes over",
           min=1),
    Option("loadgen_op_timeout", OPT_FLOAT, 30.0,
           "per-op client deadline; exceeding it is a wedged op",
           min=0.1),
    Option("loadgen_open_max_inflight", OPT_INT, 1024,
           "open-loop safety valve: max ops in flight before the "
           "dispatcher stalls (stalls are reported, not hidden)",
           min=1),
    Option("loadgen_preload_concurrency", OPT_INT, 64,
           "concurrent writes while preloading the working set",
           min=1),
    Option("loadgen_kill_osds", OPT_INT, 1,
           "OSDs killed by the recovery-interference phase", min=0),
    Option("loadgen_recovery_settle", OPT_FLOAT, 15.0,
           "seconds allowed for the mon to mark the victim down",
           min=0.1),
    Option("loadgen_hist_growth", OPT_FLOAT, 2 ** 0.125,
           "latency histogram bucket growth factor (bounds the "
           "relative error of reported percentiles)", min=1.0001),
    Option("loadgen_hist_min_s", OPT_FLOAT, 1e-5,
           "latency histogram first bucket upper bound (seconds)",
           min=1e-9),
    Option("debug_osd", OPT_INT, 1, "osd log verbosity", min=0, max=20,
           level=LEVEL_DEV),
    Option("debug_mon", OPT_INT, 1, "mon log verbosity", min=0, max=20,
           level=LEVEL_DEV),
    Option("debug_ms", OPT_INT, 0, "messenger log verbosity", min=0,
           max=20, level=LEVEL_DEV),
    Option("log_max_recent", OPT_INT, 1000,
           "ring-buffered log entries kept for crash dump", min=0),
]


class ConfigProxy:
    """Layered typed config: defaults < file < env < runtime set()."""

    ENV_PREFIX = "CEPH_TPU_"

    def __init__(self, schema: list[Option] | None = None,
                 conf_file: str | None = None,
                 values: dict | None = None,
                 read_env: bool = True) -> None:
        self.schema: dict[str, Option] = {
            o.name: o for o in (schema or DEFAULT_SCHEMA)}
        self._values: dict[str, Any] = {}
        self._observers: dict[str, list[Callable[[str, Any], None]]] = {}
        if conf_file and os.path.exists(conf_file):
            self._load_file(conf_file)
        if read_env:
            self._load_env()
        for k, v in (values or {}).items():
            self.set(k, v, notify=False)

    def _load_file(self, path: str) -> None:
        with open(path) as f:
            data = json.load(f)
        for k, v in data.items():
            if k in self.schema:
                self._values[k] = self.schema[k].cast(v)

    def _load_env(self) -> None:
        for name, opt in self.schema.items():
            env = os.environ.get(self.ENV_PREFIX + name.upper())
            if env is not None:
                self._values[name] = opt.cast(env)

    # -- access -------------------------------------------------------------
    def get(self, name: str) -> Any:
        opt = self.schema.get(name)
        if opt is None:
            raise KeyError(f"unknown option {name}")
        return self._values.get(name, opt.default)

    def __getitem__(self, name: str) -> Any:
        return self.get(name)

    def set(self, name: str, value: Any, notify: bool = True) -> None:
        opt = self.schema.get(name)
        if opt is None:
            raise KeyError(f"unknown option {name}")
        v = opt.cast(value)
        self._values[name] = v
        if notify:
            for cb in self._observers.get(name, []):
                cb(name, v)

    def add_observer(self, name: str,
                     cb: Callable[[str, Any], None]) -> None:
        if name not in self.schema:
            raise KeyError(f"unknown option {name}")
        self._observers.setdefault(name, []).append(cb)

    # -- introspection (`ceph config help/show` analog) ---------------------
    def show(self) -> dict[str, Any]:
        return {name: self.get(name) for name in sorted(self.schema)}

    def describe(self, name: str) -> dict:
        o = self.schema[name]
        return {"name": o.name, "type": o.type, "level": o.level,
                "default": o.default, "desc": o.desc, "min": o.min,
                "max": o.max, "enum_values": o.enum_values,
                "current": self.get(name)}
