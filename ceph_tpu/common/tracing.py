"""Cross-daemon trace spans (src/common/tracer.h:10-27 role).

A trace id is minted at the CLIENT when an op is submitted; every hop
-- client -> primary OSD -> replica OSDs -> object store -- opens a
child span carrying (trace_id, parent span id) and records its own
timing.  Span contexts ride the wire inside message data ("trace"
field on osd_op / rep_op), and within a daemon they propagate through
the asyncio task via a ContextVar, so deep call chains (pg -> backend
-> store) pick up their parent without threading arguments.

Each daemon keeps its finished spans in a bounded ring, dumpable via
the admin socket ("dump_tracing"); assembling the rings from every
daemon yields the full hop tree for any op (the tracepoint + jaeger
span story, compressed to what this framework can verify in-process).
"""

from __future__ import annotations

import contextvars
import os
import time
from collections import deque

# the active span of THIS asyncio task (or sync call chain under it)
current_span: contextvars.ContextVar = contextvars.ContextVar(
    "ceph_tpu_span", default=None)

RING = 2048


class Span:
    __slots__ = ("trace_id", "span_id", "parent_id", "name", "daemon",
                 "start", "end", "tags", "_tracer", "_token")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: str | None, tags: dict) -> None:
        self._tracer = tracer
        self.name = name
        self.daemon = tracer.daemon
        self.trace_id = trace_id
        self.span_id = os.urandom(4).hex()
        self.parent_id = parent_id
        self.tags = tags
        self.start = time.time()
        self.end: float | None = None
        self._token = None

    def ctx(self) -> dict:
        """The wire context a child hop embeds in its message."""
        return {"id": self.trace_id, "parent": self.span_id}

    def finish(self) -> None:
        if self.end is None:
            self.end = time.time()
            self._tracer._done(self)
        if self._token is not None:
            current_span.reset(self._token)
            self._token = None

    def activate(self) -> "Span":
        """Make this the task's current span (children attach to it)."""
        self._token = current_span.set(self)
        return self

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "daemon": self.daemon, "start": self.start,
                "end": self.end,
                "duration_ms": None if self.end is None
                else round((self.end - self.start) * 1000, 3),
                "tags": self.tags}


class Tracer:
    def __init__(self, daemon: str) -> None:
        self.daemon = daemon
        self.finished: deque[Span] = deque(maxlen=RING)

    def start(self, name: str, parent: dict | None = None,
              **tags) -> Span:
        """Open a span.  ``parent`` is a wire context ({"id",
        "parent"}) from an incoming message; absent that, the task's
        current span is the parent; absent both, this is a ROOT span
        with a fresh trace id."""
        if parent and parent.get("id"):
            return Span(self, name, parent["id"],
                        parent.get("parent"), tags)
        cur = current_span.get()
        if cur is not None:
            return Span(self, name, cur.trace_id, cur.span_id, tags)
        return Span(self, name, os.urandom(8).hex(), None, tags)

    def _done(self, span: Span) -> None:
        self.finished.append(span)

    def dump(self, trace_id: str | None = None) -> list[dict]:
        return [s.to_dict() for s in self.finished
                if trace_id is None or s.trace_id == trace_id]


# per-process registry (daemon name -> tracer): tests and admin
# sockets look tracers up here
_TRACERS: dict[str, Tracer] = {}


def get_tracer(daemon: str) -> Tracer:
    t = _TRACERS.get(daemon)
    if t is None:
        t = _TRACERS[daemon] = Tracer(daemon)
    return t


def all_spans(trace_id: str) -> list[dict]:
    """Every span of a trace across every tracer IN THIS PROCESS
    (tests run whole clusters in-process; multi-process deployments
    dump per-daemon over the admin socket instead)."""
    out = []
    for t in _TRACERS.values():
        out.extend(t.dump(trace_id))
    return sorted(out, key=lambda s: s["start"])
