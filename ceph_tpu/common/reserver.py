"""AsyncReserver: bounded, priority-ordered reservation slots.

The analog of src/common/AsyncReserver.h: recovery/backfill work must
take a slot before moving data so a recovering cluster cannot saturate
every OSD at once (the slot count is the `osd_max_backfills` knob).
Local and remote reservations use the same primitive -- the remote side
simply services requests arriving as messages.
"""

from __future__ import annotations

import asyncio
import heapq


class AsyncReserver:
    def __init__(self, max_allowed: int = 1) -> None:
        self.max_allowed = max_allowed
        self.granted: set = set()
        self._queue: list[tuple[int, int, object, asyncio.Future]] = []
        self._seq = 0
        self._leases: dict = {}     # item -> monotonic expiry

    def _do_grants(self) -> None:
        while self._queue and len(self.granted) < self.max_allowed:
            _, _, item, fut = heapq.heappop(self._queue)
            if fut.done():          # cancelled while queued
                continue
            self.granted.add(item)
            fut.set_result(True)

    async def request(self, item, prio: int = 0,
                      timeout: float | None = None) -> None:
        """Wait for a slot.  Re-requesting a granted item is a no-op."""
        self._purge_leases()    # a crashed remote holder's expired
        if item in self.granted:  # lease must not starve local waiters
            return
        fut = asyncio.get_event_loop().create_future()
        heapq.heappush(self._queue, (-prio, self._seq, item, fut))
        self._seq += 1
        self._do_grants()
        try:
            await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            self.cancel(item)
            raise

    def get_or_fail(self, item, lease: float | None = None) -> bool:
        """Immediate grant or False -- never queues (the remote-
        reservation pattern: a busy peer answers 'rejected' and the
        requester retries later rather than parking a slot).

        ``lease`` bounds the grant's lifetime: a remote holder that
        crashes (or whose release message is lost) must not leak the
        slot forever -- with one slot that would wedge the feature
        until restart.  Expired leases are purged lazily."""
        import time
        self._purge_leases()
        if item in self.granted:
            if lease is not None:
                self._leases[item] = time.monotonic() + lease
            return True
        if len(self.granted) >= self.max_allowed:
            return False
        self.granted.add(item)
        if lease is not None:
            self._leases[item] = time.monotonic() + lease
        return True

    def _purge_leases(self) -> None:
        import time
        if not self._leases:
            return
        now = time.monotonic()
        for item, expires in list(self._leases.items()):
            if now >= expires:
                del self._leases[item]
                self.granted.discard(item)
        self._do_grants()

    def release(self, item) -> None:
        self.granted.discard(item)
        self._leases.pop(item, None)
        self._do_grants()

    def cancel(self, item) -> None:
        """Drop a queued (or granted) reservation."""
        for entry in self._queue:
            if entry[2] == item and not entry[3].done():
                entry[3].cancel()
        self._queue = [e for e in self._queue if not e[3].done()]
        heapq.heapify(self._queue)
        self.release(item)
