"""AsyncReserver: bounded, priority-ordered reservation slots.

The analog of src/common/AsyncReserver.h: recovery/backfill work must
take a slot before moving data so a recovering cluster cannot saturate
every OSD at once (the slot count is the `osd_max_backfills` knob).
Local and remote reservations use the same primitive -- the remote side
simply services requests arriving as messages.
"""

from __future__ import annotations

import asyncio
import heapq


class AsyncReserver:
    def __init__(self, max_allowed: int = 1) -> None:
        self.max_allowed = max_allowed
        self.granted: set = set()
        self._queue: list[tuple[int, int, object, asyncio.Future]] = []
        self._seq = 0

    def _do_grants(self) -> None:
        while self._queue and len(self.granted) < self.max_allowed:
            _, _, item, fut = heapq.heappop(self._queue)
            if fut.done():          # cancelled while queued
                continue
            self.granted.add(item)
            fut.set_result(True)

    async def request(self, item, prio: int = 0,
                      timeout: float | None = None) -> None:
        """Wait for a slot.  Re-requesting a granted item is a no-op."""
        if item in self.granted:
            return
        fut = asyncio.get_event_loop().create_future()
        heapq.heappush(self._queue, (-prio, self._seq, item, fut))
        self._seq += 1
        self._do_grants()
        try:
            await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            self.cancel(item)
            raise

    def release(self, item) -> None:
        self.granted.discard(item)
        self._do_grants()

    def cancel(self, item) -> None:
        """Drop a queued (or granted) reservation."""
        for entry in self._queue:
            if entry[2] == item and not entry[3].done():
                entry[3].cancel()
        self._queue = [e for e in self._queue if not e[3].done()]
        heapq.heapify(self._queue)
        self.release(item)
