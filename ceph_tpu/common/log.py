"""dout-style logging: per-subsystem levels, ring buffer, crash dump.

src/log/Log.cc + SubsystemMap analog: every entry is kept in a bounded
ring regardless of level; entries at or below the subsystem's level
also go to the sink immediately.  On a crash the recent ring is dumped
— the low-overhead always-on flight recorder the reference relies on.
"""

from __future__ import annotations

import sys
import time
import threading
from collections import deque


class Logger:
    def __init__(self, max_recent: int = 1000,
                 sink=None) -> None:
        self._levels: dict[str, int] = {}
        self.default_level = 1
        self._recent: deque[tuple[float, str, int, str]] = deque(
            maxlen=max_recent)
        self._lock = threading.Lock()
        self._sink = sink or sys.stderr

    def set_level(self, subsys: str, level: int) -> None:
        self._levels[subsys] = level

    def get_level(self, subsys: str) -> int:
        return self._levels.get(subsys, self.default_level)

    def log(self, subsys: str, level: int, msg: str) -> None:
        now = time.time()
        with self._lock:
            self._recent.append((now, subsys, level, msg))
        if level <= self.get_level(subsys):
            ts = time.strftime("%H:%M:%S", time.localtime(now))
            print(f"{ts} {subsys} {level} : {msg}", file=self._sink)

    # dout(n) convenience
    def debug(self, subsys: str, msg: str, level: int = 10) -> None:
        self.log(subsys, level, msg)

    def info(self, subsys: str, msg: str) -> None:
        self.log(subsys, 1, msg)

    def error(self, subsys: str, msg: str) -> None:
        self.log(subsys, 0, msg)

    def recent(self, n: int | None = None) -> list[tuple]:
        with self._lock:
            items = list(self._recent)
        return items if n is None else items[-n:]

    def dump_recent(self, sink=None) -> None:
        """Crash-time dump of the ring buffer (Log::dump_recent)."""
        sink = sink or self._sink
        print("--- begin dump of recent events ---", file=sink)
        for ts, subsys, level, msg in self.recent():
            t = time.strftime("%H:%M:%S", time.localtime(ts))
            print(f"  {t} {subsys} {level} : {msg}", file=sink)
        print("--- end dump of recent events ---", file=sink)


_context: Logger | None = None


def log_context() -> Logger:
    """Process-wide logger (CephContext::_log analog)."""
    global _context
    if _context is None:
        _context = Logger()
    return _context
