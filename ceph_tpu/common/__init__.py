"""Common runtime: typed config, perf counters, admin socket, logging.

The src/common analog: ConfigProxy with observers
(src/common/config_proxy.h), PerfCounters (src/common/perf_counters.cc),
per-daemon admin socket (src/common/admin_socket.cc), and the dout
ring-buffer logger (src/log/Log.cc).
"""

from .config import Option, ConfigProxy, OPT_INT, OPT_FLOAT, OPT_STR, \
    OPT_BOOL
from .faults import FaultDecision, FaultRule, MessageFaultInjector
from .perf import PerfCounters, PerfCountersCollection
from .admin_socket import AdminSocket
from .log import Logger, log_context


def make_task_tracker(tasks: list):
    """Track a background task with a strong ref that self-prunes on
    completion -- long-running daemons spawn periodic tasks and an
    append-only list is an unbounded leak."""
    def track(t):
        tasks.append(t)

        def _done(task, _tasks=tasks):
            try:
                _tasks.remove(task)
            except ValueError:
                pass
        t.add_done_callback(_done)
        return t
    return track


__all__ = [
    "Option", "ConfigProxy", "OPT_INT", "OPT_FLOAT", "OPT_STR",
    "OPT_BOOL", "PerfCounters", "PerfCountersCollection", "AdminSocket",
    "Logger", "log_context", "make_task_tracker", "FaultDecision",
    "FaultRule", "MessageFaultInjector",
]
