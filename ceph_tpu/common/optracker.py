"""OpTracker: in-flight op introspection and historic-op retention.

The TrackedOp/OpTracker analog (src/common/TrackedOp.h): every client
op registers on arrival, marks named EVENTS as it moves through the
pipeline (queued -> reached_pg -> started -> sub_op_commit ...), and
on completion migrates into a bounded historic ring kept two ways --
most recent and slowest -- exactly the pair ``dump_historic_ops`` /
``dump_historic_ops_by_duration`` serve.  Ops in flight past the
complaint threshold surface as slow ops (the OSD warns the cluster
log and counts them; src/osd/OSD.cc get_health_metrics).
"""

from __future__ import annotations

import itertools
import time
from collections import deque


class TrackedOp:
    __slots__ = ("tracker", "opid", "desc", "start", "events", "done")

    def __init__(self, tracker: "OpTracker", opid: int,
                 desc: dict) -> None:
        self.tracker = tracker
        self.opid = opid
        self.desc = desc
        self.start = time.monotonic()
        self.events: list[tuple[float, str]] = [(self.start,
                                                 "initiated")]
        self.done = False

    def event(self, name: str) -> None:
        if not self.done:
            self.events.append((time.monotonic(), name))

    @property
    def age(self) -> float:
        return time.monotonic() - self.start

    @property
    def duration(self) -> float:
        return (self.events[-1][0] - self.start) if self.done \
            else self.age

    def finish(self) -> None:
        if not self.done:
            self.event("done")
            self.done = True
            self.tracker._retire(self)

    def to_dict(self) -> dict:
        t0 = self.start
        return {
            "id": self.opid, **self.desc,
            "age": round(self.age, 4),
            "duration": round(self.duration, 4),
            "events": [{"t": round(t - t0, 4), "event": name}
                       for t, name in self.events],
        }


class OpTracker:
    def __init__(self, history_size: int = 20,
                 history_slow_size: int = 20,
                 complaint_time: float = 30.0) -> None:
        self.inflight: dict[int, TrackedOp] = {}
        self.history: deque[TrackedOp] = deque(maxlen=history_size)
        self.history_slow: list[TrackedOp] = []   # kept sorted, bounded
        self.history_slow_size = history_slow_size
        self.complaint_time = complaint_time
        self._serial = itertools.count(1)
        self.complained: set[int] = set()         # slow ops already warned

    def create(self, **desc) -> TrackedOp:
        op = TrackedOp(self, next(self._serial), desc)
        self.inflight[op.opid] = op
        return op

    def _retire(self, op: TrackedOp) -> None:
        self.inflight.pop(op.opid, None)
        self.complained.discard(op.opid)
        self.history.append(op)
        self.history_slow.append(op)
        self.history_slow.sort(key=lambda o: -o.duration)
        del self.history_slow[self.history_slow_size:]

    # -- dumps (admin socket surface) ----------------------------------------
    def dump_ops_in_flight(self) -> dict:
        ops = sorted(self.inflight.values(), key=lambda o: o.start)
        return {"num_ops": len(ops),
                "ops": [o.to_dict() for o in ops]}

    def dump_historic_ops(self) -> dict:
        return {"num_ops": len(self.history),
                "ops": [o.to_dict() for o in self.history]}

    def dump_historic_ops_by_duration(self) -> dict:
        return {"num_ops": len(self.history_slow),
                "ops": [o.to_dict() for o in self.history_slow]}

    def slow_ops(self) -> list[TrackedOp]:
        """In-flight ops past the complaint threshold."""
        return [o for o in self.inflight.values()
                if o.age > self.complaint_time]
