"""Throttle, Finisher, FaultInjector (src/common analogs).

  * Throttle (src/common/Throttle.h): async token bucket with
    backpressure -- get() waits while the budget is exhausted, FIFO
    fair.  The OSD caps in-flight client op bytes with one
    (osd_client_message_size_cap).
  * Finisher (src/common/Finisher.h): ordered completion queue -- one
    drain task executes callbacks strictly in queue order, decoupling
    completion work from the context that produced it.
  * FaultInjector (src/common/fault_injector.h:66): typed, targeted
    failure injection for tests/chaos -- arm a site by name with a
    probability or a countdown, hot paths call check()/maybe_raise().
    Wired consumers: store read EIO injection and messenger socket
    failures (ms_inject_socket_failures).
"""

from __future__ import annotations

import asyncio
import random
from collections import deque
from typing import Awaitable, Callable


class Throttle:
    def __init__(self, name: str, limit: int) -> None:
        self.name = name
        self.limit = limit
        self.current = 0
        self._waiters: deque[tuple[int, asyncio.Future]] = deque()

    def _wake(self) -> None:
        while self._waiters:
            count, fut = self._waiters[0]
            if self.current + count > self.limit and self.current > 0:
                break
            self._waiters.popleft()
            if not fut.done():
                self.current += count
                fut.set_result(None)

    async def get(self, count: int = 1) -> None:
        """Take ``count`` tokens, waiting while over limit.  A single
        request larger than the whole limit is admitted alone rather
        than deadlocking (Throttle::get oversized semantics)."""
        if count < 0:
            raise ValueError("negative throttle count")
        if (self.current + count <= self.limit or self.current == 0) \
                and not self._waiters:
            self.current += count
            return
        fut = asyncio.get_event_loop().create_future()
        self._waiters.append((count, fut))
        try:
            await fut
        except asyncio.CancelledError:
            # Task.cancel() cancels the FUTURE (fut.done() is True but
            # no tokens were granted); only a future _wake resolved
            # with a result actually took tokens and owes a put()
            if fut.cancelled():
                try:
                    self._waiters.remove((count, fut))
                except ValueError:
                    pass
                self._wake()     # our slot may have blocked others
            else:
                self.put(count)
            raise

    def get_or_fail(self, count: int = 1) -> bool:
        if self.current + count > self.limit and self.current > 0:
            return False
        self.current += count
        return True

    def put(self, count: int = 1) -> None:
        self.current = max(0, self.current - count)
        self._wake()

    def past_midpoint(self) -> bool:
        return self.current * 2 >= self.limit


class Finisher:
    """Ordered completion runner: queue() preserves execution order."""

    def __init__(self, name: str = "fin") -> None:
        self.name = name
        self._q: asyncio.Queue = asyncio.Queue()
        self._task: asyncio.Task | None = None
        self._drained = asyncio.Event()
        self._drained.set()

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._run())

    def queue(self, fn: Callable[[], None | Awaitable]) -> None:
        self._drained.clear()
        self._q.put_nowait(fn)
        self.start()

    async def _run(self) -> None:
        try:
            while True:
                if self._q.empty():
                    self._drained.set()
                fn = await self._q.get()
                try:
                    out = fn()
                    if asyncio.iscoroutine(out):
                        await out
                except Exception:
                    pass                      # completions never kill the drain
        except asyncio.CancelledError:
            pass

    async def wait_for_empty(self) -> None:
        await self._drained.wait()

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None


class FaultInjector:
    """Named injection sites armed with probability or countdown."""

    def __init__(self, seed: int | None = None) -> None:
        self._sites: dict[str, dict] = {}
        self._rng = random.Random(seed)
        self.fired: dict[str, int] = {}

    def arm(self, site: str, *, probability: float = 0.0,
            countdown: int = 0, error: type = IOError,
            detail: str = "") -> None:
        """probability: fire on each check with p; countdown: fire once
        after N-1 passes (the reference's one-shot typed injection)."""
        self._sites[site] = {"p": probability, "count": countdown,
                             "error": error, "detail": detail}

    def disarm(self, site: str) -> None:
        self._sites.pop(site, None)

    def check(self, site: str) -> bool:
        """True when the fault fires (caller raises/acts)."""
        spec = self._sites.get(site)
        if spec is None:
            return False
        if spec["count"] > 0:
            spec["count"] -= 1
            if spec["count"] == 0:
                self._sites.pop(site, None)
                self.fired[site] = self.fired.get(site, 0) + 1
                return True
            return False
        if spec["p"] > 0 and self._rng.random() < spec["p"]:
            self.fired[site] = self.fired.get(site, 0) + 1
            return True
        return False

    def maybe_raise(self, site: str) -> None:
        spec = self._sites.get(site)
        if spec is not None and self.check(site):
            raise spec["error"](
                spec["detail"] or f"injected fault at {site}")


# process-wide injector the wired sites consult (tests arm it)
injector = FaultInjector()
