"""PaxosService breadth: auth, centralized config, cluster log, health.

The reference multiplexes every map service over ONE paxos instance
(PaxosService.cc propose batching); here the extra services ride the
osdmap Incremental's ``service_kv`` payload, so their state commits
and replays with the same quorum guarantees as the map itself:

  * AuthMonitor  (src/mon/AuthMonitor.cc): entity -> {key, caps}
    provisioning (auth get-or-create / get / ls / rm).
  * ConfigMonitor (src/mon/ConfigMonitor.cc): the central config DB
    (ceph config set/get/rm/dump), pushed to daemons on commit and at
    boot so runtime options flow through each daemon's ConfigProxy
    observers.
  * LogMonitor   (src/mon/LogMonitor.cc): the structured cluster log
    (ceph log / log last), fed by daemon clog messages and by the
    mon's own events (osd down, pool create...).
  * HealthMonitor (src/mon/health_check.h): derived health checks
    (OSD_DOWN, MON_DOWN, POOL_TOO_FEW_OSDS, MGR_DOWN) aggregated into
    HEALTH_OK/WARN/ERR for ``ceph health`` / ``ceph -s``.
"""

from __future__ import annotations

import json
import logging
import os
import time

LOG_CAP = 1000


def _surface_task_death(task) -> None:
    """Done-callback for fire-and-forget publish tasks: a task whose
    exception is never retrieved dies silently (and may be GC'd
    mid-flight) -- retrieve it and log instead."""
    if task.cancelled():
        return
    exc = task.exception()
    if exc is not None:
        logging.getLogger("ceph_tpu.mon").warning(
            "mgr_map publish task died: %r", exc)


class UnknownCommand(Exception):
    """Not a service command -- the caller's table handles it.  A
    dedicated type: KeyError would also catch missing ARGUMENTS inside
    handlers and misreport them as unknown commands."""


class MonServices:
    def __init__(self, mon) -> None:
        self.mon = mon
        self.config_db: dict[str, str] = {}       # "who/name" -> value
        self.auth_db: dict[str, dict] = {}        # entity -> {key, caps}
        self.cluster_log: list[dict] = []         # ring of log entries
        self.log_seq = 0
        # FSMap (MDSMonitor): mon-owned MDS membership -- which daemon
        # is the active metadata server, who stands by, epoch per
        # change (src/mon/MDSMonitor.cc / FSMap).  Replicated through
        # paxos like every service; beacon liveness is in-memory on
        # the leader (mds_last_beacon on the Monitor).
        self.fsmap: dict = {"epoch": 0, "active": None, "standbys": []}
        # MgrMap (MgrMonitor): which mgr is active, who stands by --
        # replicated so a peon answers mgr_map and failover survives
        # mon leadership changes (src/mon/MgrMonitor.cc)
        self.mgrmap: dict = {"epoch": 0, "active": None,
                             "active_addr": None, "standbys": []}
        # KVMonitor (config-key store, src/mon/KVMonitor.cc): the
        # cluster-wide durable key/value stash ("ceph config-key ...")
        self.kv_db: dict[str, str] = {}
        # replicated cephx rotating service keys: service -> dict
        self.cephx_keys: dict[str, dict] = {}

    # -- replication hook ----------------------------------------------------
    def apply(self, service_kv: dict) -> None:
        """Apply a committed incremental's service payloads (also runs
        at replay, so state rebuilds from the paxos log)."""
        for key, val in service_kv.get("config", {}).items():
            if val is None:
                self.config_db.pop(key, None)
            else:
                self.config_db[key] = val
        for entity, val in service_kv.get("auth", {}).items():
            if val is None:
                self.auth_db.pop(entity, None)
            else:
                self.auth_db[entity] = json.loads(val) \
                    if isinstance(val, str) else val
        for svc, val in service_kv.get("cephx", {}).items():
            self.cephx_keys[svc] = (json.loads(val)
                                    if isinstance(val, str) else val)
            # a live authority must see replicated rotations too
            mon = self.mon
            if getattr(mon, "_cephx", None) is not None:
                from ..common.cephx import RotatingKeys
                mon._cephx.rotating[svc] = RotatingKeys.from_dict(
                    self.cephx_keys[svc], mon._cephx.ttl)
        fsval = service_kv.get("fsmap", {}).get("map")
        if fsval is not None:
            self.fsmap = (json.loads(fsval)
                          if isinstance(fsval, str) else fsval)
        mgrval = service_kv.get("mgrmap", {}).get("map")
        if mgrval is not None:
            self.mgrmap = (json.loads(mgrval)
                           if isinstance(mgrval, str) else mgrval)
            # EVERY mon pushes the new mgr_map to its own subscribers
            # (daemons may be sessioned to a peon)
            import asyncio as _asyncio
            try:
                t = _asyncio.ensure_future(self.mon._publish_mgr_map())
                t.add_done_callback(_surface_task_death)
            except RuntimeError:
                pass          # replay outside a loop (mon boot)
        for key, val in service_kv.get("kvstore", {}).items():
            if val is None:
                self.kv_db.pop(key, None)
            else:
                self.kv_db[key] = val
        for _, val in sorted(service_kv.get("log", {}).items()):
            entry = json.loads(val) if isinstance(val, str) else val
            self.cluster_log.append(entry)
            self.log_seq = max(self.log_seq, entry.get("seq", 0))
        del self.cluster_log[:-LOG_CAP]

    # -- LogMonitor ----------------------------------------------------------
    def log_entry(self, level: str, message: str,
                  who: str = "") -> dict:
        """Build a cluster-log service payload (caller folds it into an
        incremental; the mon's own events share the map's commit)."""
        self.log_seq += 1
        return {str(self.log_seq): {
            "seq": self.log_seq, "stamp": time.time(),
            "level": level, "who": who or f"mon.{self.mon.rank}",
            "message": message}}

    # -- ConfigMonitor -------------------------------------------------------
    def config_for(self, who: str) -> dict[str, str]:
        """Effective config for a daemon: global < type < id sections
        (ConfigMonitor's option masking)."""
        out: dict[str, str] = {}
        dtype = who.split(".")[0]
        for section in ("global", dtype, who):
            for key, val in self.config_db.items():
                sec, _, name = key.partition("/")
                if sec == section:
                    out[name] = val
        return out

    # -- AuthMonitor ---------------------------------------------------------
    def auth_get_or_create(self, entity: str,
                           caps: dict | None = None) -> dict:
        if entity not in self.auth_db:
            return {"entity": entity,
                    "key": os.urandom(16).hex(),
                    "caps": caps or {}}
        cur = dict(self.auth_db[entity])
        if caps:
            cur = {**cur, "caps": caps}
        return {"entity": entity, **cur}

    # -- HealthMonitor -------------------------------------------------------
    def health(self) -> dict:
        mon = self.mon
        checks: dict[str, dict] = {}
        down = [o for o, info in mon.osdmap.osds.items()
                if not info.up and info.in_cluster]
        if down:
            checks["OSD_DOWN"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{len(down)} osds down",
                "detail": [f"osd.{o} is down" for o in sorted(down)]}
        n_mons = len([a for a in mon.peer_addrs if a is not None])
        if n_mons and len(mon.quorum) < n_mons:
            missing = sorted(set(range(n_mons)) - mon.quorum)
            checks["MON_DOWN"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{len(missing)}/{n_mons} mons out of quorum",
                "detail": [f"mon.{r} not in quorum" for r in missing]}
        n_up = sum(1 for o in mon.osdmap.osds.values() if o.up)
        narrow = [p for p in mon.osdmap.pools.values()
                  if p.size > max(n_up, 0)]
        if narrow:
            checks["POOL_TOO_FEW_OSDS"] = {
                "severity": "HEALTH_ERR",
                "summary": f"{len(narrow)} pool(s) wider than the "
                           f"up OSD count",
                "detail": [f"pool {p.name} size {p.size} > "
                           f"{n_up} up osds" for p in narrow]}
        slow = {o: r for o, r in getattr(mon, "slow_ops_reports",
                                         {}).items()
                if r["count"] > 0
                and time.monotonic() - r["stamp"] < 60.0}
        if slow:
            total = sum(r["count"] for r in slow.values())
            oldest = max(r["oldest_age"] for r in slow.values())
            names = ",".join(f"osd.{o}" for o in sorted(slow))
            checks["SLOW_OPS"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{total} slow ops, oldest one blocked for "
                           f"{oldest:.0f} sec, daemons [{names}] "
                           f"have slow ops.",
                "detail": [f"osd.{o}: {r['count']} ops, oldest "
                           f"{r['oldest_age']:.0f}s"
                           for o, r in sorted(slow.items())]}
        act = self.mgrmap.get("active")
        beats = getattr(mon, "mgr_last_beacon", None) or {}
        beat = beats.get(act) if act else None
        if act and beat is not None \
                and time.monotonic() - beat > 30.0:
            checks["MGR_DOWN"] = {
                "severity": "HEALTH_WARN",
                "summary": f"no beacon from active mgr {act} for 30s",
                "detail": []}
        status = "HEALTH_OK"
        for c in checks.values():
            if c["severity"] == "HEALTH_ERR":
                status = "HEALTH_ERR"
                break
            status = "HEALTH_WARN"
        return {"status": status, "checks": checks}

    # -- command surface -----------------------------------------------------
    async def handle_command(self, cmd: str, args: dict):
        """Returns the result, or raises UnknownCommand to fall through."""
        mon = self.mon
        if cmd == "fs dump":
            return dict(self.fsmap)
        if cmd == "config set":
            who = args.get("who", "global")
            await mon.propose_service_kv("config", {
                f"{who}/{args['name']}": str(args["value"])})
            return f"{who}/{args['name']} = {args['value']}"
        if cmd == "config rm":
            who = args.get("who", "global")
            await mon.propose_service_kv("config",
                                         {f"{who}/{args['name']}": None})
            return ""
        if cmd == "config get":
            return self.config_for(args.get("who", "global"))
        if cmd == "config dump":
            return dict(sorted(self.config_db.items()))
        if cmd == "config-key set":
            await mon.propose_service_kv(
                "kvstore", {args["key"]: str(args["value"])})
            return ""
        if cmd == "config-key get":
            if args["key"] not in self.kv_db:
                raise ValueError(f"no such key {args['key']}")
            return self.kv_db[args["key"]]
        if cmd == "config-key rm":
            await mon.propose_service_kv("kvstore",
                                         {args["key"]: None})
            return ""
        if cmd == "config-key ls":
            return sorted(self.kv_db)
        if cmd == "mgr dump":
            return dict(self.mgrmap)
        if cmd == "mgr fail":
            # depose the active and promote a standby NOW (not on the
            # next beacon race, which the deposed mgr usually wins)
            m = dict(self.mgrmap)
            if m.get("active"):
                m["epoch"] += 1
                stand = m.get("standbys", [])
                if stand:
                    nxt = stand[0]
                    m.update({"active": nxt["name"],
                              "active_addr": nxt["addr"],
                              "standbys": stand[1:]})
                else:
                    m.update({"active": None, "active_addr": None})
                await mon.propose_service_kv(
                    "mgrmap", {"map": json.dumps(m)})
                await mon._publish_mgr_map()
            return dict(m)
        if cmd == "auth get-or-create":
            entry = self.auth_get_or_create(args["entity"],
                                            args.get("caps"))
            entity = entry.pop("entity")
            if self.auth_db.get(entity) != entry:
                await mon.propose_service_kv("auth", {entity: entry})
            return {"entity": entity, **entry}
        if cmd == "auth get":
            if args["entity"] not in self.auth_db:
                raise ValueError(f"no such entity {args['entity']}")
            return {"entity": args["entity"],
                    **self.auth_db[args["entity"]]}
        if cmd == "auth ls":
            return {e: {"caps": v.get("caps", {})}
                    for e, v in sorted(self.auth_db.items())}
        if cmd == "auth rm":
            await mon.propose_service_kv("auth", {args["entity"]: None})
            return ""
        if cmd == "log":
            payload = self.log_entry(args.get("level", "INF"),
                                     args["message"],
                                     who=args.get("who", "client"))
            await mon.propose_service_kv("log", payload)
            return ""
        if cmd == "log last":
            n = int(args.get("n", 20))
            return self.cluster_log[-n:]
        if cmd == "health":
            h = self.health()
            if args.get("detail"):
                return h
            return {"status": h["status"],
                    "summary": {k: v["summary"]
                                for k, v in h["checks"].items()}}
        raise UnknownCommand(cmd)
