"""OSDMap: the cluster map clients and OSDs both compute placement from.

Semantics mirrored from src/osd/OSDMap.cc: object->pg via the rjenkins
string hash and ceph_stable_mod (:2606-2624, src/include/rados.h:96),
pg->osds via pps = crush_hash32_2(stable_mod(ps, pgp_num, mask), pool)
(src/osd/osd_types.cc:1817) into crush_do_rule, nonexistent-osd filtering
(:2651), primary = first mapped shard.  Maps evolve by Incrementals keyed
by epoch, exactly how the reference distributes MOSDMap deltas.
"""

from __future__ import annotations

from dataclasses import dataclass, field, asdict
from typing import Any

from ..crush import (
    CrushMap, crush_do_rule, ceph_str_hash_rjenkins, crush_hash32_2,
)
from ..crush.types import (
    Bucket, Rule, RuleStep, Tunables, CRUSH_ITEM_NONE,
)

POOL_TYPE_REPLICATED = 1
POOL_TYPE_ERASURE = 3


def calc_bits_of(n: int) -> int:
    return n.bit_length()


def ceph_stable_mod(x: int, b: int, bmask: int) -> int:
    return x & bmask if (x & bmask) < b else x & (bmask >> 1)


@dataclass
class PoolSpec:
    pool_id: int
    name: str
    type: int = POOL_TYPE_REPLICATED
    size: int = 3
    min_size: int = 2
    pg_num: int = 32
    pgp_num: int = 32
    crush_rule: int = 0
    erasure_code_profile: str = ""
    flags: int = 1  # FLAG_HASHPSPOOL
    # self-managed snapshots (pg_pool_t::snap_seq / removed_snaps):
    # snap ids are allocated monotonically by the mon; removal marks
    # the id for OSD-side trimming
    snap_seq: int = 0
    removed_snaps: list = field(default_factory=list)

    @property
    def pg_num_mask(self) -> int:
        return (1 << calc_bits_of(self.pg_num - 1)) - 1

    @property
    def pgp_num_mask(self) -> int:
        return (1 << calc_bits_of(self.pgp_num - 1)) - 1

    def hash_key(self, key: str, nspace: str = "") -> int:
        if nspace:
            data = nspace.encode() + b"\x1f" + key.encode()
        else:
            data = key.encode()
        return ceph_str_hash_rjenkins(data)

    def raw_pg_to_pps(self, ps: int) -> int:
        if self.flags & 1:
            return crush_hash32_2(
                ceph_stable_mod(ps, self.pgp_num, self.pgp_num_mask),
                self.pool_id)
        return ceph_stable_mod(ps, self.pgp_num, self.pgp_num_mask) + \
            self.pool_id

    def raw_pg_to_pg(self, ps: int) -> int:
        return ceph_stable_mod(ps, self.pg_num, self.pg_num_mask)

    def is_erasure(self) -> bool:
        return self.type == POOL_TYPE_ERASURE

    def can_shift_osds(self) -> bool:
        return not self.is_erasure()


@dataclass
class OsdInfo:
    up: bool = False
    in_cluster: bool = True
    weight: int = 0x10000          # reweight, 16.16
    addr: tuple[str, int] | None = None
    uuid: str = ""
    host: str = ""
    down_at_epoch: int = 0
    # last epoch through which this OSD is known to have SERVED writes
    # as a primary (osd_info_t::up_thru): peering bumps it before
    # activating, so past intervals whose primary never got an up_thru
    # bump provably never went read-write and need not be probed
    up_thru: int = 0


@dataclass
class Incremental:
    epoch: int
    new_up: dict[int, list] = field(default_factory=dict)     # osd -> addr
    new_down: list[int] = field(default_factory=list)
    new_in: list[int] = field(default_factory=list)
    new_out: list[int] = field(default_factory=list)
    new_weights: dict[int, int] = field(default_factory=dict)
    new_pools: dict[int, dict] = field(default_factory=dict)
    removed_pools: list[int] = field(default_factory=list)
    new_crush: dict | None = None
    new_ec_profiles: dict[str, dict] = field(default_factory=dict)
    removed_ec_profiles: list[str] = field(default_factory=list)
    new_max_osd: int | None = None
    # pgid -> acting override; [] removes (OSDMap::Incremental
    # new_pg_temp semantics).  pg_upmap_items: pgid -> [[from, to]...]
    new_pg_temp: dict[str, list[int]] = field(default_factory=dict)
    new_up_thru: dict[int, int] = field(default_factory=dict)
    new_pg_upmap_items: dict[str, list] = field(default_factory=dict)
    removed_pg_upmap_items: list[str] = field(default_factory=list)
    # replicated identity/topology state: a NEW leader must be able to
    # rebuild the crush hierarchy and keep osd ids stable from the MAP
    # alone, not from the old leader's in-memory registries
    new_uuids: dict[int, str] = field(default_factory=dict)
    new_hosts: dict[int, str] = field(default_factory=dict)
    # pool_id -> {"snap_seq": int, "removed": [snapids]}
    new_pool_snaps: dict[int, dict] = field(default_factory=dict)
    # client-instance blocklist (OSDMap::Incremental new_blocklist,
    # mon/OSDMonitor.cc "osd blocklist"): instance id "name:inc" ->
    # absolute wall-clock expiry; OSDs refuse ops from listed
    # instances, fencing lease-lapsed CephFS clients and deposed rbd
    # lock holders whose delayed writes are still in flight
    new_blocklist: dict[str, float] = field(default_factory=dict)
    old_blocklist: list[str] = field(default_factory=list)

    def placement_neutral(self) -> bool:
        """True when applying this incremental cannot change any PG's
        up/acting: only liveness bookkeeping (up_thru), client fencing
        (blocklist), identity/topology labels (uuid/host), snap
        bookkeeping, EC profile registration or service payloads.
        The placement cache survives such epochs untouched — on a
        large cluster the peering storm after a pool create emits one
        up_thru epoch per PG, and rebuilding a 64-OSD full-cluster
        table on every daemon for each of them is minutes of CPU that
        produce byte-identical tables."""
        return not (self.new_up or self.new_down or self.new_in
                    or self.new_out or self.new_weights
                    or self.new_pools or self.removed_pools
                    or self.new_crush is not None
                    or self.new_max_osd is not None
                    or self.new_pg_temp or self.new_pg_upmap_items
                    or self.removed_pg_upmap_items)

    # other PaxosService payloads riding the SAME paxos commit (the
    # reference multiplexes every service over one paxos instance):
    # service -> {key: value-or-None(delete)}; applied by the Monitor's
    # service layer, opaque to the osdmap itself
    service_kv: dict[str, dict] = field(default_factory=dict)

    def to_dict(self) -> dict:
        d = asdict(self)
        d["new_up"] = {str(k): v for k, v in self.new_up.items()}
        d["new_weights"] = {str(k): v for k, v in self.new_weights.items()}
        d["new_pools"] = {str(k): v for k, v in self.new_pools.items()}
        d["new_uuids"] = {str(k): v for k, v in self.new_uuids.items()}
        d["new_hosts"] = {str(k): v for k, v in self.new_hosts.items()}
        d["new_pool_snaps"] = {str(k): v
                               for k, v in self.new_pool_snaps.items()}
        d["new_up_thru"] = {str(k): v for k, v in self.new_up_thru.items()}
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Incremental":
        return cls(
            epoch=d["epoch"],
            new_up={int(k): v for k, v in d.get("new_up", {}).items()},
            new_down=list(d.get("new_down", [])),
            new_in=list(d.get("new_in", [])),
            new_out=list(d.get("new_out", [])),
            new_weights={int(k): v
                         for k, v in d.get("new_weights", {}).items()},
            new_pools={int(k): v for k, v in d.get("new_pools", {}).items()},
            removed_pools=list(d.get("removed_pools", [])),
            new_crush=d.get("new_crush"),
            new_ec_profiles=dict(d.get("new_ec_profiles", {})),
            removed_ec_profiles=list(d.get("removed_ec_profiles", [])),
            new_max_osd=d.get("new_max_osd"),
            new_pg_temp=dict(d.get("new_pg_temp", {})),
            new_up_thru={int(k): v
                         for k, v in d.get("new_up_thru", {}).items()},
            new_pg_upmap_items=dict(d.get("new_pg_upmap_items", {})),
            removed_pg_upmap_items=list(
                d.get("removed_pg_upmap_items", [])),
            new_uuids={int(k): v
                       for k, v in d.get("new_uuids", {}).items()},
            new_hosts={int(k): v
                       for k, v in d.get("new_hosts", {}).items()},
            service_kv=dict(d.get("service_kv", {})),
            new_pool_snaps={int(k): v for k, v in
                            d.get("new_pool_snaps", {}).items()},
            new_blocklist=dict(d.get("new_blocklist", {})),
            old_blocklist=list(d.get("old_blocklist", [])),
        )


def crush_to_dict(cm: CrushMap) -> dict:
    return {
        "buckets": [
            {"id": b.id, "type": b.type, "alg": b.alg, "hash": b.hash,
             "items": list(b.items), "item_weights": list(b.item_weights),
             "name": cm.bucket_names.get(b.id, "")}
            for b in cm.buckets.values()
        ],
        "rules": [
            {"rule_id": r.rule_id, "type": r.type,
             "steps": [[s.op, s.arg1, s.arg2] for s in r.steps]}
            for r in cm.rules.values()
        ],
        "tunables": asdict(cm.tunables),
        "max_devices": cm.max_devices,
    }


def crush_from_dict(d: dict) -> CrushMap:
    cm = CrushMap(tunables=Tunables(**d.get("tunables", {})))
    for bd in d.get("buckets", []):
        b = Bucket(id=bd["id"], type=bd["type"], alg=bd["alg"],
                   hash=bd.get("hash", 0), items=list(bd["items"]),
                   item_weights=list(bd["item_weights"]))
        cm.add_bucket(b, bd.get("name") or None)
    for rd in d.get("rules", []):
        cm.add_rule(Rule(rule_id=rd["rule_id"], type=rd["type"],
                         steps=[RuleStep(*s) for s in rd["steps"]]))
    cm.max_devices = max(cm.max_devices, d.get("max_devices", 0))
    return cm


class OSDMap:
    def __init__(self) -> None:
        self.epoch = 0
        self.max_osd = 0
        self.osds: dict[int, OsdInfo] = {}
        self.pools: dict[int, PoolSpec] = {}
        self.pool_names: dict[str, int] = {}
        self.crush = CrushMap()
        self.ec_profiles: dict[str, dict] = {}
        # placement cache plumbing (mon/pg_mapping.py): every mutation
        # entry point bumps _mutation_gen, and the memoized full-
        # cluster table + weight vector are keyed on it -- a stale-
        # generation read is structurally impossible
        self._mutation_gen = 0
        self._pcache: tuple[int, Any] | None = None
        self._weights_memo: tuple[int, list[int]] | None = None
        self._placement_perf = None
        # explicit placement overrides (OSDMap.cc:2705 _apply_upmap /
        # pg_temp): upmap items rewrite the raw CRUSH result (balancer
        # output), pg_temp overrides the ACTING set only (serving
        # continuity while the up set backfills)
        self.pg_temp: dict[str, list[int]] = {}
        self.pg_upmap_items: dict[str, list[tuple[int, int]]] = {}
        # fenced client instances: "name:incarnation" -> expiry (wall
        # clock).  OSDs refuse ops from these (OSDMap blocklist)
        self.blocklist: dict[str, float] = {}

    def is_blocklisted(self, instance_id: str,
                       now: float | None = None) -> bool:
        import time as _time
        exp = self.blocklist.get(instance_id)
        if exp is None:
            return False
        return exp > (_time.time() if now is None else now)

    # -- queries ------------------------------------------------------------
    def exists(self, osd: int) -> bool:
        return osd in self.osds

    def is_up(self, osd: int) -> bool:
        return osd in self.osds and self.osds[osd].up

    def get_up_thru(self, osd: int) -> int:
        info = self.osds.get(osd)
        return 0 if info is None else info.up_thru

    def get_pool_by_name(self, name: str) -> PoolSpec | None:
        pid = self.pool_names.get(name)
        return None if pid is None else self.pools.get(pid)

    def osd_weights(self) -> list[int]:
        """CRUSH input weight vector: 0 for out, reweight otherwise.

        Down-but-IN OSDs KEEP their weight (the reference feeds only
        in/out + reweight into CRUSH; up/down is applied by the post-
        filter in pg_to_up_acting).  Zeroing a down OSD here would
        re-run CRUSH without it and RESHUFFLE the raw placement -- for
        EC pools the acting-set position IS the shard id, so a reshuffle
        relabels every surviving OSD's stored shard bytes (the
        degraded-read corruption pinned by tests/test_ec_degraded.py).

        Memoized per mutation generation (the vector used to be
        rebuilt over max_osd on EVERY pg_to_up_acting call); callers
        treat the returned list as read-only."""
        if (self._weights_memo is not None
                and self._weights_memo[0] == self._mutation_gen):
            return self._weights_memo[1]
        n = max([self.max_osd] + [o + 1 for o in self.osds]) if self.osds \
            else self.max_osd
        w = [0] * n
        for osd, info in self.osds.items():
            if info.in_cluster:
                w[osd] = info.weight
        self._weights_memo = (self._mutation_gen, w)
        return w

    # -- placement cache ----------------------------------------------------
    @property
    def placement_perf(self):
        """This map's 'placement_cache' counter set (bulk_recomputes,
        fused/scalar pools, recompute time, lookups, delta_pgs).
        Daemons adopt it into their PerfCountersCollection so `perf
        dump` and the chaos driver see it."""
        if self._placement_perf is None:
            from ..common.perf import PerfCounters
            self._placement_perf = PerfCounters("placement_cache")
        return self._placement_perf

    def peek_placement_cache(self):
        """The built PGMapping for the CURRENT generation, or None --
        never triggers a build (map-change handlers capture the
        previous table for delta() before applying an incremental)."""
        if (self._pcache is not None
                and self._pcache[0] == self._mutation_gen):
            return self._pcache[1]
        return None

    def placement_cache(self):
        """The full-cluster placement table for this epoch, building
        it (one bulk recompute) on first use per mutation generation."""
        cached = self.peek_placement_cache()
        if cached is not None:
            return cached
        from .pg_mapping import PGMapping
        pm = PGMapping.build(self, perf=self.placement_perf)
        self._pcache = (self._mutation_gen, pm)
        return pm

    def invalidate_placement_cache(self) -> None:
        """Out-of-band map surgery (tests, offline tools editing
        fields directly) must call this; apply_incremental and the
        dict loaders bump the generation themselves."""
        self._mutation_gen += 1

    # -- placement ----------------------------------------------------------
    def object_to_pg(self, pool_id: int, name: str, nspace: str = "",
                     key: str = "") -> tuple[int, int]:
        pool = self.pools[pool_id]
        ps = pool.hash_key(key or name, nspace)
        return pool_id, ps

    def _apply_upmap(self, pgid: str, raw: list[int]) -> list[int]:
        """Rewrite the raw CRUSH result with the pg's upmap items
        (OSDMap.cc:2705 _apply_upmap): each (from, to) replaces one
        occurrence, skipped when `to` already appears in the set."""
        items = self.pg_upmap_items.get(pgid)
        if not items:
            return raw
        out = list(raw)
        for frm, to in items:
            if to in out or not self.exists(to):
                continue
            for i, o in enumerate(out):
                if o == frm:
                    out[i] = to
                    break
        return out

    def pg_to_up_acting(self, pool_id: int,
                        ps: int) -> tuple[list[int], list[int]]:
        """(up, acting) for a pg (OSDMap.cc:2928 _pg_to_up_acting_osds).

        up = CRUSH + upmap + down-filter; acting = the pg_temp override
        when one is set (the serving set during backfill), else up.

        Served from the epoch-memoized full-cluster table (OSDMapMapping
        analog, mon/pg_mapping.py): CRUSH runs once per map generation
        in bulk, and this is an O(1) array read.  The per-PG scalar
        pipeline survives as _pg_to_up_acting_scalar -- the oracle the
        parity suite holds the table to, entry for entry."""
        pm = self.placement_cache()
        if self._placement_perf is not None:
            self._placement_perf.inc("lookups")
        return pm.lookup(pool_id, ps)

    def _pg_to_up_acting_scalar(self, pool_id: int,
                                ps: int) -> tuple[list[int], list[int]]:
        """Reference per-PG pipeline (one scalar crush_do_rule)."""
        pool = self.pools[pool_id]
        pgid = self.pg_name(pool_id, ps)
        pps = pool.raw_pg_to_pps(pool.raw_pg_to_pg(ps))
        weights = self.osd_weights()
        raw = crush_do_rule(self.crush, pool.crush_rule, pps, pool.size,
                            weights)
        raw = self._apply_upmap(pgid, raw)
        # filter nonexistent/down osds (_raw_to_up_osds, OSDMap.cc:2773):
        # replicated pools shift the survivors up; EC pools keep holes
        # because the acting-set position IS the shard id.  Holes are
        # NORMALIZED to -1 here -- every consumer downstream (pg.py
        # role/shard logic, clients, tools) uses the `o >= 0` test, and
        # a raw CRUSH_ITEM_NONE (2^31-1) leaking through reads as a
        # live osd id (the no-primary wedge the degraded-read repro hit)
        def live(o: int) -> bool:
            return o != CRUSH_ITEM_NONE and o >= 0 and self.is_up(o)
        if pool.can_shift_osds():
            up = [o for o in raw if live(o)]
        else:
            up = [o if live(o) else -1 for o in raw]
        temp = self.pg_temp.get(pgid)
        if temp:
            acting = [o if live(o) else -1 for o in temp]
            if pool.can_shift_osds():
                acting = [o for o in acting if o >= 0]
            if not acting:
                acting = up
        else:
            acting = up
        return up, acting

    def pg_to_up_acting_osds(self, pool_id: int, ps: int) -> list[int]:
        """Acting set (what clients target); see pg_to_up_acting."""
        return self.pg_to_up_acting(pool_id, ps)[1]

    def pg_primary(self, up: list[int]) -> int | None:
        # holes are -1 post-normalization; tolerate raw NONE too
        for o in up:
            if o >= 0 and o != CRUSH_ITEM_NONE:
                return o
        return None

    def pg_name(self, pool_id: int, ps: int) -> str:
        pool = self.pools[pool_id]
        return f"{pool_id}.{pool.raw_pg_to_pg(ps):x}"

    def pg_ids(self, pool_id: int) -> list[str]:
        pool = self.pools[pool_id]
        return [f"{pool_id}.{i:x}" for i in range(pool.pg_num)]

    # -- mutation -----------------------------------------------------------
    def apply_incremental(self, inc: Incremental) -> None:
        assert inc.epoch == self.epoch + 1, (inc.epoch, self.epoch)
        self.epoch = inc.epoch
        if inc.new_max_osd is not None:
            self.max_osd = inc.new_max_osd
        for osd, addr in inc.new_up.items():
            info = self.osds.setdefault(osd, OsdInfo())
            info.up = True
            info.addr = tuple(addr) if addr else None
        for osd in inc.new_down:
            info = self.osds.get(osd)
            if info is not None:
                info.up = False
                info.down_at_epoch = inc.epoch
        for osd in inc.new_in:
            self.osds.setdefault(osd, OsdInfo()).in_cluster = True
        for osd in inc.new_out:
            info = self.osds.get(osd)
            if info is not None:
                info.in_cluster = False
        for osd, w in inc.new_weights.items():
            self.osds.setdefault(osd, OsdInfo()).weight = w
        for osd, uuid in inc.new_uuids.items():
            self.osds.setdefault(osd, OsdInfo()).uuid = uuid
        for osd, host in inc.new_hosts.items():
            self.osds.setdefault(osd, OsdInfo()).host = host
        for pid, pd in inc.new_pools.items():
            spec = PoolSpec(**pd)
            self.pools[pid] = spec
            self.pool_names[spec.name] = pid
        for pid in inc.removed_pools:
            spec = self.pools.pop(pid, None)
            if spec:
                self.pool_names.pop(spec.name, None)
            # pool ids are reused (max+1): stale placement overrides
            # must not leak onto a future pool with the same id
            prefix = f"{pid}."
            for d in (self.pg_temp, self.pg_upmap_items):
                for pgid in [k for k in d if k.startswith(prefix)]:
                    d.pop(pgid)
        for iid, exp in inc.new_blocklist.items():
            self.blocklist[iid] = exp
        for iid in inc.old_blocklist:
            self.blocklist.pop(iid, None)
        if inc.new_crush is not None:
            self.crush = crush_from_dict(inc.new_crush)
        for name, profile in inc.new_ec_profiles.items():
            self.ec_profiles[name] = dict(profile)
        for name in inc.removed_ec_profiles:
            self.ec_profiles.pop(name, None)
        for osd, e in inc.new_up_thru.items():
            info = self.osds.setdefault(osd, OsdInfo())
            info.up_thru = max(info.up_thru, e)
        for pgid, osds in inc.new_pg_temp.items():
            if osds:
                self.pg_temp[pgid] = list(osds)
            else:
                self.pg_temp.pop(pgid, None)
        for pid, snaps in inc.new_pool_snaps.items():
            pool = self.pools.get(pid)
            if pool is not None:
                pool.snap_seq = max(pool.snap_seq,
                                    int(snaps.get("snap_seq", 0)))
                for sid in snaps.get("removed", []):
                    if sid not in pool.removed_snaps:
                        pool.removed_snaps.append(sid)
        for pgid, items in inc.new_pg_upmap_items.items():
            self.pg_upmap_items[pgid] = [tuple(i) for i in items]
        for pgid in inc.removed_pg_upmap_items:
            self.pg_upmap_items.pop(pgid, None)
        # a placement-AFFECTING incremental -- osd state, weights,
        # pools, crush, pg_temp, upmap -- retires the memoized
        # placement table and weight vector for the previous
        # generation; a placement-NEUTRAL one (up_thru/blocklist/...)
        # carries both forward, so the peering storm after a pool
        # create (one up_thru epoch per PG) costs zero rebuilds
        pcache, weights = self._pcache, self._weights_memo
        self._mutation_gen += 1
        if inc.placement_neutral():
            if pcache is not None and pcache[0] == self._mutation_gen - 1:
                self._pcache = (self._mutation_gen, pcache[1])
            if weights is not None and weights[0] == self._mutation_gen - 1:
                self._weights_memo = (self._mutation_gen, weights[1])

    # -- serialization ------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "max_osd": self.max_osd,
            "osds": {str(o): {"up": i.up, "in": i.in_cluster,
                              "weight": i.weight, "addr": i.addr,
                              "uuid": i.uuid, "host": i.host,
                              "down_at": i.down_at_epoch,
                              "up_thru": i.up_thru}
                     for o, i in self.osds.items()},
            "pools": {str(p): asdict(s) for p, s in self.pools.items()},
            "crush": crush_to_dict(self.crush),
            "ec_profiles": self.ec_profiles,
            "pg_temp": self.pg_temp,
            "pg_upmap_items": {k: [list(i) for i in v]
                               for k, v in self.pg_upmap_items.items()},
            "blocklist": dict(self.blocklist),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "OSDMap":
        m = cls()
        m.epoch = d["epoch"]
        m.max_osd = d["max_osd"]
        for o, i in d.get("osds", {}).items():
            m.osds[int(o)] = OsdInfo(
                up=i["up"], in_cluster=i["in"], weight=i["weight"],
                addr=tuple(i["addr"]) if i.get("addr") else None,
                uuid=i.get("uuid", ""), host=i.get("host", ""),
                down_at_epoch=i.get("down_at", 0),
                up_thru=i.get("up_thru", 0))
        for p, s in d.get("pools", {}).items():
            spec = PoolSpec(**s)
            m.pools[int(p)] = spec
            m.pool_names[spec.name] = int(p)
        m.crush = crush_from_dict(d["crush"])
        m.blocklist = dict(d.get("blocklist", {}))
        m.ec_profiles = dict(d.get("ec_profiles", {}))
        m.pg_temp = {k: list(v) for k, v in d.get("pg_temp", {}).items()}
        m.pg_upmap_items = {k: [tuple(i) for i in v]
                            for k, v in d.get("pg_upmap_items",
                                              {}).items()}
        return m
