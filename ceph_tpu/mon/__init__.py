"""Cluster control plane: monitors own the maps.

Monitors hold the authoritative OSDMap (epochs + incrementals), pool and
EC-profile tables, and the CRUSH map; changes commit through a
paxos-lite replicated log and publish to subscribers (the
Paxos/PaxosService/OSDMonitor stack of src/mon, rendered as asyncio
services over the v2-lite messenger).
"""

from .osdmap import OSDMap, PoolSpec, Incremental  # noqa: F401
from .pg_mapping import PGMapping  # noqa: F401
from .monitor import Monitor  # noqa: F401
