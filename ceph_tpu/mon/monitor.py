"""Monitor daemon: map authority, paxos-lite replication, failure handling.

Functional rendering of the src/mon stack: a replicated commit log with
collect/begin/accept/commit phases and a leader lease (Paxos.cc:154-1530),
map services that batch pending changes and propose them
(PaxosService.cc:196), and the OSDMonitor behaviors the data path needs:
osd boot -> up, failure reports with a min-reporter threshold
(mon_osd_min_down_reporters), down->out aging, pool and EC-profile
commands, CRUSH rule creation at pool create (OSDMonitor.cc:7484-7566).
"""

from __future__ import annotations

import asyncio
import json
import sqlite3
import time
from collections import defaultdict

from ..common import AdminSocket, PerfCountersCollection
from ..msg import Message, Messenger
from ..crush.types import (
    Bucket, CrushMap, CRUSH_BUCKET_STRAW2,
)
from ..crush.builder import replicated_rule, erasure_rule
from ..ec import registry as ec_registry
from .osdmap import (
    OSDMap, Incremental, PoolSpec, crush_to_dict,
    POOL_TYPE_REPLICATED, POOL_TYPE_ERASURE,
)

DEFAULT_EC_PROFILE = {"plugin": "tpu", "k": "2", "m": "1",
                      "technique": "reed_sol_van"}


class MonStore:
    """Versioned commit log + stashed full maps (MonitorDBStore analog)."""

    def __init__(self, path: str = ":memory:") -> None:
        self.conn = sqlite3.connect(path)
        with self.conn:
            self.conn.execute(
                "CREATE TABLE IF NOT EXISTS log ("
                "version INTEGER PRIMARY KEY, value BLOB)")
            self.conn.execute(
                "CREATE TABLE IF NOT EXISTS kv (key TEXT PRIMARY KEY, "
                "value BLOB)")

    def last_committed(self) -> int:
        row = self.conn.execute("SELECT MAX(version) FROM log").fetchone()
        return row[0] or 0

    def commit(self, version: int, value: bytes) -> None:
        with self.conn:
            self.conn.execute("INSERT OR REPLACE INTO log VALUES (?,?)",
                              (version, value))

    def get(self, version: int) -> bytes | None:
        row = self.conn.execute("SELECT value FROM log WHERE version=?",
                                (version,)).fetchone()
        return None if row is None else row[0]

    def put_kv(self, key: str, value: bytes) -> None:
        with self.conn:
            self.conn.execute("INSERT OR REPLACE INTO kv VALUES (?,?)",
                              (key, value))

    def get_kv(self, key: str) -> bytes | None:
        row = self.conn.execute("SELECT value FROM kv WHERE key=?",
                                (key,)).fetchone()
        return None if row is None else row[0]


class Monitor:
    def __init__(self, rank: int = 0, peers: list[tuple[str, int]] | None = None,
                 store_path: str = ":memory:", secret: bytes | None = None,
                 config: dict | None = None,
                 admin_socket_path: str | None = None,
                 msgr_opts: dict | None = None) -> None:
        self.rank = rank
        self.peer_addrs = peers or []     # rank -> addr (incl. self slot)
        self.msgr = Messenger(f"mon.{rank}", secret=secret,
                              **(msgr_opts or {}))
        self.store = MonStore(store_path)
        self.osdmap = OSDMap()
        self.config = {
            "mon_osd_min_down_reporters": 2,
            "mon_osd_down_out_interval": 600.0,
            "mon_lease": 5.0,
            **(config or {}),
        }
        self.incrementals: dict[int, Incremental] = {}
        self.subscribers: dict[str, object] = {}   # peer name -> Connection
        self.failure_reports: dict[int, set[str]] = defaultdict(set)
        self._pending_lock = asyncio.Lock()
        self._boot_lock = asyncio.Lock()
        self._pending_up_thru: set[int] = set()
        self._up_thru_flush: asyncio.Future | None = None
        self._up_thru_task: asyncio.Task | None = None
        self._tick_task: asyncio.Task | None = None
        self._down_since: dict[int, float] = {}
        # paxos-lite
        self.quorum: set[int] = {rank}
        self.accepts: dict[int, set[int]] = {}
        self._commit_waiters: dict[int, asyncio.Future] = {}
        # elections (ElectionLogic analog): epoch odd while electing,
        # even when a leader holds a quorum; the LOWEST alive rank wins
        # and data consistency is the collect phase's job, not the
        # election's (Elector.cc / Paxos.cc:154)
        self.election_epoch = 0
        self.leader: int | None = None
        self._election_acks: set[int] = set()
        self._election_task: asyncio.Task | None = None
        self._lease_expire = 0.0       # peon: leader lease deadline
        self._lease_acks: set[int] = set()
        self._lease_misses = 0
        self._lease_round = 0
        self._collect_replies: dict[int, dict] = {}
        self._collected = False        # leader ran collect this term
        self._stopped = False
        # observability (Paxos registers PerfCounters too, Paxos.cc:117)
        self.perf = PerfCountersCollection()
        self.perf_paxos = self.perf.create("paxos")
        self.admin_socket: AdminSocket | None = None
        self._admin_socket_path = admin_socket_path
        # the other PaxosServices (auth/config/log/health) ride the
        # same paxos commits via Incremental.service_kv
        from .services import MonServices
        self.services = MonServices(self)
        self.msgr.add_dispatcher(self._dispatch)
        self._replay()

    # -- lifecycle ----------------------------------------------------------
    def _replay(self) -> None:
        last = self.store.last_committed()
        for v in range(1, last + 1):
            blob = self.store.get(v)
            if blob:
                inc = Incremental.from_dict(json.loads(blob))
                self.osdmap.apply_incremental(inc)
                self.services.apply(inc.service_kv)
                self.incrementals[inc.epoch] = inc

    async def start(self, host: str = "127.0.0.1", port: int = 0):
        addr = await self.msgr.bind(host, port)
        while len(self.peer_addrs) <= self.rank:
            self.peer_addrs.append(None)
        self.peer_addrs[self.rank] = addr
        self._tick_task = asyncio.ensure_future(self._tick_loop())
        if self._admin_socket_path:
            self.admin_socket = AdminSocket(self._admin_socket_path)

            async def perf_dump(req):
                return self.perf.dump()

            async def mon_status(req):
                return {"rank": self.rank, "quorum": sorted(self.quorum),
                        "leader": self.is_leader,
                        "epoch": self.osdmap.epoch,
                        "last_committed": self.store.last_committed()}

            self.admin_socket.register("perf dump",
                                       "dump perf counters", perf_dump)
            self.admin_socket.register("mon_status", "monitor status",
                                       mon_status)
            await self.admin_socket.start()
        return addr

    async def stop(self) -> None:
        self._stopped = True
        if self.admin_socket is not None:
            await self.admin_socket.stop()
        if self._tick_task:
            self._tick_task.cancel()
        if self._election_task:
            self._election_task.cancel()
        await self.msgr.shutdown()

    @property
    def is_leader(self) -> bool:
        return self.leader == self.rank or self._n_mons() <= 1

    # -- public accessors (the in-process daemon boundary) ------------------
    # Harness/bench code must not hold the mon's live subsystems
    # (cross-daemon-state rule): these return plain data that a
    # mon_command round-trip could equally serve in the swarm.

    @property
    def addr(self) -> tuple[str, int] | None:
        """The mon's bound messenger address (None before start)."""
        return self.msgr.addr

    def osd_is_up(self, osd_id: int) -> bool:
        """Liveness of one OSD in the mon's current map view."""
        return self.osdmap.is_up(osd_id)

    def osd_addr(self, osd_id: int) -> tuple[str, int] | None:
        """Bound address of one OSD per the mon's current map view."""
        info = self.osdmap.osds.get(osd_id)
        addr = getattr(info, "addr", None)
        return tuple(addr) if addr else None

    def placement_counters(self) -> dict:
        """Snapshot of the mon-side placement-cache perf counters."""
        return self.osdmap.placement_perf.dump()

    def _n_mons(self) -> int:
        return len([a for a in self.peer_addrs if a is not None])

    # -- elections ----------------------------------------------------------
    def _mon_peers(self) -> list[int]:
        return [r for r, a in enumerate(self.peer_addrs)
                if a is not None and r != self.rank]

    async def _send_mon(self, r: int, msg: Message) -> None:
        try:
            await self.msgr.send(tuple(self.peer_addrs[r]), f"mon.{r}",
                                 msg)
        except (ConnectionError, OSError):
            pass

    def start_election(self) -> None:
        if self._n_mons() <= 1:
            self.leader = self.rank
            self.quorum = {self.rank}
            return
        if self._election_task is None or self._election_task.done():
            self._election_task = asyncio.ensure_future(
                self._run_election())

    async def _run_election(self) -> None:
        """Campaign until a leader (us or a lower rank) holds a quorum.

        Lowest alive rank wins; a higher-ranked campaigner defers as
        soon as it sees a lower rank's proposal (ElectionLogic's
        rank-priority deferral)."""
        try:
            backoff = 0.3
            while not self._stopped:
                # every campaign uses a FRESH odd epoch: reusing one
                # would let acks from an abandoned round count toward a
                # relaunched candidacy (double victory)
                self.election_epoch += 2 if self.election_epoch % 2 \
                    else 1
                self.leader = None
                self._election_acks = {self.rank}
                epoch = self.election_epoch
                for r in self._mon_peers():
                    await self._send_mon(r, Message(
                        "mon_election_propose",
                        {"epoch": epoch, "rank": self.rank,
                         "last_committed": self.store.last_committed()}))
                await asyncio.sleep(backoff)
                if self.election_epoch != epoch or self.leader is not None:
                    return        # someone else won (or a newer round)
                if len(self._election_acks) >= self._majority():
                    await self._declare_victory(epoch)
                    return
                self.election_epoch += 2   # new odd round
                backoff = min(2.0, backoff * 1.7)
        except asyncio.CancelledError:
            pass

    async def _declare_victory(self, epoch: int) -> None:
        self.election_epoch = epoch + 1        # even: stable
        self.leader = self.rank
        self.quorum = set(self._election_acks)
        self._lease_misses = 0
        self._collected = False
        for r in sorted(self.quorum - {self.rank}):
            await self._send_mon(r, Message(
                "mon_election_victory",
                {"epoch": self.election_epoch, "rank": self.rank,
                 "quorum": sorted(self.quorum)}))
        # recover any in-flight value before serving (Paxos collect)
        await self._paxos_collect()

    async def _h_mon_election_propose(self, conn, msg) -> None:
        epoch, rank = msg.data["epoch"], msg.data["rank"]
        if epoch < self.election_epoch:
            return                              # stale round
        if epoch > self.election_epoch:
            self.election_epoch = epoch
            self.leader = None
        if rank < self.rank:
            # defer to the lower rank; stop our own candidacy and hold
            # off re-campaigning long enough for its victory to land
            # (without the hold, the tick loop would relaunch us at a
            # higher epoch and depose the winner -- election flapping)
            if self._election_task and not self._election_task.done():
                self._election_task.cancel()
            self._defer_until = time.monotonic() + 1.5
            # the PROMISE: at most ONE ack per epoch -- acking a second
            # candidate in the same epoch (even a lower rank) could
            # hand two candidates a majority at once.  The lower rank
            # simply wins the next round instead.
            acked = getattr(self, "_acked", None)
            if acked is None or epoch > acked[0]:
                self._acked = (epoch, rank)
                await self._send_mon(rank, Message(
                    "mon_election_ack",
                    {"epoch": epoch, "rank": self.rank}))
        elif (self.leader is None
              and time.monotonic() > getattr(self, "_defer_until", 0.0)):
            self.start_election()               # outrank them: campaign

    async def _h_mon_election_ack(self, conn, msg) -> None:
        if msg.data["epoch"] == self.election_epoch:
            self._election_acks.add(msg.data["rank"])

    async def _h_mon_election_victory(self, conn, msg) -> None:
        epoch = msg.data["epoch"]
        if epoch < self.election_epoch:
            return
        if self._election_task and not self._election_task.done():
            self._election_task.cancel()
        self.election_epoch = epoch
        self.leader = msg.data["rank"]
        self.quorum = set(msg.data["quorum"])
        self._lease_expire = (time.monotonic()
                              + self.config["mon_lease"])

    # -- leases (Paxos lease: peons trust the leader while fresh) -----------
    async def _h_mon_lease(self, conn, msg) -> None:
        if msg.data["epoch"] != self.election_epoch:
            return
        self._lease_expire = time.monotonic() + self.config["mon_lease"]
        await conn.send(Message("mon_lease_ack",
                                {"epoch": self.election_epoch,
                                 "rank": self.rank}))

    async def _h_mon_lease_ack(self, conn, msg) -> None:
        if msg.data["epoch"] == self.election_epoch:
            self._lease_acks.add(msg.data["rank"])

    # -- paxos collect (Paxos.cc:154-613) -----------------------------------
    async def _paxos_collect(self) -> None:
        """New-leader recovery: learn every committed version the
        quorum has, re-propose any accepted-but-uncommitted value, and
        catch lagging peons up.  Nothing is served until this runs."""
        peers = sorted(self.quorum - {self.rank})
        self._collect_replies: dict[int, dict] = {}
        for r in peers:
            await self._send_mon(r, Message(
                "paxos_collect",
                {"epoch": self.election_epoch,
                 "last_committed": self.store.last_committed()}))
        deadline = time.monotonic() + 5.0
        while (len(self._collect_replies) < len(peers)
               and time.monotonic() < deadline):
            await asyncio.sleep(0.05)
        # 1. adopt committed versions we missed (they are FACTS)
        for rep in self._collect_replies.values():
            for v_str, blob_hex in sorted(rep.get("missing", {}).items(),
                                          key=lambda kv: int(kv[0])):
                v = int(v_str)
                if v == self.store.last_committed() + 1:
                    self._commit_local(v, bytes.fromhex(blob_hex))
        # 2. re-propose the HIGHEST-BALLOT accepted-but-uncommitted
        # value (classic phase-1: among competing accepted values, the
        # newest term's may already be committed somewhere unseen)
        next_v = self.store.last_committed() + 1
        best: tuple[int, bytes] | None = None
        if (blob := self.store.get_kv(f"pending_{next_v}")) is not None:
            ballot = int(self.store.get_kv(f"pending_e_{next_v}")
                         or b"0")
            best = (ballot, blob)
        for rep in self._collect_replies.values():
            u = rep.get("uncommitted")
            if u and int(u[0]) == next_v:
                ballot = int(u[2]) if len(u) > 2 else 0
                if best is None or ballot > best[0]:
                    best = (ballot, bytes.fromhex(u[1]))
        if best is not None:
            inc = Incremental.from_dict(json.loads(best[1]))
            inc.epoch = 0
            await self._propose_locked(inc, recovery=True)
        # 3. catch lagging peons up to our committed state
        for r, rep in self._collect_replies.items():
            for v in range(int(rep["last_committed"]) + 1,
                           self.store.last_committed() + 1):
                blob = self.store.get(v)
                if blob is not None:
                    await self._send_mon(r, Message(
                        "paxos_commit",
                        {"version": v, "value": blob.decode()}))
        self._collected = True

    async def _h_paxos_collect(self, conn, msg) -> None:
        if msg.data["epoch"] != self.election_epoch:
            return
        leader_lc = int(msg.data["last_committed"])
        mine = self.store.last_committed()
        missing = {str(v): self.store.get(v).hex()
                   for v in range(leader_lc + 1, mine + 1)
                   if self.store.get(v) is not None}
        uncommitted = None
        pending = self.store.get_kv(f"pending_{mine + 1}")
        if pending is not None:
            ballot = int(self.store.get_kv(f"pending_e_{mine + 1}")
                         or b"0")
            uncommitted = [mine + 1, pending.hex(), ballot]
        await conn.send(Message("paxos_last",
                                {"epoch": self.election_epoch,
                                 "rank": self.rank,
                                 "last_committed": mine,
                                 "missing": missing,
                                 "uncommitted": uncommitted}))

    async def _h_paxos_last(self, conn, msg) -> None:
        if msg.data["epoch"] == self.election_epoch:
            self._collect_replies[msg.data["rank"]] = msg.data

    def _majority(self) -> int:
        return len([a for a in self.peer_addrs if a is not None]) // 2 + 1

    # -- proposal path ------------------------------------------------------
    async def propose(self, inc: Incremental) -> None:
        """Commit one incremental through the quorum (leader-side)."""
        self.perf_paxos.inc("begin")
        with self.perf_paxos.time("commit_latency"):
            await self._propose_locked(inc)
        self.perf_paxos.inc("commit")

    async def _propose_locked(self, inc: Incremental,
                              recovery: bool = False) -> None:
        async with self._pending_lock:
            inc.epoch = self.osdmap.epoch + 1
            blob = json.dumps(inc.to_dict()).encode()
            version = inc.epoch
            n_peers = len([a for a in self.peer_addrs if a is not None])
            if n_peers <= 1:
                self._commit_local(version, blob)
            else:
                # a proposal that lands while an election is settling
                # waits for the term AND for the collect phase: serving
                # before collect could assign a version number the old
                # quorum already committed elsewhere (recovery=True is
                # the collect phase's own re-proposal)
                deadline = time.monotonic() + 5.0
                while (time.monotonic() < deadline
                       and (self.leader is None
                            or (self.is_leader and not recovery
                                and not self._collected))):
                    await asyncio.sleep(0.1)
                if not self.is_leader or (not recovery
                                          and not self._collected):
                    raise RuntimeError(
                        f"mon.{self.rank} cannot propose "
                        f"(leader={self.leader}, "
                        f"collected={self._collected})")
                inc.epoch = self.osdmap.epoch + 1
                blob = json.dumps(inc.to_dict()).encode()
                version = inc.epoch
                self.accepts[version] = {self.rank}
                fut = asyncio.get_event_loop().create_future()
                self._commit_waiters[version] = fut
                for r, addr in enumerate(self.peer_addrs):
                    if r == self.rank or addr is None:
                        continue
                    try:
                        await self.msgr.send(
                            tuple(addr), f"mon.{r}",
                            Message("paxos_begin",
                                    {"version": version,
                                     "e": self.election_epoch,
                                     "value": blob.decode()}))
                    except (ConnectionError, OSError):
                        pass
                await asyncio.wait_for(fut, timeout=10)
                self._commit_local(version, blob)
            await self._publish(inc)

    def _commit_local(self, version: int, blob: bytes) -> None:
        self.store.commit(version, blob)
        inc = Incremental.from_dict(json.loads(blob))
        self.osdmap.apply_incremental(inc)
        self.services.apply(inc.service_kv)
        if "config" in inc.service_kv:
            # EVERY mon pushes config to ITS subscribers (a daemon
            # subscribed to a peon must see changes the leader commits)
            t = asyncio.ensure_future(self.push_config())
            self._bg_tasks = getattr(self, "_bg_tasks", set())
            self._bg_tasks.add(t)
            t.add_done_callback(self._bg_tasks.discard)
        self.incrementals[inc.epoch] = inc
        # EVERY mon pushes deltas to its own subscribers (peons serve
        # map subscriptions too; the reference mons all publish)
        if self.subscribers:
            t = asyncio.ensure_future(self._push_subscribers(inc))
            self._bg_tasks = getattr(self, "_bg_tasks", set())
            self._bg_tasks.add(t)
            t.add_done_callback(self._bg_tasks.discard)

    async def _push_subscribers(self, inc: Incremental) -> None:
        dead = []
        for name, conn in list(self.subscribers.items()):
            try:
                await conn.send(Message("osdmap_inc",
                                        {"inc": inc.to_dict()}))
            except (ConnectionError, OSError):
                dead.append(name)
        for name in dead:
            self.subscribers.pop(name, None)

    async def _publish(self, inc: Incremental) -> None:
        # distribute commit (with its value: a peon that missed the
        # begin still converges) to the quorum
        n_peers = len([a for a in self.peer_addrs if a is not None])
        if n_peers > 1:
            blob = json.dumps(inc.to_dict()).encode()
            for r, addr in enumerate(self.peer_addrs):
                if r == self.rank or addr is None:
                    continue
                try:
                    await self.msgr.send(
                        tuple(addr), f"mon.{r}",
                        Message("paxos_commit",
                                {"version": inc.epoch,
                                 "value": blob.decode()}))
                except (ConnectionError, OSError):
                    pass

    # -- dispatch -----------------------------------------------------------
    async def _dispatch(self, conn, msg: Message) -> None:
        handler = getattr(self, f"_h_{msg.type}", None)
        if handler is not None:
            await handler(conn, msg)

    async def _h_paxos_begin(self, conn, msg) -> None:
        version = msg.data["version"]
        blob = msg.data["value"].encode()
        # peon: accept if it extends our log AND comes from the current
        # term (a deposed leader's in-flight begin must not be accepted
        # into the new leader's quorum)
        e = msg.data.get("e")
        if e is not None and e != self.election_epoch:
            return
        if version == self.store.last_committed() + 1:
            self.store.put_kv(f"pending_{version}", blob)
            # record the BALLOT (term) with the acceptance: collect
            # picks the highest-ballot value among competing pendings
            self.store.put_kv(f"pending_e_{version}",
                              str(e if e is not None
                                  else self.election_epoch).encode())
            await conn.send(Message("paxos_accept", {"version": version,
                                                     "rank": self.rank}))

    async def _h_paxos_accept(self, conn, msg) -> None:
        version = msg.data["version"]
        acc = self.accepts.get(version)
        if acc is None:
            return
        acc.add(msg.data["rank"])
        if len(acc) >= self._majority():
            fut = self._commit_waiters.pop(version, None)
            if fut and not fut.done():
                fut.set_result(True)

    async def _h_paxos_commit(self, conn, msg) -> None:
        version = msg.data["version"]
        # commit messages may carry the value (collect catch-up path);
        # otherwise it was stashed at begin time
        if "value" in msg.data:
            blob = msg.data["value"].encode()
        else:
            blob = self.store.get_kv(f"pending_{version}")
        if blob is not None and version == self.store.last_committed() + 1:
            self._commit_local(version, blob)

    async def _h_mon_probe(self, conn, msg) -> None:
        # discovery only: quorum membership comes from ELECTIONS, never
        # from a probe (a stale or partitioned mon must not inject
        # itself into an active quorum)
        await conn.send(Message("mon_probe_ack",
                                {"rank": self.rank,
                                 "election_epoch": self.election_epoch,
                                 "leader": self.leader}))

    async def _h_mon_probe_ack(self, conn, msg) -> None:
        pass

    # -- osd lifecycle ------------------------------------------------------
    async def _h_osd_boot(self, conn, msg) -> None:
        """OSD announces itself: {uuid, addr, host, osd_id?}.

        Identity (uuid->id) and topology (id->host) come from the
        replicated MAP, so any elected leader resolves reboots
        identically -- never from a single mon's in-memory registry.

        Serialized: id assignment reads ``osdmap.max_osd`` and the
        commit that bumps it happens inside ``propose`` -- two fresh
        OSDs booting concurrently (the cluster harness boots in
        batches) would otherwise both read the same ``max_osd`` and
        claim the same id."""
        async with self._boot_lock:
            await self._h_osd_boot_locked(conn, msg)

    async def _h_osd_boot_locked(self, conn, msg) -> None:
        uuid = msg.data["uuid"]
        host = msg.data.get("host", "host0")
        addr = msg.data["addr"]
        osd_id = msg.data.get("osd_id")
        if osd_id is None:
            for o, info in self.osdmap.osds.items():
                if info.uuid == uuid:
                    osd_id = o
                    break
        if osd_id is None:
            osd_id = self.osdmap.max_osd
        inc = Incremental(epoch=0)
        inc.new_up[osd_id] = list(addr)
        inc.new_in.append(osd_id)
        inc.new_weights[osd_id] = 0x10000
        inc.new_uuids[osd_id] = uuid
        inc.new_hosts[osd_id] = host
        inc.new_max_osd = max(self.osdmap.max_osd, osd_id + 1)
        inc.new_crush = self._build_crush_dict(extra_osd=(osd_id, host))
        await self.propose(inc)
        await conn.send(Message(
            "osd_boot_ack",
            {"osd_id": osd_id, "epoch": self.osdmap.epoch,
             "monmap": [list(a) for a in self.peer_addrs
                        if a is not None]}))

    def _build_crush_dict(self, extra_osd=None) -> dict:
        """Rebuild the CRUSH map from the osd->host registry.

        Two-level straw2 hierarchy root->host->osd with rule 0 replicated
        and rule 1 erasure (chooseleaf over hosts when >1 host, else
        direct osd choose) -- the default map OSDMonitor builds as OSDs
        register.
        """
        hosts: dict[str, list[int]] = defaultdict(list)
        for osd, info in self.osdmap.osds.items():
            if info.host:
                hosts[info.host].append(osd)
        if extra_osd is not None:
            osd, host = extra_osd
            if osd not in hosts[host]:
                hosts[host].append(osd)
        cm = CrushMap()
        host_ids = []
        for i, hname in enumerate(sorted(hosts)):
            hid = -(2 + i)
            osds = sorted(hosts[hname])
            cm.add_bucket(
                Bucket(id=hid, type=1, alg=CRUSH_BUCKET_STRAW2, items=osds,
                       item_weights=[0x10000] * len(osds)), hname)
            host_ids.append(hid)
        cm.add_bucket(
            Bucket(id=-1, type=10, alg=CRUSH_BUCKET_STRAW2, items=host_ids,
                   item_weights=[0x10000 * len(hosts[h])
                                 for h in sorted(hosts)]), "default")
        multi_host = len(host_ids) > 1
        cm.add_rule(replicated_rule(0, -1, choose_type=1 if multi_host else 0,
                                    leaf=multi_host))
        cm.add_rule(erasure_rule(1, -1, choose_type=1 if multi_host else 0,
                                 leaf=multi_host))
        return crush_to_dict(cm)

    async def _h_osd_failure(self, conn, msg) -> None:
        """Failure report; mark down once enough distinct reporters agree."""
        target = msg.data["target"]
        reporter = msg.data.get("reporter") or msg.from_name
        if not self.is_leader:
            if self.leader is not None:
                await self._send_mon(self.leader, Message(
                    "osd_failure", {"target": target,
                                    "reporter": reporter}))
            return
        if not self.osdmap.is_up(target):
            return
        self.failure_reports[target].add(reporter)
        n_up = sum(1 for o in self.osdmap.osds.values() if o.up)
        need = min(self.config["mon_osd_min_down_reporters"],
                   max(1, n_up - 1))
        if len(self.failure_reports[target]) >= need:
            inc = Incremental(epoch=0)
            inc.new_down.append(target)
            # the mark-down rides with its cluster-log entry in ONE
            # commit (LogMonitor entries share the map's paxos)
            inc.service_kv = {"log": self.services.log_entry(
                "WRN", f"osd.{target} marked down after "
                       f"{len(self.failure_reports[target])} reports")}
            self.failure_reports.pop(target, None)
            self._down_since[target] = time.monotonic()
            await self.propose(inc)

    async def _h_osd_alive(self, conn, msg) -> None:
        """MOSDAlive: clears pending failure reports and, when the OSD
        asks (want_up_thru), bumps its up_thru in the map so peering
        can prove the new interval went active (OSDMonitor::
        prepare_alive -- up_thru is the prior-interval-liveness fact
        past_intervals pruning depends on)."""
        osd = msg.data["osd_id"]
        self.failure_reports.pop(osd, None)
        want = int(msg.data.get("want_up_thru", 0))
        if want and not self.is_leader and self.leader is not None:
            # peon: forward to the leader (as _h_osd_failure does) and
            # ROUTE THE REPLY BACK -- without the relay the OSD whose
            # mon session landed here would never see osd_alive_reply
            # and peering would stall on the request timeout
            tid = f"alivefwd-{self.rank}-{time.monotonic_ns()}"
            self._fwd_register(tid, conn, "osd_alive_reply")
            await self._send_mon(self.leader, Message(
                "osd_alive", {**msg.data,
                              "fwd_tids": msg.data.get("fwd_tids", [])
                              + [tid]}))
            return
        if want and self.is_leader and self.osdmap.is_up(osd):
            if self.osdmap.get_up_thru(osd) < want:
                await self._up_thru_batched(osd)
            await conn.send(Message(
                "osd_alive_reply",
                {"osd_id": osd, "up_thru": self.osdmap.get_up_thru(osd),
                 "epoch": self.osdmap.epoch,
                 **({"fwd_tids": msg.data["fwd_tids"]}
                    if "fwd_tids" in msg.data else {})}))

    async def _up_thru_batched(self, osd: int) -> None:
        """Coalesce up_thru bumps into one proposal per window.

        A pool create on a big cluster makes EVERY new PG's primary
        request up_thru within milliseconds; one paxos epoch (and one
        map-delta broadcast to every subscriber) per request is an
        epoch storm -- hundreds of epochs x every OSD applying each.
        OSDMonitor batches the same way via pending_inc: requests
        arriving within mon_up_thru_batch_window commit as ONE epoch.
        """
        self._pending_up_thru.add(osd)
        if self._up_thru_flush is None or self._up_thru_flush.done():
            self._up_thru_flush = asyncio.get_event_loop() \
                .create_future()
            self._up_thru_task = asyncio.ensure_future(
                self._flush_up_thru(self._up_thru_flush))
        await self._up_thru_flush

    async def _flush_up_thru(self, fut: asyncio.Future) -> None:
        try:
            await asyncio.sleep(float(self.config.get(
                "mon_up_thru_batch_window", 0.05)))
            batch, self._pending_up_thru = self._pending_up_thru, set()
            inc = Incremental(epoch=0)
            for o in batch:
                if self.osdmap.is_up(o):
                    inc.new_up_thru[o] = self.osdmap.epoch
            if inc.new_up_thru:
                await self.propose(inc)
        finally:
            if not fut.done():
                fut.set_result(None)

    async def _h_osd_alive_reply(self, conn, msg) -> None:
        # mon side: a forwarded alive's reply coming back from the
        # leader; relay to the waiting OSD connection
        await self._auth_relay_reply(msg)

    # -- subscriptions ------------------------------------------------------
    async def _h_osd_pg_temp(self, conn, msg) -> None:
        """An OSD requests an acting-set override for a pg (MOSDPGTemp:
        the gapped CRUSH primary hands serving to a complete peer while
        it backfills; an empty list clears the override)."""
        if not self.is_leader:
            return                  # the OSD's mon failover finds the leader
        pgid = msg.data["pgid"]
        osds = [int(o) for o in msg.data.get("osds", [])]
        if self.osdmap.pg_temp.get(pgid, []) != osds:
            inc = Incremental(epoch=0)
            inc.new_pg_temp[pgid] = osds
            await self.propose(inc)
        await conn.send(Message("osd_pg_temp_reply",
                                {"pgid": pgid,
                                 "tid": msg.data.get("tid"),
                                 "epoch": self.osdmap.epoch}))

    # -- cephx (AuthMonitor ticket service) ----------------------------------
    @property
    def cephx(self):
        from ..common.cephx import CephxAuthority
        if getattr(self, "_cephx", None) is None:
            self._cephx = CephxAuthority(
                ttl=float(self.config.get("auth_service_ticket_ttl",
                                          3600.0)),
                ticket_ttl=float(self.config.get("auth_ticket_ttl",
                                                 600.0)))
            # replicated rotating keys (peons validate/restore from
            # the paxos log via services.apply)
            for svc, d in getattr(self.services, "cephx_keys",
                                  {}).items():
                from ..common.cephx import RotatingKeys
                self._cephx.rotating[svc] = RotatingKeys.from_dict(
                    d, self._cephx.ttl)
        return self._cephx

    async def _persist_rotating(self, service: str) -> None:
        rk = self.cephx.rotating[service]
        await self.propose_service_kv(
            "cephx", {service: json.dumps(rk.to_dict())})

    async def _auth_forward(self, conn, msg, reply_type: str) -> None:
        """Relay an auth request to the leader and route the reply
        back to the original requester: only the LEADER may create or
        rotate service keys (it alone persists them through paxos); a
        peon minting keys locally would issue tickets no service can
        validate (round-4 advisor finding).  Forwarding pushes onto a
        fwd_tids STACK so a stale-leadership re-forward chain still
        routes the reply hop by hop back to the origin."""
        if self.leader is None or self.peer_addrs[self.leader] is None:
            await conn.send(Message(
                reply_type, {"err": "no quorum leader",
                             **({"tid": msg.data["tid"]}
                                if "tid" in msg.data else {})}))
            return
        tid = f"authfwd-{self.rank}-{time.monotonic_ns()}"
        self._fwd_register(tid, conn, reply_type)
        await self._send_mon(self.leader, Message(
            msg.type, {**msg.data,
                       "fwd_tids": msg.data.get("fwd_tids", [])
                       + [tid]}))

    def _fwd_register(self, tid: str, conn, reply_type: str) -> None:
        """Track a forwarded request; sweep entries the leader never
        answered (e.g. it crashed) so dead Connections don't pin."""
        fwd = getattr(self, "_auth_fwd", None)
        if fwd is None:
            fwd = self._auth_fwd = {}
        now = time.monotonic()
        for k in [k for k, (_, _, dl) in fwd.items() if dl < now]:
            del fwd[k]
        fwd[tid] = (conn, reply_type, now + 30.0)

    async def _h_auth_ticket_reply(self, conn, msg) -> None:
        await self._auth_relay_reply(msg)

    async def _h_auth_rotating_reply(self, conn, msg) -> None:
        await self._auth_relay_reply(msg)

    async def _auth_relay_reply(self, msg) -> None:
        tids = list(msg.data.get("fwd_tids", []))
        if not tids:
            return
        ent = getattr(self, "_auth_fwd", {}).pop(tids[-1], None)
        if ent is not None:
            c, reply_type, _ = ent
            rest = tids[:-1]
            await c.send(Message(
                reply_type,
                {**{k: v for k, v in msg.data.items()
                    if k != "fwd_tids"},
                 **({"fwd_tids": rest} if rest else {})}))

    async def _h_auth_get_ticket(self, conn, msg) -> None:
        """CephxServiceHandler: a client proves its entity key and
        receives a session ticket for a service."""
        from ..common.cephx import CephxError
        if not self.is_leader:
            await self._auth_forward(conn, msg, "auth_ticket_reply")
            return
        d = msg.data
        entity = d["entity"]
        rec = self.services.auth_db.get(entity)
        extra = {k: d[k] for k in ("fwd_tids", "tid") if k in d}
        try:
            if rec is None:
                raise CephxError(f"unknown entity {entity}")
            self.cephx.verify_entity_proof(rec["key"], d["nonce"],
                                           d["proof"])
            before = self.cephx.rotating.get(d["service"])
            gen_before = before.gen if before else 0
            pkg = self.cephx.issue_ticket(entity, rec["key"],
                                          d["service"])
            if self.cephx.rotating[d["service"]].gen != gen_before:
                await self._persist_rotating(d["service"])
            await conn.send(Message("auth_ticket_reply",
                                    {**pkg, **extra}))
        except CephxError as e:
            await conn.send(Message("auth_ticket_reply",
                                    {"err": str(e), **extra}))

    async def _h_auth_rotating(self, conn, msg) -> None:
        """A service daemon fetches its rotating validation keys,
        proving its own entity key; keys ship sealed under it."""
        from ..common.cephx import CephxError, seal
        if not self.is_leader:
            await self._auth_forward(conn, msg, "auth_rotating_reply")
            return
        d = msg.data
        entity = d["entity"]
        rec = self.services.auth_db.get(entity)
        extra = {k: d[k] for k in ("fwd_tids", "tid") if k in d}
        try:
            if rec is None:
                raise CephxError(f"unknown entity {entity}")
            if not entity.startswith(f"{d['service']}."):
                raise CephxError(
                    f"{entity} may not read {d['service']} keys")
            self.cephx.verify_entity_proof(rec["key"], d["nonce"],
                                           d["proof"])
            before = self.cephx.rotating.get(d["service"])
            gen_before = before.gen if before else 0
            rk = self.cephx.service_keys(d["service"])
            if rk.gen != gen_before:
                await self._persist_rotating(d["service"])
            # the reply must seal with the key the client just
            # proved with; a rotation landing during the persist must
            # not swap it mid-exchange (clients re-auth on failure)
            # lint: disable=await-invalidates-snapshot -- proof-bound key
            blob = seal(bytes.fromhex(rec["key"]), rk.to_dict())
            await conn.send(Message("auth_rotating_reply",
                                    {"sealed": blob, **extra}))
        except CephxError as e:
            await conn.send(Message("auth_rotating_reply",
                                    {"err": str(e), **extra}))

    # -- MDSMonitor (FSMap) --------------------------------------------------
    MDS_BEACON_GRACE = 8.0

    async def _h_mds_beacon(self, conn, msg) -> None:
        """MMDSBeacon: mon-owned MDS membership (MDSMonitor::
        preprocess_beacon).  The leader assigns the active rank and
        promotes a standby when the active's beacons go silent past
        the grace; every change bumps the FSMap epoch through paxos."""
        name = msg.data["name"]
        addr = tuple(msg.data["addr"])
        if not self.is_leader:
            if self.leader is not None:
                await self._send_mon(self.leader, Message(
                    "mds_beacon", dict(msg.data)))
            # the peon answers from its REPLICATED fsmap: the leader's
            # assignment reaches the mds even when only a peon is
            # reachable (the forwarded beacon keeps liveness flowing)
            fsm = self.services.fsmap
            you = ("active" if fsm.get("active")
                   and fsm["active"]["name"] == name else "standby")
            await conn.send(Message("mds_beacon_ack",
                                    {"fsmap": fsm, "you": you}))
            return
        now = time.monotonic()
        beats = getattr(self, "mds_last_beacon", None)
        if beats is None:
            beats = self.mds_last_beacon = {}
        beats[name] = now
        fsmap = self.services.fsmap
        active = fsmap.get("active")
        changed = False
        new = {"epoch": fsmap.get("epoch", 0),
               "active": dict(active) if active else None,
               "standbys": [dict(s) for s in fsmap.get("standbys", [])]}
        if new["active"] and new["active"]["name"] == name:
            if tuple(new["active"]["addr"]) != addr:
                new["active"]["addr"] = list(addr)
                changed = True
        else:
            sb = {s["name"]: s for s in new["standbys"]}
            if name not in sb or tuple(sb[name]["addr"]) != addr:
                sb[name] = {"name": name, "addr": list(addr)}
                new["standbys"] = list(sb.values())
                changed = True
        # failover: the active's beacons lapsed -> promote a live
        # standby (MDSMonitor::tick fail_mds_gid path)
        act = new["active"]
        if act is not None and act["name"] != name:
            # a fresh leader has an empty beacon table: grace is
            # measured from FIRST observation, never from epoch zero
            last = beats.setdefault(act["name"], now)
            if now - last > self.MDS_BEACON_GRACE:
                act = None
        if act is None:
            live = [s for s in new["standbys"]
                    if now - beats.get(s["name"], 0.0)
                    < self.MDS_BEACON_GRACE]
            if live:
                promoted = live[0]
                new["standbys"] = [s for s in new["standbys"]
                                   if s["name"] != promoted["name"]]
                # a deposed daemon rejoins as a standby on its next
                # beacon (the registration branch above)
                new["active"] = promoted
                changed = True
            else:
                if new["active"] is not None:
                    new["active"] = None
                    changed = True
        if changed:
            new["epoch"] = new.get("epoch", 0) + 1
            await self.propose_service_kv("fsmap", {"map": new})
        fsmap = self.services.fsmap
        you = ("active" if fsmap.get("active")
               and fsmap["active"]["name"] == name else "standby")
        await conn.send(Message("mds_beacon_ack",
                                {"fsmap": fsmap, "you": you}))

    async def _h_sub_fsmap(self, conn, msg) -> None:
        # subscription reply for MDS clients that subscribe over the
        # wire; the in-tree client polls `fs dump` via mon_command
        # instead, so no dispatcher matches the type yet
        # lint: disable=wire-safety -- no in-tree fsmap subscriber
        await conn.send(Message("fsmap",
                                {"fsmap": self.services.fsmap}))

    async def _h_osd_slow_ops(self, conn, msg) -> None:
        """An OSD complains about ops in flight past the complaint
        threshold (OSD::get_health_metrics -> mon SLOW_OPS health +
        cluster log)."""
        osd = msg.data["osd_id"]
        if not self.is_leader and self.leader is not None:
            # health answers come from the leader: forward like
            # _h_osd_failure so the report lands where it is read
            await self._send_mon(self.leader, Message(
                "osd_slow_ops", dict(msg.data)))
            return
        reports = getattr(self, "slow_ops_reports", None)
        if reports is None:
            reports = self.slow_ops_reports = {}
        reports[osd] = {"count": int(msg.data.get("count", 0)),
                        "oldest_age": float(msg.data.get(
                            "oldest_age", 0.0)),
                        "stamp": time.monotonic()}
        if self.is_leader and msg.data.get("log"):
            await self.propose_service_kv("log", self.services.log_entry(
                "WRN", f"osd.{osd} has {msg.data['count']} slow ops, "
                       f"oldest {msg.data.get('oldest_age', 0):.0f}s",
                who=f"osd.{osd}"))

    MGR_BEACON_GRACE = 8.0

    async def _h_mgr_beacon(self, conn, msg) -> None:
        """MgrMonitor::prepare_beacon: the LEADER owns the replicated
        MgrMap -- first mgr to beacon becomes active, later ones stand
        by, and a lapsed active is deposed in _tick with a standby
        promoted.  Peons forward so the map is mon-agnostic."""
        name = msg.data.get("name", "")
        addr = list(msg.data["addr"])
        if not self.is_leader:
            if self.leader is not None:
                await self._send_mon(self.leader, Message(
                    "mgr_beacon", dict(msg.data)))
            return
        beats = getattr(self, "mgr_last_beacon", None)
        if beats is None:
            beats = self.mgr_last_beacon = {}
        beats[name] = time.monotonic()
        m = dict(self.services.mgrmap)
        changed = False
        if m.get("active") is None:
            m.update({"active": name, "active_addr": addr,
                      "epoch": m["epoch"] + 1,
                      "standbys": [x for x in m.get("standbys", [])
                                   if x["name"] != name]})
            changed = True
        elif m["active"] == name:
            if m.get("active_addr") != addr:
                m.update({"active_addr": addr,
                          "epoch": m["epoch"] + 1})
                changed = True
        else:
            stand = list(m.get("standbys", []))
            cur = next((x for x in stand if x["name"] == name), None)
            if cur is None:
                m["standbys"] = stand + [{"name": name, "addr": addr}]
                m["epoch"] += 1
                changed = True
            elif cur["addr"] != addr:
                # a restarted standby's NEW address must be the one a
                # later failover promotes
                cur["addr"] = addr
                m["standbys"] = stand
                m["epoch"] += 1
                changed = True
        if changed:
            await self.propose_service_kv(
                "mgrmap", {"map": json.dumps(m)})
            await self._publish_mgr_map()

    async def _publish_mgr_map(self) -> None:
        m = self.services.mgrmap
        if not m.get("active"):
            return
        payload = {"name": m["active"], "addr": m["active_addr"]}
        for name, sub in list(self.subscribers.items()):
            try:
                await sub.send(Message("mgr_map", payload))
            except (ConnectionError, OSError):
                self.subscribers.pop(name, None)

    async def _h_sub_osdmap(self, conn, msg) -> None:
        self.subscribers[msg.from_name] = conn
        await conn.send(Message("osdmap_full",
                                {"map": self.osdmap.to_dict()}))
        cfg = self.services.config_for(msg.from_name)
        if cfg:                  # central config lands at subscription
            await conn.send(Message("config_update", {"config": cfg}))
        mgrm = self.services.mgrmap
        if mgrm.get("active"):             # late joiners learn the mgr
            await conn.send(Message("mgr_map",
                                    {"name": mgrm["active"],
                                     "addr": mgrm["active_addr"]}))

    async def _h_get_osdmap(self, conn, msg) -> None:
        # a delta fetch keeps the caller on the broadcast feed: the
        # refresh path must survive a mon restart that dropped the
        # subscriber table
        self.subscribers[msg.from_name] = conn
        since = msg.data.get("since", 0)
        incs = [self.incrementals[e].to_dict()
                for e in range(since + 1, self.osdmap.epoch + 1)
                if e in self.incrementals]
        if len(incs) == self.osdmap.epoch - since:
            await conn.send(Message("osdmap_incs", {"incs": incs}))
        else:
            await conn.send(Message("osdmap_full",
                                    {"map": self.osdmap.to_dict()}))

    # -- commands -----------------------------------------------------------
    async def _h_mon_command(self, conn, msg) -> None:
        cmd = msg.data.get("cmd", "")
        args = msg.data.get("args", {})
        if not self.is_leader and not msg.data.get("fwd"):
            # peon: relay mutating traffic to the leader (the reference
            # forwards with MForward); the reply routes back here
            data = await self._forward_to_leader(msg)
            data["tid"] = msg.data.get("tid")
            await conn.send(Message("mon_command_reply", data))
            return
        try:
            result = await self.handle_command(cmd, args)
            await conn.send(Message("mon_command_reply",
                                    {"ok": True, "result": result,
                                     "tid": msg.data.get("tid")}))
        except Exception as e:  # command errors return to caller
            await conn.send(Message("mon_command_reply",
                                    {"ok": False, "error": str(e),
                                     "tid": msg.data.get("tid")}))

    async def _forward_to_leader(self, msg) -> dict:
        if self.leader is None or self.peer_addrs[self.leader] is None:
            return {"ok": False, "error": "no quorum leader"}
        relay_tid = f"fwd-{self.rank}-{time.monotonic_ns()}"
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._fwd_waiters = getattr(self, "_fwd_waiters", {})
        self._fwd_waiters[relay_tid] = fut
        try:
            await self._send_mon(self.leader, Message(
                "mon_command", {"cmd": msg.data.get("cmd", ""),
                                "args": msg.data.get("args", {}),
                                "tid": relay_tid, "fwd": True}))
            return await asyncio.wait_for(fut, 10)
        except asyncio.TimeoutError:
            return {"ok": False, "error": "leader did not answer"}
        finally:
            self._fwd_waiters.pop(relay_tid, None)

    async def _h_mon_command_reply(self, conn, msg) -> None:
        fut = getattr(self, "_fwd_waiters", {}).pop(
            msg.data.get("tid"), None)
        if fut is not None and not fut.done():
            fut.set_result({k: v for k, v in msg.data.items()
                            if k != "tid"})

    async def propose_service_kv(self, service: str, kv: dict) -> None:
        """Commit a non-osdmap service mutation through paxos."""
        inc = Incremental(epoch=0)
        inc.service_kv = {service: kv}
        await self.propose(inc)

    async def push_config(self) -> None:
        """Push effective config to subscribed daemons (the mon sends
        MConfig on changes; daemons apply via ConfigProxy observers)."""
        for name, conn in list(self.subscribers.items()):
            try:
                await conn.send(Message(
                    "config_update",
                    {"config": self.services.config_for(name)}))
            except (ConnectionError, OSError):
                pass

    async def handle_command(self, cmd: str, args: dict):
        from .services import UnknownCommand
        try:
            return await self.services.handle_command(cmd, args)
        except UnknownCommand:
            pass                 # not a service command; fall through
        if cmd == "osd blocklist":
            # fence a client INSTANCE ("name:incarnation") at the data
            # path: every OSD refuses its ops once the map propagates
            # (OSDMonitor.cc blocklist; fences lease-lapsed cephfs
            # clients and deposed rbd lock holders)
            iid = args["id"]
            inc = Incremental(epoch=0)
            if args.get("rm"):
                inc.old_blocklist.append(iid)
            else:
                until = time.time() + float(args.get("duration", 3600))
                inc.new_blocklist[iid] = until
            inc.service_kv = {"log": self.services.log_entry(
                "WRN", f"blocklist {'rm ' if args.get('rm') else ''}"
                       f"{iid}")}
            await self.propose(inc)
            return {"id": iid, "epoch": self.osdmap.epoch}
        if cmd == "osd blocklist ls":
            now = time.time()
            return {iid: exp for iid, exp in
                    self.osdmap.blocklist.items() if exp > now}
        if cmd == "osd pool create":
            return await self._cmd_pool_create(args)
        if cmd == "osd pool rm":
            return await self._cmd_pool_rm(args)
        if cmd == "osd pool ls":
            return sorted(self.osdmap.pool_names)
        if cmd == "osd erasure-code-profile set":
            name = args["name"]
            profile = dict(args.get("profile", {}))
            # validate by instantiating the plugin
            plugin = profile.get("plugin", "tpu")
            codec = ec_registry().factory(
                plugin, {k: v for k, v in profile.items()
                         if k != "plugin"})
            if "stripe_unit" in profile:
                # prepare_pool_stripe_width analog (OSDMonitor.cc:7782):
                # reject unaligned/zero/garbage stripe units HERE, not
                # at first I/O on some OSD
                from ..osd.ec_util import parse_stripe_unit
                parse_stripe_unit(codec, profile["stripe_unit"])
            inc = Incremental(epoch=0)
            inc.new_ec_profiles[name] = profile
            await self.propose(inc)
            return name
        if cmd == "osd erasure-code-profile ls":
            return sorted(self.osdmap.ec_profiles)
        if cmd == "osd erasure-code-profile get":
            return self.osdmap.ec_profiles[args["name"]]
        if cmd == "osd erasure-code-profile rm":
            inc = Incremental(epoch=0)
            inc.removed_ec_profiles.append(args["name"])
            await self.propose(inc)
            return args["name"]
        if cmd == "osd out":
            inc = Incremental(epoch=0)
            inc.new_out.append(int(args["osd_id"]))
            await self.propose(inc)
            return int(args["osd_id"])
        if cmd == "osd in":
            inc = Incremental(epoch=0)
            inc.new_in.append(int(args["osd_id"]))
            await self.propose(inc)
            return int(args["osd_id"])
        if cmd == "osd reweight":
            inc = Incremental(epoch=0)
            inc.new_weights[int(args["osd_id"])] = int(args["weight"])
            await self.propose(inc)
            return True
        if cmd == "osd pool selfmanaged-snap create":
            # serialize allocation: two concurrent creates reading the
            # same snap_seq would hand out one id twice
            if not hasattr(self, "_snap_alloc_lock"):
                self._snap_alloc_lock = asyncio.Lock()
            async with self._snap_alloc_lock:
                pool = self.osdmap.get_pool_by_name(args["pool"])
                if pool is None:
                    raise ValueError(f"no pool {args['pool']}")
                snapid = pool.snap_seq + 1
                inc = Incremental(epoch=0)
                inc.new_pool_snaps[pool.pool_id] = {"snap_seq": snapid}
                await self.propose(inc)
            return snapid
        if cmd == "osd pool selfmanaged-snap rm":
            pool = self.osdmap.get_pool_by_name(args["pool"])
            if pool is None:
                raise ValueError(f"no pool {args['pool']}")
            sid = int(args["snap"])
            inc = Incremental(epoch=0)
            inc.new_pool_snaps[pool.pool_id] = {
                "snap_seq": pool.snap_seq, "removed": [sid]}
            await self.propose(inc)
            return sid
        if cmd == "osd pg-upmap-items":
            pgid = args["pgid"]
            items = [[int(a), int(b)] for a, b in args["mappings"]]
            for _, to in items:
                if not self.osdmap.exists(to):
                    raise ValueError(f"osd.{to} does not exist")
            inc = Incremental(epoch=0)
            inc.new_pg_upmap_items[pgid] = items
            await self.propose(inc)
            return pgid
        if cmd == "osd rm-pg-upmap-items":
            inc = Incremental(epoch=0)
            inc.removed_pg_upmap_items.append(args["pgid"])
            await self.propose(inc)
            return args["pgid"]
        if cmd == "osd balancer run":
            from ..mgr.balancer import balance
            res = balance(self.osdmap, max_moves=int(args.get("max", 10)))
            plans = res["plans"]
            if plans:
                from ..mgr.balancer import compact_items
                inc = Incremental(epoch=0)
                for pgid, items in plans.items():
                    inc.new_pg_upmap_items[pgid] = compact_items(
                        self.osdmap.pg_upmap_items.get(pgid, []), items)
                await self.propose(inc)
            return {"moved": len(plans), "before": res["before"],
                    "after": res["after"]}
        if cmd == "osd dump":
            return self.osdmap.to_dict()
        if cmd == "osd tree":
            return self._cmd_osd_tree()
        if cmd == "status":
            n_up = sum(1 for o in self.osdmap.osds.values() if o.up)
            n_in = sum(1 for o in self.osdmap.osds.values() if o.in_cluster)
            health = self.services.health()
            return {"epoch": self.osdmap.epoch,
                    "num_osds": len(self.osdmap.osds),
                    "num_up": n_up, "num_in": n_in,
                    "pools": len(self.osdmap.pools),
                    "quorum": sorted(self.quorum),
                    "health": health["status"],
                    "checks": {k: v["summary"]
                               for k, v in health["checks"].items()}}
        raise ValueError(f"unknown command: {cmd}")

    async def _cmd_pool_create(self, args: dict):
        name = args["name"]
        if name in self.osdmap.pool_names:
            return self.osdmap.pool_names[name]
        pg_num = int(args.get("pg_num", 32))
        pool_id = max(self.osdmap.pools, default=0) + 1
        pool_type = args.get("type", "replicated")
        inc = Incremental(epoch=0)
        if pool_type == "erasure":
            profile_name = args.get("erasure_code_profile", "default")
            profile = self.osdmap.ec_profiles.get(profile_name)
            if profile is None:
                if profile_name != "default":
                    raise ValueError(f"no EC profile {profile_name}")
                profile = dict(DEFAULT_EC_PROFILE)
                inc.new_ec_profiles["default"] = profile
            # pool width comes from the PLUGIN, not k+m: layered codes
            # (lrc) add local parity chunks beyond k+m (the reference
            # sizes pools via the instantiated codec the same way,
            # OSDMonitor::get_erasure_code -> get_chunk_count)
            codec = ec_registry().factory(
                profile.get("plugin", "tpu"),
                {pk: pv for pk, pv in profile.items() if pk != "plugin"})
            if "stripe_unit" in profile:
                # pool creation is the last gate before the profile's
                # stripe geometry becomes I/O-visible
                from ..osd.ec_util import parse_stripe_unit
                parse_stripe_unit(codec, profile["stripe_unit"])
            width = codec.get_chunk_count()
            k = codec.get_data_chunk_count()
            spec = PoolSpec(pool_id=pool_id, name=name,
                            type=POOL_TYPE_ERASURE, size=width,
                            min_size=k + 1 if width - k > 1 else k,
                            pg_num=pg_num, pgp_num=pg_num, crush_rule=1,
                            erasure_code_profile=profile_name)
        else:
            spec = PoolSpec(pool_id=pool_id, name=name,
                            type=POOL_TYPE_REPLICATED,
                            size=int(args.get("size", 3)),
                            min_size=int(args.get("min_size", 2)),
                            pg_num=pg_num, pgp_num=pg_num, crush_rule=0)
        from dataclasses import asdict
        inc.new_pools[pool_id] = asdict(spec)
        await self.propose(inc)
        return pool_id

    async def _cmd_pool_rm(self, args: dict):
        name = args["name"]
        pid = self.osdmap.pool_names.get(name)
        if pid is None:
            raise ValueError(f"no pool {name}")
        inc = Incremental(epoch=0)
        inc.removed_pools.append(pid)
        await self.propose(inc)
        # pid is the id the command resolved and removed; returning
        # the captured value after the commit is the contract
        # lint: disable=await-invalidates-snapshot -- captured return value
        return pid

    def _cmd_osd_tree(self):
        tree = []
        hosts = defaultdict(list)
        for osd, info in self.osdmap.osds.items():
            if info.host:
                hosts[info.host].append(osd)
        for host in sorted(hosts):
            tree.append({"type": "host", "name": host})
            for osd in sorted(hosts[host]):
                info = self.osdmap.osds.get(osd)
                tree.append({"type": "osd", "id": osd,
                             "up": bool(info and info.up),
                             "in": bool(info and info.in_cluster),
                             "weight": info.weight if info else 0})
        return tree

    # -- ticking (down->out aging) -----------------------------------------
    async def _tick_loop(self) -> None:
        try:
            while True:
                # lease renewal must outpace lease expiry by a
                # comfortable margin (the reference renews at lease/2)
                await asyncio.sleep(min(0.5,
                                        self.config["mon_lease"] / 3))
                await self._tick()
        except asyncio.CancelledError:
            pass

    async def _tick(self) -> None:
        now = time.monotonic()
        # -- election/lease upkeep ------------------------------------------
        if self._n_mons() > 1:
            if self.leader is None:
                if now > getattr(self, "_defer_until", 0.0):
                    self.start_election()
            elif self.is_leader:
                # renew the lease; two consecutive sub-majority rounds
                # mean we lost the quorum: step down and re-elect
                if len(self._lease_acks | {self.rank}) < self._majority() \
                        and self._lease_round > 0:
                    self._lease_misses += 1
                    if self._lease_misses >= 2:
                        self.leader = None
                        self._lease_misses = 0
                        self.start_election()
                else:
                    self._lease_misses = 0
                self._lease_acks = set()
                self._lease_round = getattr(self, "_lease_round", 0) + 1
                for r in sorted(self.quorum - {self.rank}):
                    await self._send_mon(r, Message(
                        "mon_lease", {"epoch": self.election_epoch}))
            else:
                if now > self._lease_expire:
                    # leader went quiet: elect
                    self.leader = None
                    self.start_election()
        interval = self.config["mon_osd_down_out_interval"]
        to_out = [osd for osd, t in self._down_since.items()
                  if now - t > interval
                  and self.osdmap.osds.get(osd)
                  and self.osdmap.osds[osd].in_cluster
                  and not self.osdmap.osds[osd].up]
        if to_out and self.is_leader:
            inc = Incremental(epoch=0)
            inc.new_out.extend(to_out)
            for osd in to_out:
                self._down_since.pop(osd, None)
            await self.propose(inc)
        # MgrMonitor: a lapsed active mgr is deposed and a standby
        # promoted (mgr failover)
        if self.is_leader:
            m = self.services.mgrmap
            beats = getattr(self, "mgr_last_beacon", None)
            if beats is None:
                beats = self.mgr_last_beacon = {}
            act = m.get("active")
            if act and act not in beats:
                # a NEW leader has no beat record for the active: start
                # the grace clock now instead of resetting it each tick
                # (else a dead active is never deposed after a mon
                # leadership change)
                beats[act] = now
            if act and now - beats[act] > self.MGR_BEACON_GRACE:
                nm = dict(m)
                nm["epoch"] += 1
                stand = nm.get("standbys", [])
                if stand:
                    nxt = stand[0]
                    nm.update({"active": nxt["name"],
                               "active_addr": nxt["addr"],
                               "standbys": stand[1:]})
                else:
                    nm.update({"active": None, "active_addr": None})
                await self.propose_service_kv(
                    "mgrmap", {"map": json.dumps(nm)})
                await self._publish_mgr_map()
        # expired blocklist entries leave the map (OSDMonitor::tick
        # does the same sweep); without it every fence ever made rides
        # in every full map forever
        if self.is_leader:
            expired = [iid for iid, exp in self.osdmap.blocklist.items()
                       if exp <= time.time()]
            if expired:
                inc = Incremental(epoch=0)
                inc.old_blocklist.extend(expired)
                await self.propose(inc)
