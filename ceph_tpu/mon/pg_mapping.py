"""PGMapping: the epoch-memoized full-cluster placement table.

The reference never runs CRUSH per client op: OSDMapMapping
(src/osd/OSDMapMapping.h:175) holds the whole pg->osd table, recomputed
in bulk by ParallelPGMapper whenever a new map epoch lands, and every
lookup is an array read.  This module is that table for this repo: ONE
bulk recompute per OSDMap epoch -- a single VectorCrush launch over all
(pool, ps) lanes when the (map, rule) compiles for the fused path, a
batched scalar sweep otherwise -- followed by numpy-vectorized
application of the existing placement semantics (pps hashing, upmap
rewrite, nonexistent/down filtering with EC holes normalized to -1,
pg_temp overrides), so every cached entry is identical to what
``OSDMap.pg_to_up_acting`` computed per PG.

``OSDMap.pg_to_up_acting`` becomes an O(1) read of this table behind an
epoch-keyed memo (mon/osdmap.py), and ``OSD._on_map_change`` consumes
``delta(prev)`` so an epoch bump touches only the PGs whose up/acting
actually changed.  Placement cost then scales with map CHURN, not op
count -- the same shift the codec batching made for EC math.
"""

from __future__ import annotations

import os
import time

import numpy as np

from ..crush import crush_do_rule
from ..crush.hashes import crush_hash32_2_np
from ..crush.types import CRUSH_ITEM_NONE

# below this many lanes (sum of pg_num over same-rule pools) the fused
# JAX path is not worth its trace/compile cost -- the scalar sweep wins
# on the small maps unit tests and the chaos smoke run.  Large maps
# (the bench, real clusters) clear it easily.
FUSED_MIN_LANES = int(os.environ.get("CEPH_TPU_PLACEMENT_FUSED_MIN",
                                     "2048"))


# structurally-identical maps share ONE compiled instance process-wide
# (bounded: stale structures age out).  An in-process cluster runs one
# CrushMap object PER DAEMON, all deserialized from the same mon map;
# without structural sharing each of 64 OSDs would pay its own
# multi-second jit compile for byte-identical hierarchies.
_VC_SHARED: dict[tuple, object] = {}
_VC_SHARED_MAX = 8


def _crush_digest(crush_map) -> str:
    """Structural fingerprint of a CrushMap (buckets/rules/tunables/
    choose_args), cached on the object (maps are replaced wholesale on
    change, never mutated in place)."""
    dig = crush_map.__dict__.get("_structure_digest")
    if dig is None:
        import hashlib
        import json as _json
        from .osdmap import crush_to_dict
        # choose_args are baked into the compiled instance
        # (CompiledMap.from_map falls back to map.choose_args) but are
        # NOT part of crush_to_dict -- digest them explicitly
        blob = _json.dumps(
            {"crush": crush_to_dict(crush_map),
             "choose_args": getattr(crush_map, "choose_args", None)},
            sort_keys=True, default=str)
        dig = hashlib.sha256(blob.encode()).hexdigest()
        crush_map.__dict__["_structure_digest"] = dig
    return dig


def _vector_crush_for(crush_map, ruleno: int):
    """Compiled VectorCrush for a (map, rule), shared two ways: per
    CrushMap object (the jit stays warm across weight-only epochs),
    and across structurally-identical maps process-wide (every daemon
    of an in-process cluster deserializes its own copy of the same
    map; one compile serves them all)."""
    cache = crush_map.__dict__.setdefault("_vc_cache", {})
    ca = getattr(crush_map, "choose_args", None)
    key = (ruleno, id(ca) if ca else None)
    if key not in cache:
        shared_key = (_crush_digest(crush_map), ruleno)
        vc = _VC_SHARED.get(shared_key)
        if vc is None:
            from ..crush.vectorized import VectorCrush
            vc = VectorCrush(crush_map, ruleno)
            while len(_VC_SHARED) >= _VC_SHARED_MAX:
                _VC_SHARED.pop(next(iter(_VC_SHARED)))
            _VC_SHARED[shared_key] = vc
        cache[key] = vc
    return cache[key]


def bulk_crush(crush_map, ruleno: int, xs, numrep: int, weights,
               fused: str = "auto",
               min_lanes: int | None = None) -> tuple[np.ndarray, bool]:
    """Map every x in ``xs`` through one rule: (rows, used_fused).

    rows is (len(xs), numrep) int64 with CRUSH_ITEM_NONE holes -- the
    raw result vector, before any OSDMap-level filtering.  ``fused``:
    'auto' tries the vectorized engine when the lane count clears
    ``min_lanes`` and the (map, rule) shape compiles; 'always' forces
    it (raising if the shape cannot compile); 'never' is the pure
    scalar sweep.  crushtool --test and the placement cache both ride
    this helper so offline what-ifs exercise the exact production path.
    """
    xs = np.asarray(xs, dtype=np.int64)
    lanes = int(xs.shape[0])
    threshold = FUSED_MIN_LANES if min_lanes is None else min_lanes
    # a WARM VectorCrush for this (map, rule) makes the fused launch
    # all but free -- the threshold only guards the one-time
    # trace/compile cost, so it does not apply once that cost is sunk
    # (the epoch-recompute path hits the same map object dozens of
    # times during peering/recovery churn on a big cluster)
    ca = getattr(crush_map, "choose_args", None)
    warm = ((ruleno, id(ca) if ca else None)
            in crush_map.__dict__.get("_vc_cache", {})
            or (_crush_digest(crush_map), ruleno) in _VC_SHARED)
    if fused == "always" or (fused == "auto"
                             and (warm or lanes >= threshold)):
        try:
            vc = _vector_crush_for(crush_map, ruleno)
            rows = np.asarray(vc.map_pgs(xs, numrep, list(weights)),
                              dtype=np.int64)
            return rows, True
        except ValueError:
            if fused == "always":
                raise
    rows = np.full((lanes, numrep), CRUSH_ITEM_NONE, dtype=np.int64)
    for i, x in enumerate(xs):
        got = crush_do_rule(crush_map, ruleno, int(x), numrep,
                            weights)[:numrep]
        rows[i, :len(got)] = got
    return rows, False


def pool_pps(pool) -> np.ndarray:
    """pps seed per raw pg of a pool, vectorized (pg_pool_t::
    raw_pg_to_pps for ps in [0, pg_num))."""
    pgs = np.arange(pool.pg_num, dtype=np.int64)
    stable = np.where((pgs & pool.pgp_num_mask) < pool.pgp_num,
                      pgs & pool.pgp_num_mask,
                      pgs & (pool.pgp_num_mask >> 1))
    if pool.flags & 1:      # FLAG_HASHPSPOOL
        return crush_hash32_2_np(
            stable.astype(np.uint32),
            np.full(pool.pg_num, pool.pool_id,
                    dtype=np.int64).astype(np.uint32)).astype(np.int64)
    return stable + pool.pool_id


class PGMapping:
    """The full-cluster placement table for one OSDMap epoch.

    ``up`` and ``acting`` per (pool, raw pg), entry-identical to the
    per-PG ``pg_to_up_acting`` result.  Instances are immutable
    snapshots: a new epoch builds a new PGMapping (OSDMap memoizes one
    per mutation generation and hands the previous one to ``delta``)."""

    def __init__(self, epoch: int) -> None:
        self.epoch = epoch
        self.fused_pools = 0
        self.scalar_pools = 0
        # pool_id -> list[list[int]] indexed by raw pg
        self._up: dict[int, list[list[int]]] = {}
        self._acting: dict[int, list[list[int]]] = {}
        self._pg_num: dict[int, int] = {}
        self._pg_num_mask: dict[int, int] = {}

    # -- construction -------------------------------------------------------
    @classmethod
    def build(cls, osdmap, perf=None, fused: str = "auto",
              min_lanes: int | None = None) -> "PGMapping":
        t0 = time.perf_counter()
        pm = cls(osdmap.epoch)
        weights = osdmap.osd_weights()
        # live[o] <=> the post-CRUSH filter keeps osd o (exists + up)
        n = len(weights) + 1
        live = np.zeros(n, dtype=bool)
        for o, info in osdmap.osds.items():
            if info.up and o < n:
                live[o] = True
        for pool_id, pool in osdmap.pools.items():
            pps = pool_pps(pool)
            rows, used_fused = bulk_crush(
                osdmap.crush, pool.crush_rule, pps, pool.size, weights,
                fused=fused, min_lanes=min_lanes)
            if used_fused:
                pm.fused_pools += 1
            else:
                pm.scalar_pools += 1
            pm._ingest_pool(osdmap, pool_id, pool, rows, live)
        dt = time.perf_counter() - t0
        if perf is not None:
            perf.inc("bulk_recomputes")
            perf.inc("fused_pools", pm.fused_pools)
            perf.inc("scalar_pools", pm.scalar_pools)
            perf.tinc("recompute", dt)
            total = sum(pm._pg_num.values())
            if dt > 0:
                perf.set_gauge("recompute_pgs_per_s",
                               round(total / dt, 1))
        return pm

    def _ingest_pool(self, osdmap, pool_id: int, pool,
                     rows: np.ndarray, live: np.ndarray) -> None:
        """Raw CRUSH rows -> up/acting lists with the full OSDMap
        semantics applied in bulk (OSDMap.cc _apply_upmap,
        _raw_to_up_osds, pg_temp), vectorized where the data is dense
        and per-entry only for the sparse override dicts."""
        n_live = live.shape[0]
        # upmap rewrite first (it edits the RAW result): sparse dict,
        # touch only the pgs that carry items
        prefix = f"{pool_id}."
        upmapped = [k for k in osdmap.pg_upmap_items
                    if k.startswith(prefix)]
        for pgid in upmapped:
            try:
                pg = int(pgid.split(".", 1)[1], 16)
            except ValueError:
                continue
            if 0 <= pg < pool.pg_num:
                rows[pg] = osdmap._apply_upmap(
                    pgid, [int(o) for o in rows[pg]])
        # live filter, holes normalized to -1 (EC shard ids ride the
        # position, so indep pools keep holes; replicated compact)
        valid = ((rows != CRUSH_ITEM_NONE) & (rows >= 0)
                 & (rows < n_live))
        ok = np.zeros_like(valid)
        ok[valid] = live[rows[valid]]
        if pool.can_shift_osds():
            up = [[int(o) for o in row[okr]]
                  for row, okr in zip(rows, ok)]
        else:
            filt = np.where(ok, rows, -1)
            up = [[int(o) for o in row] for row in filt]
        acting = list(up)           # shared rows until pg_temp overrides
        for pgid, temp in osdmap.pg_temp.items():
            if not pgid.startswith(prefix):
                continue
            try:
                pg = int(pgid.split(".", 1)[1], 16)
            except ValueError:
                continue
            if not (0 <= pg < pool.pg_num) or not temp:
                continue
            act = [int(o) if (o != CRUSH_ITEM_NONE and o >= 0
                              and o < n_live and live[o]) else -1
                   for o in temp]
            if pool.can_shift_osds():
                act = [o for o in act if o >= 0]
            acting[pg] = act if act else up[pg]
        self._up[pool_id] = up
        self._acting[pool_id] = acting
        self._pg_num[pool_id] = pool.pg_num
        self._pg_num_mask[pool_id] = pool.pg_num_mask

    # -- queries ------------------------------------------------------------
    def raw_pg(self, pool_id: int, ps: int) -> int:
        b, mask = self._pg_num[pool_id], self._pg_num_mask[pool_id]
        return ps & mask if (ps & mask) < b else ps & (mask >> 1)

    def lookup(self, pool_id: int,
               ps: int) -> tuple[list[int], list[int]]:
        """(up, acting) for a pg: one table read.  Returns fresh lists
        (callers historically mutate/keep the per-call result)."""
        pg = self.raw_pg(pool_id, ps)
        return list(self._up[pool_id][pg]), \
            list(self._acting[pool_id][pg])

    def iter_all(self):
        """Yield (pool_id, pg, up, acting) over the whole table."""
        for pool_id, ups in self._up.items():
            acts = self._acting[pool_id]
            for pg in range(len(ups)):
                yield pool_id, pg, ups[pg], acts[pg]

    def pg_count(self) -> int:
        return sum(self._pg_num.values())

    # -- deltas -------------------------------------------------------------
    def delta(self, prev: "PGMapping",
              perf=None) -> list[tuple[int, int]]:
        """(pool_id, pg) for every entry whose up OR acting differs
        from ``prev``, including pgs of pools present in only one of
        the two tables (pool create/delete, pg_num resize).  Exactly
        the brute-force entry-for-entry diff, so a map consumer can
        retarget only what moved."""
        if prev is self:
            # placement-neutral epochs (up_thru/blocklist-only) carry
            # the table object across generations: nothing moved
            return []
        changed: list[tuple[int, int]] = []
        pools = set(self._up) | set(prev._up)
        for pool_id in sorted(pools):
            cur_u = self._up.get(pool_id)
            old_u = prev._up.get(pool_id)
            if cur_u is None or old_u is None:
                src = cur_u if cur_u is not None else old_u
                changed.extend((pool_id, pg) for pg in range(len(src)))
                continue
            cur_a = self._acting[pool_id]
            old_a = prev._acting[pool_id]
            span = max(len(cur_u), len(old_u))
            for pg in range(span):
                if (pg >= len(cur_u) or pg >= len(old_u)
                        or cur_u[pg] != old_u[pg]
                        or cur_a[pg] != old_a[pg]):
                    changed.append((pool_id, pg))
        if perf is not None:
            perf.inc("delta_pgs", len(changed))
        return changed
