"""GF(2^8) table-based arithmetic (numpy, host side).

This is the scalar/host reference implementation; the TPU path in
``ceph_tpu.ops`` reformulates the same field operations as GF(2) bit-matrix
multiplications that run on the MXU.  Both must agree byte-for-byte.

The field is GF(2)[x] / (x^8 + x^4 + x^3 + x^2 + 1)  (poly 0x11d), the field
used by ISA-L erasure coding and jerasure w=8, which is what the reference's
ISA plugin drives (reference: src/erasure-code/isa/ErasureCodeIsa.cc:27,
via the isa-l submodule's ec_encode_data / gf_mul).
"""

from __future__ import annotations

import numpy as np

GF_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1
_GEN = 2  # x is a generator for 0x11d


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    v = 1
    for i in range(255):
        exp[i] = v
        log[v] = i
        v <<= 1
        if v & 0x100:
            v ^= GF_POLY
    # replicate so exp[log a + log b] never needs a mod
    for i in range(255, 512):
        exp[i] = exp[i - 255]
    return exp, log


GF_EXP, GF_LOG = _build_tables()

# full 256x256 multiplication table: GF_MUL_TABLE[a, b] = a*b
_la = GF_LOG[:, None] + GF_LOG[None, :]
GF_MUL_TABLE = GF_EXP[_la]
GF_MUL_TABLE[0, :] = 0
GF_MUL_TABLE[:, 0] = 0
del _la

GF_INV = np.zeros(256, dtype=np.uint8)
GF_INV[1:] = GF_EXP[255 - GF_LOG[1:]]


def gf_mul(a: int, b: int) -> int:
    """Scalar product in GF(2^8)."""
    return int(GF_MUL_TABLE[a & 0xFF, b & 0xFF])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    if a == 0:
        return 0
    return int(GF_EXP[(GF_LOG[a] - GF_LOG[b]) % 255])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(2^8) inverse of zero")
    return int(GF_INV[a])


def gf_pow(a: int, n: int) -> int:
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(GF_LOG[a] * n) % 255])


def gf_mul_bytes(c: int, data: np.ndarray) -> np.ndarray:
    """Multiply every byte of ``data`` by the constant ``c``."""
    data = np.asarray(data, dtype=np.uint8)
    return GF_MUL_TABLE[c][data]


def gf_matmul(mat: np.ndarray, data: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix product of an (r,k) coefficient matrix with (k,n) bytes.

    out[i, :] = XOR_j  mat[i, j] * data[j, :]

    This is exactly what ISA-L's ec_encode_data computes with its expanded
    tables (the hot loop the TPU kernels replace).
    """
    mat = np.asarray(mat, dtype=np.uint8)
    data = np.asarray(data, dtype=np.uint8)
    r, k = mat.shape
    assert data.shape[0] == k, (mat.shape, data.shape)
    out = np.zeros((r, data.shape[1]), dtype=np.uint8)
    for i in range(r):
        acc = out[i]
        for j in range(k):
            c = mat[i, j]
            if c == 0:
                continue
            elif c == 1:
                acc ^= data[j]
            else:
                acc ^= GF_MUL_TABLE[c][data[j]]
        out[i] = acc
    return out


def gf_invert_matrix(mat: np.ndarray) -> np.ndarray:
    """Invert a square GF(2^8) matrix by Gauss-Jordan with partial pivoting.

    Raises ValueError when singular (mirrors gf_invert_matrix < 0 in the
    reference's decode path, src/erasure-code/isa/ErasureCodeIsa.cc:292).
    """
    mat = np.array(mat, dtype=np.uint8, copy=True)
    n = mat.shape[0]
    assert mat.shape == (n, n)
    aug = np.concatenate([mat, np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        pivot = -1
        for row in range(col, n):
            if aug[row, col]:
                pivot = row
                break
        if pivot < 0:
            raise ValueError("singular GF(2^8) matrix")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv = GF_INV[aug[col, col]]
        aug[col] = GF_MUL_TABLE[inv][aug[col]]
        for row in range(n):
            if row != col and aug[row, col]:
                aug[row] ^= GF_MUL_TABLE[aug[row, col]][aug[col]]
    return aug[:, n:].copy()


def gf_solve_rows(src_rows: np.ndarray,
                  target_rows: np.ndarray) -> np.ndarray:
    """Express ``target_rows`` as GF(2^8) combinations of ``src_rows``.

    Solves X @ src_rows = target_rows for X (t, s) given src_rows
    (s, q) and target_rows (t, q); src_rows need not be square or full
    rank -- only the targets must lie in their row span.  This is the
    general repair-matrix builder the layered/regenerating codecs use:
    the local-group repair of an LRC chunk and the flat decode of any
    recoverable erasure pattern are both "write the lost rows over the
    rows we read".  Raises ValueError when a target is outside the span
    (the pattern is not recoverable from these sources).

    Any consistent solution yields byte-identical repairs: stored data
    equals generator @ data exactly, so X @ stored = target @ data for
    every X satisfying the row identity.  Free variables are pinned to
    zero, so the same (sources, targets) always produce the same
    matrix (a stable cache/schedule key).
    """
    src = np.array(src_rows, dtype=np.uint8, copy=True)
    tgt = np.asarray(target_rows, dtype=np.uint8)
    s, q = src.shape
    t = tgt.shape[0]
    assert tgt.shape[1] == q, (src.shape, tgt.shape)
    # row-reduce [src | I_s]: record each pivot column; the identity
    # side accumulates the combination that produced each reduced row
    aug = np.concatenate([src, np.eye(s, dtype=np.uint8)], axis=1)
    pivots: list[tuple[int, int]] = []      # (row, column)
    row = 0
    for col in range(q):
        piv = -1
        for r2 in range(row, s):
            if aug[r2, col]:
                piv = r2
                break
        if piv < 0:
            continue
        if piv != row:
            aug[[row, piv]] = aug[[piv, row]]
        inv = GF_INV[aug[row, col]]
        aug[row] = GF_MUL_TABLE[inv][aug[row]]
        for r2 in range(s):
            if r2 != row and aug[r2, col]:
                aug[r2] ^= GF_MUL_TABLE[aug[r2, col]][aug[row]]
        pivots.append((row, col))
        row += 1
        if row == s:
            break
    out = np.zeros((t, s), dtype=np.uint8)
    for i in range(t):
        residue = np.array(tgt[i], copy=True)
        combo = np.zeros(s, dtype=np.uint8)
        for prow, pcol in pivots:
            c = residue[pcol]
            if c:
                residue ^= GF_MUL_TABLE[c][aug[prow, :q]]
                combo ^= GF_MUL_TABLE[c][aug[prow, q:]]
        if residue.any():
            raise ValueError(
                "target row outside the span of the source rows "
                "(erasure pattern not recoverable from these sources)")
        out[i] = combo
    return out


# ---------------------------------------------------------------------------
# GF(2) bit-matrix representation.
#
# Multiplication by a constant c is linear over GF(2): representing a byte as
# its 8 polynomial coefficient bits (bit i = coefficient of x^i), there is an
# 8x8 binary matrix M_c with  bits(c*d) = M_c @ bits(d) (mod 2).  A full
# (m,k) GF coefficient matrix becomes an (8m, 8k) binary matrix, turning RS
# encode into a plain binary matmul -- the formulation the TPU MXU runs.
# ---------------------------------------------------------------------------

def coeff_to_bitmatrix(c: int) -> np.ndarray:
    """8x8 GF(2) matrix of multiplication by constant ``c``.

    Column t holds the bits of c * x^t.
    """
    out = np.zeros((8, 8), dtype=np.uint8)
    for t in range(8):
        prod = gf_mul(c, 1 << t)
        for i in range(8):
            out[i, t] = (prod >> i) & 1
    return out


def matrix_to_bitmatrix(mat: np.ndarray) -> np.ndarray:
    """Expand an (r,k) GF(2^8) coefficient matrix to its (8r,8k) GF(2) form."""
    # lint: disable=device-path-host-sync -- (r,k) coefficient matrix at codec setup, not batch payload
    mat = np.asarray(mat, dtype=np.uint8)
    r, k = mat.shape
    out = np.zeros((8 * r, 8 * k), dtype=np.uint8)
    for i in range(r):
        for j in range(k):
            out[8 * i:8 * i + 8, 8 * j:8 * j + 8] = coeff_to_bitmatrix(mat[i, j])
    return out


def gf_mul_bitmatrix(bitmat: np.ndarray, data: np.ndarray) -> np.ndarray:
    """Reference bit-matmul evaluation: (8r,8k) x (k,n) bytes -> (r,n) bytes.

    Slow (numpy) -- used only to validate the TPU kernels' formulation.
    """
    data = np.asarray(data, dtype=np.uint8)
    k = data.shape[0]
    n = data.shape[1]
    r8 = bitmat.shape[0]
    assert bitmat.shape[1] == 8 * k
    shifts = np.arange(8, dtype=np.uint8)
    bits = ((data[:, None, :] >> shifts[None, :, None]) & 1).reshape(8 * k, n)
    out_bits = (bitmat.astype(np.int32) @ bits.astype(np.int32)) & 1
    out_bits = out_bits.reshape(r8 // 8, 8, n).astype(np.uint8)
    return (out_bits << shifts[None, :, None]).sum(axis=1).astype(np.uint8)
