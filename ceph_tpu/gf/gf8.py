"""GF(2^8) table-based arithmetic (numpy, host side).

This is the scalar/host reference implementation; the TPU path in
``ceph_tpu.ops`` reformulates the same field operations as GF(2) bit-matrix
multiplications that run on the MXU.  Both must agree byte-for-byte.

The field is GF(2)[x] / (x^8 + x^4 + x^3 + x^2 + 1)  (poly 0x11d), the field
used by ISA-L erasure coding and jerasure w=8, which is what the reference's
ISA plugin drives (reference: src/erasure-code/isa/ErasureCodeIsa.cc:27,
via the isa-l submodule's ec_encode_data / gf_mul).
"""

from __future__ import annotations

import numpy as np

GF_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1
_GEN = 2  # x is a generator for 0x11d


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    v = 1
    for i in range(255):
        exp[i] = v
        log[v] = i
        v <<= 1
        if v & 0x100:
            v ^= GF_POLY
    # replicate so exp[log a + log b] never needs a mod
    for i in range(255, 512):
        exp[i] = exp[i - 255]
    return exp, log


GF_EXP, GF_LOG = _build_tables()

# full 256x256 multiplication table: GF_MUL_TABLE[a, b] = a*b
_la = GF_LOG[:, None] + GF_LOG[None, :]
GF_MUL_TABLE = GF_EXP[_la]
GF_MUL_TABLE[0, :] = 0
GF_MUL_TABLE[:, 0] = 0
del _la

GF_INV = np.zeros(256, dtype=np.uint8)
GF_INV[1:] = GF_EXP[255 - GF_LOG[1:]]


def gf_mul(a: int, b: int) -> int:
    """Scalar product in GF(2^8)."""
    return int(GF_MUL_TABLE[a & 0xFF, b & 0xFF])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    if a == 0:
        return 0
    return int(GF_EXP[(GF_LOG[a] - GF_LOG[b]) % 255])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(2^8) inverse of zero")
    return int(GF_INV[a])


def gf_pow(a: int, n: int) -> int:
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(GF_LOG[a] * n) % 255])


def gf_mul_bytes(c: int, data: np.ndarray) -> np.ndarray:
    """Multiply every byte of ``data`` by the constant ``c``."""
    data = np.asarray(data, dtype=np.uint8)
    return GF_MUL_TABLE[c][data]


def gf_matmul(mat: np.ndarray, data: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix product of an (r,k) coefficient matrix with (k,n) bytes.

    out[i, :] = XOR_j  mat[i, j] * data[j, :]

    This is exactly what ISA-L's ec_encode_data computes with its expanded
    tables (the hot loop the TPU kernels replace).
    """
    mat = np.asarray(mat, dtype=np.uint8)
    data = np.asarray(data, dtype=np.uint8)
    r, k = mat.shape
    assert data.shape[0] == k, (mat.shape, data.shape)
    out = np.zeros((r, data.shape[1]), dtype=np.uint8)
    for i in range(r):
        acc = out[i]
        for j in range(k):
            c = mat[i, j]
            if c == 0:
                continue
            elif c == 1:
                acc ^= data[j]
            else:
                acc ^= GF_MUL_TABLE[c][data[j]]
        out[i] = acc
    return out


def gf_invert_matrix(mat: np.ndarray) -> np.ndarray:
    """Invert a square GF(2^8) matrix by Gauss-Jordan with partial pivoting.

    Raises ValueError when singular (mirrors gf_invert_matrix < 0 in the
    reference's decode path, src/erasure-code/isa/ErasureCodeIsa.cc:292).
    """
    mat = np.array(mat, dtype=np.uint8, copy=True)
    n = mat.shape[0]
    assert mat.shape == (n, n)
    aug = np.concatenate([mat, np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        pivot = -1
        for row in range(col, n):
            if aug[row, col]:
                pivot = row
                break
        if pivot < 0:
            raise ValueError("singular GF(2^8) matrix")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv = GF_INV[aug[col, col]]
        aug[col] = GF_MUL_TABLE[inv][aug[col]]
        for row in range(n):
            if row != col and aug[row, col]:
                aug[row] ^= GF_MUL_TABLE[aug[row, col]][aug[col]]
    return aug[:, n:].copy()


# ---------------------------------------------------------------------------
# GF(2) bit-matrix representation.
#
# Multiplication by a constant c is linear over GF(2): representing a byte as
# its 8 polynomial coefficient bits (bit i = coefficient of x^i), there is an
# 8x8 binary matrix M_c with  bits(c*d) = M_c @ bits(d) (mod 2).  A full
# (m,k) GF coefficient matrix becomes an (8m, 8k) binary matrix, turning RS
# encode into a plain binary matmul -- the formulation the TPU MXU runs.
# ---------------------------------------------------------------------------

def coeff_to_bitmatrix(c: int) -> np.ndarray:
    """8x8 GF(2) matrix of multiplication by constant ``c``.

    Column t holds the bits of c * x^t.
    """
    out = np.zeros((8, 8), dtype=np.uint8)
    for t in range(8):
        prod = gf_mul(c, 1 << t)
        for i in range(8):
            out[i, t] = (prod >> i) & 1
    return out


def matrix_to_bitmatrix(mat: np.ndarray) -> np.ndarray:
    """Expand an (r,k) GF(2^8) coefficient matrix to its (8r,8k) GF(2) form."""
    # lint: disable=device-path-host-sync -- (r,k) coefficient matrix at codec setup, not batch payload
    mat = np.asarray(mat, dtype=np.uint8)
    r, k = mat.shape
    out = np.zeros((8 * r, 8 * k), dtype=np.uint8)
    for i in range(r):
        for j in range(k):
            out[8 * i:8 * i + 8, 8 * j:8 * j + 8] = coeff_to_bitmatrix(mat[i, j])
    return out


def gf_mul_bitmatrix(bitmat: np.ndarray, data: np.ndarray) -> np.ndarray:
    """Reference bit-matmul evaluation: (8r,8k) x (k,n) bytes -> (r,n) bytes.

    Slow (numpy) -- used only to validate the TPU kernels' formulation.
    """
    data = np.asarray(data, dtype=np.uint8)
    k = data.shape[0]
    n = data.shape[1]
    r8 = bitmat.shape[0]
    assert bitmat.shape[1] == 8 * k
    shifts = np.arange(8, dtype=np.uint8)
    bits = ((data[:, None, :] >> shifts[None, :, None]) & 1).reshape(8 * k, n)
    out_bits = (bitmat.astype(np.int32) @ bits.astype(np.int32)) & 1
    out_bits = out_bits.reshape(r8 // 8, 8, n).astype(np.uint8)
    return (out_bits << shifts[None, :, None]).sum(axis=1).astype(np.uint8)
