"""Erasure-code generator matrices with reference-identical construction.

The ISA-L constructions mirror gf_gen_rs_matrix / gf_gen_cauchy1_matrix as
consumed by the reference's ISA plugin (src/erasure-code/isa/
ErasureCodeIsa.cc:404-421); the jerasure construction mirrors
reed_sol_vandermonde_coding_matrix as consumed by the jerasure plugin
(src/erasure-code/jerasure/ErasureCodeJerasure.cc:203).  Byte-identical
parity requires byte-identical matrices.
"""

from __future__ import annotations

import numpy as np

from .gf8 import GF_EXP, GF_LOG, gf_mul, gf_inv, gf_pow, gf_invert_matrix, GF_MUL_TABLE


def gen_rs_matrix(m: int, k: int) -> np.ndarray:
    """ISA-L style systematic Vandermonde generator: (m, k), m = k + parity.

    Rows 0..k-1 are the identity; parity row r (row k+r) is
    [g^0, g^1, ..., g^(k-1)] with g = 2^r.  (Not a systematized Vandermonde:
    the plain rows are appended below the identity, exactly as ISA-L does.)
    """
    a = np.zeros((m, k), dtype=np.uint8)
    for i in range(k):
        a[i, i] = 1
    gen = 1
    for i in range(k, m):
        p = 1
        for j in range(k):
            a[i, j] = p
            p = gf_mul(p, gen)
        gen = gf_mul(gen, 2)
    return a


def gen_cauchy1_matrix(m: int, k: int) -> np.ndarray:
    """ISA-L style Cauchy generator: identity on top, then 1/(i ^ j)."""
    a = np.zeros((m, k), dtype=np.uint8)
    for i in range(k):
        a[i, i] = 1
    for i in range(k, m):
        for j in range(k):
            a[i, j] = gf_inv(i ^ j)
    return a


def _jerasure_extended_vandermonde(rows: int, cols: int) -> np.ndarray:
    """jerasure's extended Vandermonde matrix (w=8).

    Row 0 = e_0, last row = e_{cols-1}; interior row i has entries i^j
    (GF power), matching reed_sol_extended_vandermonde_matrix semantics.
    """
    v = np.zeros((rows, cols), dtype=np.uint8)
    v[0, 0] = 1
    for i in range(1, rows - 1):
        for j in range(cols):
            v[i, j] = gf_pow(i, j)
    v[rows - 1, cols - 1] = 1
    return v


def gen_jerasure_rs_vandermonde(k: int, m: int) -> np.ndarray:
    """jerasure reed_sol_van coding matrix: (m, k) parity rows.

    Reproduces reed_sol_big_vandermonde_distribution_matrix's distinguished
    matrix: build the (k+m, k) extended Vandermonde matrix, systematize the
    top k x k block with row swaps + column operations, then normalize so
    the first parity row and the first parity column are all ones.
    """
    rows, cols = k + m, k
    v = _jerasure_extended_vandermonde(rows, cols)
    for i in range(1, cols):
        # find a row at/below i with a nonzero pivot in column i, swap up
        piv = i
        while piv < rows and v[piv, i] == 0:
            piv += 1
        if piv >= rows:
            raise ValueError("vandermonde systematization failed")
        if piv != i:
            v[[i, piv]] = v[[piv, i]]
        # scale column i so the pivot is 1
        if v[i, i] != 1:
            inv = gf_inv(int(v[i, i]))
            v[:, i] = GF_MUL_TABLE[inv][v[:, i]]
        # clear the rest of row i with column ops
        for j in range(cols):
            c = int(v[i, j])
            if j != i and c != 0:
                v[:, j] ^= GF_MUL_TABLE[c][v[:, i]]
    # make parity row 0 (matrix row k) all ones by scaling the parity part
    # of each column
    for j in range(cols):
        c = int(v[k, j])
        if c != 1:
            inv = gf_inv(c)
            v[k:, j] = GF_MUL_TABLE[inv][v[k:, j]]
    # make parity column 0 all ones by scaling each later parity row
    for i in range(k + 1, rows):
        c = int(v[i, 0])
        if c not in (0, 1):
            inv = gf_inv(c)
            v[i] = GF_MUL_TABLE[inv][v[i]]
    return v[k:, :].copy()


def erasure_signature(decode_index: list[int], erasures: list[int]) -> str:
    """Cache key describing a decode configuration.

    Same shape as the reference's signature ("+r" per surviving source row,
    "-e" per erasure, src/erasure-code/isa/ErasureCodeIsa.cc:246-262) so
    cache behavior is comparable.
    """
    return "".join(f"+{r}" for r in decode_index) + "".join(
        f"-{e}" for e in erasures)


def decode_index_for(k: int, erasures: set[int]) -> list[int]:
    """First k surviving shard indices, in order (reference decode_index)."""
    out = []
    r = 0
    for _ in range(k):
        while r in erasures:
            r += 1
        out.append(r)
        r += 1
    return out


def build_decode_matrix(
    encode_matrix: np.ndarray,
    k: int,
    erasures: list[int],
) -> tuple[np.ndarray, list[int]]:
    """Build the (nerrs, k) decode matrix over the first k surviving shards.

    Mirrors the ISA decode path: drop erased rows of the generator, invert
    the kxk survivor matrix; for an erased data shard e the decode row is row
    e of the inverse; for an erased parity shard p the row is (generator row
    p) @ inverse.  (src/erasure-code/isa/ErasureCodeIsa.cc:268-315.)

    Returns (decode_matrix, decode_index).
    """
    eset = set(erasures)
    decode_index = decode_index_for(k, eset)
    b = encode_matrix[decode_index, :k]
    d = gf_invert_matrix(b)  # raises ValueError if singular
    nerrs = len(erasures)
    c = np.zeros((nerrs, k), dtype=np.uint8)
    for p, e in enumerate(erasures):
        if e < k:
            c[p] = d[e]
        else:
            # parity row re-expressed over the surviving sources
            for i in range(k):
                s = 0
                for j in range(k):
                    s ^= gf_mul(int(d[j, i]), int(encode_matrix[e, j]))
                c[p, i] = s
    return c, decode_index
