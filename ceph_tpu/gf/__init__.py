"""GF(2^8) arithmetic core for erasure coding.

Field semantics follow Intel ISA-L / jerasure's default w=8 field: the
primitive polynomial is x^8 + x^4 + x^3 + x^2 + 1 (0x11d), so our parity
bytes are bit-identical to what the reference's ISA plugin produces
(reference: src/erasure-code/isa/ErasureCodeIsa.cc:380-421 builds its
coefficients with gf_gen_rs_matrix / gf_gen_cauchy1_matrix over this field).
"""

from .gf8 import (  # noqa: F401
    GF_POLY,
    GF_EXP,
    GF_LOG,
    GF_INV,
    gf_mul,
    gf_div,
    gf_inv,
    gf_pow,
    gf_mul_bytes,
    gf_matmul,
    gf_invert_matrix,
    gf_solve_rows,
    gf_mul_bitmatrix,
    coeff_to_bitmatrix,
    matrix_to_bitmatrix,
)
from .matrices import (  # noqa: F401
    gen_rs_matrix,
    gen_cauchy1_matrix,
    gen_jerasure_rs_vandermonde,
    build_decode_matrix,
    erasure_signature,
)
