"""GF(2^w) scalar arithmetic + GF(2) bitmatrix construction.

The jerasure bit-matrix techniques (cauchy_orig/cauchy_good/liberation/
blaum_roth) never do field multiplies on the data path -- coding is
pure XOR of w-bit packet rows selected by a (m*w, k*w) GF(2) matrix.
Field arithmetic is only needed to CONSTRUCT matrices, so plain Python
ints suffice (w up to 32).  Polynomials match jerasure's galois.c
defaults so the matrices are the reference's matrices:
w=4: 0x13, w=8: 0x11d, w=16: 0x1100b, w=32: 0x100400007.
"""

from __future__ import annotations

import numpy as np

PRIM_POLY = {4: 0x13, 8: 0x11d, 16: 0x1100b, 32: 0x100400007}


def gf2w_mult(a: int, b: int, w: int) -> int:
    poly = PRIM_POLY[w]
    r = 0
    while b:
        if b & 1:
            r ^= a
        b >>= 1
        a <<= 1
        if a >> w:
            a ^= poly
    return r


def gf2w_div(a: int, b: int, w: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF division by zero")
    return gf2w_mult(a, gf2w_inv(b, w), w)


def gf2w_inv(a: int, w: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF inverse of zero")
    # a^(2^w - 2) by square-and-multiply
    r, e = 1, (1 << w) - 2
    base = a
    while e:
        if e & 1:
            r = gf2w_mult(r, base, w)
        base = gf2w_mult(base, base, w)
        e >>= 1
    return r


def matrix_to_bitmatrix(matrix: np.ndarray, k: int, m: int,
                        w: int) -> np.ndarray:
    """(m,k) GF(2^w) matrix -> (m*w, k*w) GF(2) matrix.

    jerasure_matrix_to_bitmatrix semantics: the w x w block for element
    e has column c equal to the bit-decomposition of e * alpha^c
    (successive columns multiply by 2)."""
    out = np.zeros((m * w, k * w), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            e = int(matrix[i, j])
            b = e
            for c in range(w):
                for r in range(w):
                    out[i * w + r, j * w + c] = (b >> r) & 1
                b = gf2w_mult(b, 2, w)
    return out


def bitmatrix_ones(row: np.ndarray) -> int:
    return int(row.sum())


# -- cauchy (jerasure cauchy.c semantics) -----------------------------------

def cauchy_original_coding_matrix(k: int, m: int, w: int) -> np.ndarray:
    """matrix[i][j] = 1 / (i XOR (m + j)) over GF(2^w)."""
    if k + m > (1 << w):
        raise ValueError(f"k+m={k + m} > 2^w={1 << w}")
    out = np.zeros((m, k), dtype=np.int64)
    for i in range(m):
        for j in range(k):
            out[i, j] = gf2w_inv(i ^ (m + j), w)
    return out


def cauchy_improve_coding_matrix(matrix: np.ndarray, k: int, m: int,
                                 w: int) -> np.ndarray:
    """cauchy_good's matrix optimization: normalize each column so row
    0 is all ones, then rescale each later row by the divisor that
    minimizes the total number of ones in its bitmatrix (fewer ones =
    fewer XORs on the data path)."""
    mat = matrix.copy()
    for j in range(k):
        d = int(mat[0, j])
        if d != 1:
            inv = gf2w_inv(d, w)
            for i in range(m):
                mat[i, j] = gf2w_mult(int(mat[i, j]), inv, w)
    for i in range(1, m):
        best_div = 1
        best = sum(_elt_ones(int(e), w) for e in mat[i])
        for j in range(k):
            d = int(mat[i, j])
            if d in (0, 1):
                continue
            inv = gf2w_inv(d, w)
            cand = [gf2w_mult(int(e), inv, w) for e in mat[i]]
            ones = sum(_elt_ones(e, w) for e in cand)
            if ones < best:
                best = ones
                best_div = d
        if best_div != 1:
            inv = gf2w_inv(best_div, w)
            for j in range(k):
                mat[i, j] = gf2w_mult(int(mat[i, j]), inv, w)
    return mat


def _elt_ones(e: int, w: int) -> int:
    """Number of ones in the w x w bitmatrix block of element e."""
    ones = 0
    b = e
    for _ in range(w):
        ones += bin(b).count("1")
        b = gf2w_mult(b, 2, w)
    return ones


# -- liberation / blaum-roth (minimal-density RAID-6 bitmatrices) ------------

def _is_prime(n: int) -> bool:
    if n < 2:
        return False
    i = 2
    while i * i <= n:
        if n % i == 0:
            return False
        i += 1
    return True


def liberation_coding_bitmatrix(k: int, w: int) -> np.ndarray:
    """Liberation codes (Plank): m=2, w prime, k <= w.

    P row: identity per chunk.  Q row: for chunk j a shifted identity
    (one at (i, (i+j) mod w)) plus, for j>0, one extra bit at row
    i = j*(w-1)/2 mod w, column (i + j - 1) mod w (liberation.c)."""
    if not _is_prime(w):
        raise ValueError(f"liberation requires prime w, got {w}")
    if k > w:
        raise ValueError(f"liberation requires k <= w ({k} > {w})")
    bm = np.zeros((2 * w, k * w), dtype=np.uint8)
    for j in range(k):
        off = j * w
        for i in range(w):
            bm[i, off + i] = 1                       # P (RAID-4 row)
            bm[w + i, off + (i + j) % w] = 1         # Q shifted identity
        if j > 0:
            i = (j * ((w - 1) // 2)) % w
            bm[w + i, off + (i + j - 1) % w] = 1     # the extra bit
    return bm


def blaum_roth_coding_bitmatrix(k: int, w: int) -> np.ndarray:
    """Blaum-Roth codes: m=2, w+1 prime, k <= w.

    Arithmetic in the ring F2[x]/M_p(x), p = w+1 prime, where
    M_p = 1 + x + ... + x^(p-1): the Q block for chunk j is the
    multiplication-by-x^j matrix in the basis {1..x^(w-1)} (with
    x^w == 1 + x + ... + x^(w-1)); P is plain parity."""
    p = w + 1
    if not _is_prime(p):
        raise ValueError(f"blaum_roth requires w+1 prime, got w={w}")
    if k > w:
        raise ValueError(f"blaum_roth requires k <= w ({k} > {w})")

    def mult_by_x(vec: np.ndarray) -> np.ndarray:
        out = np.zeros(w, dtype=np.uint8)
        out[1:] = vec[:-1]
        if vec[w - 1]:                  # x^w = sum_{i<w} x^i
            out ^= 1
        return out

    bm = np.zeros((2 * w, k * w), dtype=np.uint8)
    for j in range(k):
        off = j * w
        for i in range(w):
            bm[i, off + i] = 1                       # P
        # Q block: columns are x^j * basis vectors
        for c in range(w):
            vec = np.zeros(w, dtype=np.uint8)
            vec[c] = 1
            for _ in range(j):
                vec = mult_by_x(vec)
            bm[w:2 * w, off + c] = vec
    return bm


# -- GF(2) linear algebra on the data path ----------------------------------

def xor_matmul(bits: np.ndarray, planes: np.ndarray) -> np.ndarray:
    """(r, c) 0/1 matrix x (c, N) byte rows -> (r, N), + = XOR.

    XOR of byte vectors is addition in GF(2)^8 componentwise, so this
    is the whole bitmatrix data path.  (The TPU mapping is the same
    GF(2) bit-matmul the gf2kernels module runs on the MXU.)"""
    out = np.zeros((bits.shape[0], planes.shape[1]), dtype=np.uint8)
    for r in range(bits.shape[0]):
        sel = planes[bits[r] != 0]
        if len(sel):
            out[r] = np.bitwise_xor.reduce(sel, axis=0)
    return out


def gf2_invert(mat: np.ndarray) -> np.ndarray:
    """Invert a square 0/1 matrix over GF(2); raises on singular."""
    n = mat.shape[0]
    a = mat.astype(np.uint8).copy()
    inv = np.eye(n, dtype=np.uint8)
    for col in range(n):
        piv = next((r for r in range(col, n) if a[r, col]), None)
        if piv is None:
            raise ValueError("bitmatrix singular")
        if piv != col:
            a[[col, piv]] = a[[piv, col]]
            inv[[col, piv]] = inv[[piv, col]]
        for r in range(n):
            if r != col and a[r, col]:
                a[r] ^= a[col]
                inv[r] ^= inv[col]
    return inv
