"""rbd live migration: move an image while it stays usable.

src/librbd/migration role (prepare / execute / commit / abort):

  * prepare: create the DESTINATION image (same geometry) carrying a
    migration-source pointer; mark the SOURCE migrating (new opens of
    it are forced read-only).  From here clients use the destination:
    reads of not-yet-copied objects FALL THROUGH to the source (the
    same hole->source dispatch clone reads use), writes land on the
    destination after a copyup of the source object.
  * execute: background deep-copy of every remaining object (bounded
    concurrency) through the image APIs, atomic per object (cls
    copyup) so it races live client writes safely.  Encrypted images
    are refused at prepare (passphrase plumbing through the lazy
    source fall-through is future work).
  * commit: source is removed and the pointer dropped -- the
    destination stands alone.
  * abort: destination is removed and the source unmarked.

Markers ride header xattrs (like the encryption envelope):
``rbd.migration_source`` on the destination (JSON: pool/name/state),
``rbd.migration_target`` on the source.
"""

from __future__ import annotations

import json

from ..client.rados import RadosError
from .rbd import RBD, Image, RbdError, _gather_bounded, _header

MIG_SRC_XATTR = "rbd.migration_source"
MIG_DST_XATTR = "rbd.migration_target"


async def _get_marker(ioctx, iid: str, xattr: str) -> dict | None:
    try:
        raw = await ioctx.get_xattr(_header(iid), xattr)
    except RadosError as e:
        if e.errno_name not in ("ENOENT", "ENODATA"):
            raise
        return None
    return json.loads(raw) if raw else None


async def migration_prepare(src_ioctx, src_name: str,
                            dst_ioctx, dst_name: str) -> str:
    """Create the destination and link both ends.  The source must
    have no active writer (we take its exclusive lock transiently)."""
    # encrypted sources are refused BEFORE the open (whose passphrase
    # gate would otherwise answer EPERM and confuse the caller)
    from .crypto import ENVELOPE_XATTR
    sid = (await src_ioctx.exec(
        "rbd_directory", "rbd", "dir_get_id",
        json.dumps({"name": src_name}).encode())).decode()
    try:
        env = await src_ioctx.get_xattr(_header(sid), ENVELOPE_XATTR)
    except RadosError:
        env = None
    if env:
        raise RbdError("EOPNOTSUPP",
                       "encrypted image migration not supported")
    src = await Image.open(src_ioctx, src_name)   # excludes writers
    try:
        if await _get_marker(src_ioctx, src.id, MIG_DST_XATTR):
            raise RbdError("EBUSY", "already migrating")
        dst_id = await RBD().create(
            dst_ioctx, dst_name, src.meta["size"],
            order=src.meta["order"],
            features=src.meta.get("features"))
        await dst_ioctx.set_xattr(
            _header(dst_id), MIG_SRC_XATTR, json.dumps({
                "pool": src_ioctx.pool_name, "image": src_name,
                "image_id": src.id, "state": "prepared"}).encode())
        await src_ioctx.set_xattr(
            _header(src.id), MIG_DST_XATTR, json.dumps({
                "pool": dst_ioctx.pool_name, "image": dst_name,
                "image_id": dst_id, "state": "prepared"}).encode())
        return dst_id
    finally:
        await src.close()


async def _open_source(dst_img: Image) -> Image | None:
    marker = await _get_marker(dst_img.ioctx, dst_img.id,
                               MIG_SRC_XATTR)
    if marker is None:
        return None
    from ..client.rados import IoCtx
    sio = IoCtx(dst_img.ioctx.rados, marker["pool"],
                dst_img.ioctx.rados.objecter.osdmap.pool_names[
                    marker["pool"]])
    return await Image.open(sio, marker["image"], read_only=True,
                            exclusive=False)


async def migration_execute(dst_ioctx, dst_name: str) -> int:
    """Deep-copy all source data into the destination; returns bytes
    copied.  Safe to run while clients write to the destination: a
    client write that already landed wins (copy skips ranges the
    destination already has)."""
    # exclusive=False: the copier runs WHILE a client holds the
    # destination's lock and keeps writing (that is the "live" part);
    # per-object safety comes from the atomic cls copyup below
    dst = await Image.open(dst_ioctx, dst_name, exclusive=False)
    try:
        src = await _open_source(dst)
        if src is None:
            raise RbdError("EINVAL", f"{dst_name} is not migrating")
        try:
            size = src.meta["size"]
            lay = dst._layout
            copied = 0

            async def copy_object(objectno: int) -> int:
                obj_off = objectno * lay.object_size
                n = min(lay.object_size, size - obj_off)
                if n <= 0:
                    return 0
                oid = dst._data_obj(objectno)
                try:
                    await dst.ioctx.stat(oid)
                    return 0      # already materialized: skip the
                                  # source read entirely (re-runs,
                                  # client-written objects)
                except RadosError as e:
                    if e.errno_name != "ENOENT":
                        raise
                buf = await src.read(obj_off, n)
                if buf.strip(b"\0"):
                    # write-if-missing, atomic at the OSD: a racing
                    # client write (which copied up first) wins and
                    # this stale source copy no-ops
                    await dst._copyup_atomic(oid, buf)
                    return len(buf)
                return 0

            n_objs = dst._object_count(size)
            results = await _gather_bounded(
                [copy_object(i) for i in range(n_objs)])
            copied = sum(results)
            marker = await _get_marker(dst.ioctx, dst.id,
                                       MIG_SRC_XATTR)
            marker["state"] = "executed"
            await dst.ioctx.set_xattr(_header(dst.id), MIG_SRC_XATTR,
                                      json.dumps(marker).encode())
            return copied
        finally:
            await src.close()
    finally:
        await dst.close()


async def migration_commit(dst_ioctx, dst_name: str) -> None:
    """Drop the source; the destination stands alone."""
    dst = await Image.open(dst_ioctx, dst_name)
    try:
        marker = await _get_marker(dst.ioctx, dst.id, MIG_SRC_XATTR)
        if marker is None:
            raise RbdError("EINVAL", f"{dst_name} is not migrating")
        if marker.get("state") != "executed":
            raise RbdError("EBUSY", "execute the migration first")
        src = await _open_source(dst)
        sio = src.ioctx
        sname = marker["image"]
        # unmark the source FIRST so its removal is permitted
        await sio.rm_xattr(_header(src.id), MIG_DST_XATTR)
        await src.close()
        await RBD().remove(sio, sname)
        await dst.ioctx.rm_xattr(_header(dst.id), MIG_SRC_XATTR)
        dst._mig_marker = None
    finally:
        await dst.close()


async def migration_abort(dst_ioctx, dst_name: str) -> None:
    """Tear the destination down and free the source."""
    dst = await Image.open(dst_ioctx, dst_name)
    marker = await _get_marker(dst.ioctx, dst.id, MIG_SRC_XATTR)
    await dst.close()
    if marker is None:
        raise RbdError("EINVAL", f"{dst_name} is not migrating")
    from ..client.rados import IoCtx
    sio = IoCtx(dst_ioctx.rados, marker["pool"],
                dst_ioctx.rados.objecter.osdmap.pool_names[
                    marker["pool"]])
    # clear BOTH markers before the destination removal (remove
    # refuses images that still look mid-migration)
    await sio.rm_xattr(_header(marker["image_id"]), MIG_DST_XATTR)
    await dst_ioctx.rm_xattr(_header(dst.id), MIG_SRC_XATTR)
    await RBD().remove(dst_ioctx, dst_name)
