"""rbd-mirror analog: snapshot-based async image replication.

The reference's rbd-mirror (src/tools/rbd_mirror) replays images
between clusters; in snapshot mode it creates mirror snapshots on the
primary and copies the delta between consecutive mirror snapshots to
the secondary.  This module renders that mode:

  * enablement lives in the pool's ``rbd_mirroring`` object omap
    (image name -> enabled), the mirroring config store analog;
  * each sync cycle snapshots the primary (``.mirror.<n>``), computes
    the per-object delta against the previous mirror snapshot by
    reading both snapshots, applies it to the secondary image, and
    snapshots the secondary at the same name -- so the secondary
    always holds a crash-consistent point-in-time copy;
  * MirrorDaemon loops sync cycles over every enabled image between
    two clusters (the per-pool replayer).

The secondary is not writable by clients during mirroring (the
reference enforces this with the NON_PRIMARY flag; here the operator
contract is the same).
"""

from __future__ import annotations

import asyncio

from ..client.rados import RadosError
from .rbd import RBD, Image, RbdError

MIRROR_OID = "rbd_mirroring"
SNAP_PREFIX = ".mirror."
SNAP_RETENTION = 2      # mirror snaps kept per side after a sync


async def mirror_enable(ioctx, image_name: str,
                        mode: str = "snapshot") -> None:
    """mode: "snapshot" | "journal" (the reference's per-image mirror
    image mode); each daemon serves only its own mode."""
    if mode not in ("snapshot", "journal"):
        raise RbdError("EINVAL", f"mirror mode {mode!r}")
    await ioctx.set_omap(MIRROR_OID, {image_name: mode.encode()})


async def mirror_disable(ioctx, image_name: str) -> None:
    try:
        await ioctx.rm_omap_keys(MIRROR_OID, [image_name])
    except RadosError as e:
        if e.errno_name != "ENOENT":
            raise       # an unreachable cluster is not "already off"


async def mirror_images(ioctx) -> dict[str, str]:
    """{image_name: mode}; legacy b"enabled" entries read as
    snapshot mode."""
    try:
        omap = await ioctx.get_omap(MIRROR_OID)
    except RadosError as e:
        if e.errno_name == "ENOENT":
            return {}   # registry object not created yet
        raise           # unreachable cluster must not look like "none"
    out = {}
    for name, raw in omap.items():
        mode = raw.decode()
        out[name] = "snapshot" if mode == "enabled" else mode
    return out


async def mirror_enabled(ioctx, mode: str | None = None) -> list[str]:
    imgs = await mirror_images(ioctx)
    return sorted(n for n, m in imgs.items()
                  if mode is None or m == mode)


def _mirror_snaps(img: Image) -> list[tuple[int, str]]:
    out = []
    for s in img.list_snaps():
        if s["name"].startswith(SNAP_PREFIX):
            try:
                seq = int(s["name"][len(SNAP_PREFIX):])
            except ValueError:
                continue     # user snap that merely shares the prefix
            out.append((seq, s["name"]))
    return sorted(out)


async def mirror_sync(src_ioctx, dst_ioctx, image_name: str) -> dict:
    """One replication cycle; returns {snap, objects_copied, bytes}."""
    src = await Image.open(src_ioctx, image_name, read_only=True)
    try:
        prior = _mirror_snaps(src)
        # the delta BASE must be the newest snapshot present on BOTH
        # sides: a primary snap orphaned by a failed sync never reached
        # the secondary, and using it as base would silently lose the
        # un-replicated delta forever
        dst_names: set[str] = set()
        try:
            dimg = await Image.open(dst_ioctx, image_name,
                                    read_only=True)
            dst_names = {s["name"] for s in dimg.list_snaps()}
            await dimg.close()
        except RbdError as e:
            if e.errno_name != "ENOENT":
                raise
        common = [(n, name) for n, name in prior if name in dst_names]
        orphans = [(n, name) for n, name in prior
                   if name not in dst_names]
        prior = common
        seq = max((n for n, _ in common + orphans), default=0) + 1
        snap_name = f"{SNAP_PREFIX}{seq}"
        # snapshot the PRIMARY through a snap-only handle: taking
        # the exclusive lock would make in-use images unreplicable
        # (EBUSY forever while a client holds the image open)
        wsrc = await Image.open(src_ioctx, image_name, exclusive=False)
        try:
            for _, orphan in orphans:    # failed-sync leftovers
                await wsrc.remove_snap(orphan)
            await wsrc.create_snap(snap_name)
        finally:
            await wsrc.close()

        rbd = RBD()
        src_snap = await Image.open(src_ioctx, image_name,
                                    snapshot=snap_name)
        try:
            size = await src_snap.size()
            try:
                dst = await Image.open(dst_ioctx, image_name)
            except RbdError as e:
                if e.errno_name != "ENOENT":
                    raise
                await rbd.create(dst_ioctx, image_name, size,
                                 order=src.meta["order"])
                dst = await Image.open(dst_ioctx, image_name)
            try:
                if orphans and prior:
                    # a previous sync died mid-copy: the secondary HEAD
                    # may hold part of a delta that was never frozen;
                    # rewind it to the last common snapshot so the
                    # base-diff applies onto exactly-base content
                    await dst.rollback_snap(prior[-1][1])
                if await dst.size() != size:
                    await dst.resize(size)
                base = prior[-1][1] if prior else None
                base_img = None
                if base is not None:
                    base_img = await Image.open(src_ioctx, image_name,
                                                snapshot=base)
                copied = nbytes = 0
                step = 1 << src.meta["order"]
                try:
                    off = 0
                    while off < size:
                        n = min(step, size - off)
                        cur = await src_snap.read(off, n)
                        if base_img is not None:
                            old = await base_img.read(off, n)
                            if old == cur:
                                off += n
                                continue
                        await dst.write(off, cur)
                        copied += 1
                        nbytes += n
                        off += n
                finally:
                    if base_img is not None:
                        await base_img.close()
                # freeze the secondary at the same point in time
                await dst.create_snap(snap_name)
                # retention: unbounded mirror snaps would grow the
                # snap context (and COW cost) forever on both sides
                for _, old in _mirror_snaps(dst)[:-SNAP_RETENTION]:
                    await dst.remove_snap(old)
            finally:
                await dst.close()
        finally:
            await src_snap.close()
        wsrc = await Image.open(src_ioctx, image_name,
                                exclusive=False)
        try:
            for _, old in _mirror_snaps(wsrc)[:-SNAP_RETENTION]:
                await wsrc.remove_snap(old)
        finally:
            await wsrc.close()
        return {"snap": snap_name, "objects_copied": copied,
                "bytes": nbytes}
    finally:
        await src.close()


async def mirror_status(ioctx, image_name: str) -> dict:
    img = await Image.open(ioctx, image_name, read_only=True)
    try:
        snaps = _mirror_snaps(img)
        return {"image": image_name,
                "mirror_snaps": [n for _, n in snaps],
                "last_sync": snaps[-1][1] if snaps else None}
    finally:
        await img.close()


class MirrorDaemon:
    """Per-pool replayer: primary cluster -> secondary cluster."""

    def __init__(self, src_ioctx, dst_ioctx,
                 interval: float = 5.0) -> None:
        self.src = src_ioctx
        self.dst = dst_ioctx
        self.interval = interval
        self.stats: dict[str, dict] = {}
        self._task: asyncio.Task | None = None

    async def sync_all(self) -> dict:
        enabled = await mirror_enabled(self.src, mode="snapshot")
        for name in enabled:
            try:
                self.stats[name] = await mirror_sync(self.src, self.dst,
                                                     name)
            except (RbdError, RadosError) as e:
                self.stats[name] = {"error": str(e)}
        # stats for disabled images are not "being replicated"
        self.stats = {k: v for k, v in self.stats.items()
                      if k in enabled}
        return dict(self.stats)

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._loop())

    async def _loop(self) -> None:
        while True:
            try:
                await self.sync_all()
            except asyncio.CancelledError:
                return
            except Exception as e:
                # replication must not die silently while the launcher
                # keeps running; record and keep cycling
                self.stats["_daemon_error"] = {
                    "error": f"{type(e).__name__}: {e}"}
            try:
                await asyncio.sleep(self.interval)
            except asyncio.CancelledError:
                return

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass


# -- journal-based mirroring (src/tools/rbd_mirror journal mode) ------------
# Snapshot mode (above) ships periodic deltas; journal mode tails the
# primary image's event journal and REPLAYS it on the secondary, so
# replication lag is bounded by the tail loop, not the snapshot
# schedule.

async def journal_bootstrap(src_ioctx, dst_ioctx, image_name: str,
                            client_id: str = "mirror") -> dict:
    """Create the secondary image and register as a journal client at
    the CURRENT head: everything before the registration position is
    carried over by a full copy, everything after arrives via replay
    (rbd_mirror ImageReplayer bootstrap)."""
    from .features import ImageJournal
    src = await Image.open(src_ioctx, image_name, read_only=True)
    try:
        jr = ImageJournal(src_ioctx, src.id)
        head = await jr.head_seq()
        rbd = RBD()
        try:
            await rbd.create(dst_ioctx, image_name,
                             src.meta["size"],
                             order=src.meta["order"],
                             features=src.meta.get("features"))
        except RbdError as e:
            if e.errno_name != "EEXIST":
                raise
        dst = await Image.open(dst_ioctx, image_name,
                               exclusive=False)
        # replicated bytes must NOT re-journal on the secondary: its
        # journal has no consumers (untrimmable growth) and a later
        # promotion would replay the whole copy again
        dst.journal = None
        try:
            if dst.meta["size"] != src.meta["size"]:
                await dst.resize(src.meta["size"])
            step = 1 << src.meta["order"]
            for off in range(0, src.meta["size"], step):
                n = min(step, src.meta["size"] - off)
                buf = await src.read(off, n)
                if buf.strip(b"\x00"):
                    await dst.write(off, buf)
                else:
                    # a RE-bootstrap over an existing replica must
                    # clear ranges the primary has since zeroed --
                    # skipping them would leave stale secondary bytes
                    await dst.discard(off, n)
        finally:
            await dst.close()
        await jr.register_client(client_id, position=head)
        return {"position": head}
    finally:
        await src.close()


async def journal_replay_once(src_ioctx, dst_ioctx, image_name: str,
                              client_id: str = "mirror",
                              limit: int = 64) -> int:
    """Replay journal events past our committed position onto the
    secondary; commit + trim.  Returns events applied."""
    from .features import ImageJournal
    src = await Image.open(src_ioctx, image_name, read_only=True)
    try:
        jr = ImageJournal(src_ioctx, src.id)
        clients = {c["id"]: c for c in await jr.clients()}
        if client_id not in clients:
            raise RbdError("ENOENT",
                           f"journal client {client_id} not "
                           f"bootstrapped")
        pos = clients[client_id]["position"]
        entries = await jr.entries_after(pos, limit=limit)
        if not entries:
            return 0
        dst = await Image.open(dst_ioctx, image_name, exclusive=False)
        try:
            for seq, ev, payload in entries:
                # the image's own replay helper: one dispatch switch
                # for primary catch-up and mirror replay, and it masks
                # dst.journal so nothing re-journals on the secondary
                await dst._apply_journal_event(ev, payload)
                pos = seq
        finally:
            await dst.close()
        await jr.commit(client_id, pos)
        await jr.trim()
        return len(entries)
    finally:
        await src.close()


class JournalMirrorDaemon:
    """Tail-and-replay loop for journal-mode images."""

    def __init__(self, src_ioctx, dst_ioctx,
                 interval: float = 0.5) -> None:
        self.src = src_ioctx
        self.dst = dst_ioctx
        self.interval = interval
        self._task: asyncio.Task | None = None
        self._stopped = False

    async def replay_all(self) -> dict:
        out = {}
        for name in await mirror_enabled(self.src, mode="journal"):
            try:
                out[name] = await journal_replay_once(
                    self.src, self.dst, name)
            except (RbdError, RadosError, ConnectionError,
                    OSError) as e:
                out[name] = f"error: {e}"
        return out

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._loop())

    async def _loop(self) -> None:
        try:
            while not self._stopped:
                await self.replay_all()
                await asyncio.sleep(self.interval)
        except asyncio.CancelledError:
            pass

    async def stop(self) -> None:
        self._stopped = True
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
