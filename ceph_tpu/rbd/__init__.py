"""librbd analog: block images on RADOS.

Reference: src/librbd (io path ImageRequest.cc -> ObjectRequest ->
Objecter; metadata via cls_rbd).  See rbd.py.
"""

from .rbd import RBD, Image, RbdError

__all__ = ["RBD", "Image", "RbdError"]
