"""librbd image encryption: AES-256-XTS under the I/O path.

src/librbd/crypto role (CryptoObjectDispatch + luks EncryptionFormat):
a random DATA key encrypts every data-object sector with AES-XTS,
tweaked by (object id, sector number) so identical plaintext never
repeats ciphertext; the data key is wrapped (AES-GCM) by a key
derived from the user's passphrase (PBKDF2), and the envelope lives
on the image header -- so the passphrase can change without
re-encrypting data, and an image is unreadable without it.

The crypto sits BELOW the ObjectCacher (the cache holds plaintext,
exactly the reference's dispatch-layer ordering) and above the ioctx:
``CryptoIoCtx`` is a duck-typed ioctx whose object read/write
decrypt/encrypt transparently, read-modify-writing partial sectors
(safe under the image's exclusive single-writer lock).
"""

from __future__ import annotations

import hashlib
import json
import os

SECTOR = 4096
ENVELOPE_XATTR = "rbd.encryption"
_KDF_ITERS = 200_000


class WrongPassphrase(Exception):
    pass


def _kek(passphrase: str, salt: bytes) -> bytes:
    return hashlib.pbkdf2_hmac("sha256", passphrase.encode(), salt,
                               _KDF_ITERS)


def make_envelope(passphrase: str) -> tuple[dict, bytes]:
    """(header envelope, raw 64-byte XTS data key)."""
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    data_key = os.urandom(64)            # XTS = two 256-bit halves
    salt = os.urandom(16)
    nonce = os.urandom(12)
    wrapped = AESGCM(_kek(passphrase, salt)).encrypt(
        nonce, data_key, b"rbd-luks")
    return ({"cipher": "aes-256-xts", "kdf": "pbkdf2-sha256",
             "iters": _KDF_ITERS, "salt": salt.hex(),
             "nonce": nonce.hex(), "wrapped_key": wrapped.hex(),
             "sector": SECTOR}, data_key)


def unwrap_key(envelope: dict, passphrase: str) -> bytes:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    try:
        return AESGCM(
            _kek(passphrase, bytes.fromhex(envelope["salt"]))
        ).decrypt(bytes.fromhex(envelope["nonce"]),
                  bytes.fromhex(envelope["wrapped_key"]), b"rbd-luks")
    except Exception as e:
        raise WrongPassphrase("cannot unwrap data key "
                              "(wrong passphrase?)") from e


class CryptoIoCtx:
    """Duck-typed ioctx: object data reads/writes pass through
    AES-256-XTS at sector granularity; everything else passes through
    untouched (header/omap ops stay plaintext metadata)."""

    def __init__(self, ioctx, data_key: bytes) -> None:
        self.ioctx = ioctx
        self._key = data_key

    def _xts(self, oid: str, sector: int):
        from cryptography.hazmat.primitives.ciphers import (
            Cipher, algorithms, modes)
        tweak = (hashlib.md5(oid.encode()).digest()[:8]
                 + sector.to_bytes(8, "little"))
        return Cipher(algorithms.AES(self._key), modes.XTS(tweak))

    def _enc(self, oid: str, sector: int, plain: bytes) -> bytes:
        e = self._xts(oid, sector).encryptor()
        return e.update(plain) + e.finalize()

    def _dec(self, oid: str, sector: int, ct: bytes) -> bytes:
        d = self._xts(oid, sector).decryptor()
        return d.update(ct) + d.finalize()

    async def read(self, oid, length=None, offset: int = 0, **kw):
        if length is None:
            raw = await self.ioctx.read(oid, **kw)
            end = len(raw)
            s0 = 0
        else:
            s0 = offset // SECTOR
            end = offset + length
            raw = await self.ioctx.read(
                oid, length=((end + SECTOR - 1) // SECTOR * SECTOR
                             - s0 * SECTOR),
                offset=s0 * SECTOR, **kw)
        out = bytearray()
        zero = b"\x00" * SECTOR
        for i in range(0, len(raw), SECTOR):
            chunk = bytes(raw[i:i + SECTOR])
            if chunk == zero:
                # a HOLE: sparse objects zero-fill unwritten ranges
                # below EOF, and decrypting plaintext zeros would
                # return garbage.  Real ciphertext is never all-zero
                # (XTS of any sector; p ~ 2^-32768), so all-zero means
                # unwritten -- the sparse-read extent skip the
                # reference's crypto dispatch does, by value
                out += chunk
            elif len(chunk) == SECTOR:
                out += self._dec(oid, s0 + i // SECTOR, chunk)
            elif chunk:
                # a short tail only happens on the object's final
                # partial sector, which was stored padded; decrypt of
                # a non-multiple is impossible in XTS<16B, so pad-read
                out += self._dec(oid, s0 + i // SECTOR,
                                 chunk.ljust(SECTOR, b"\x00"))[
                                     :len(chunk)]
        if length is None:
            return bytes(out)
        return bytes(out[offset - s0 * SECTOR:
                         offset - s0 * SECTOR + length])

    async def write(self, oid, data, offset: int = 0):
        end = offset + len(data)
        s0, s1 = offset // SECTOR, (end + SECTOR - 1) // SECTOR
        aligned = bytearray((s1 - s0) * SECTOR)
        # partial head/tail sectors: read-modify-write the plaintext
        # (single writer under the exclusive lock).  A missing object
        # is all zeros here; plain read() still propagates ENOENT so
        # the image layer's hole/parent fallback keeps working
        if offset % SECTOR or end % SECTOR:
            from ..client.rados import RadosError
            try:
                existing = await self.read(
                    oid, length=len(aligned), offset=s0 * SECTOR)
                aligned[:len(existing)] = existing
            except RadosError as e:
                if e.errno_name != "ENOENT":
                    raise
        aligned[offset - s0 * SECTOR:end - s0 * SECTOR] = data
        ct = bytearray()
        for i in range(0, len(aligned), SECTOR):
            ct += self._enc(oid, s0 + i // SECTOR,
                            bytes(aligned[i:i + SECTOR]))
        # store full padded sectors; logical size tracking lives above
        # (image size / striper size xattrs), so trailing zero pad is
        # invisible to readers
        await self.ioctx.write(oid, bytes(ct), offset=s0 * SECTOR)
        return len(data)

    def encrypt_full(self, oid: str, data: bytes) -> bytes:
        """Sector-encrypt a whole-object payload starting at offset 0
        (for atomic cls copyup, which bypasses the write path)."""
        pad = (len(data) + SECTOR - 1) // SECTOR * SECTOR
        buf = bytes(data).ljust(pad, b"\x00")
        ct = bytearray()
        for i in range(0, pad, SECTOR):
            ct += self._enc(oid, i // SECTOR, buf[i:i + SECTOR])
        return bytes(ct)

    async def truncate(self, oid, size: int):
        # ciphertext is stored in whole sectors: cut on the next
        # sector boundary, then RE-ENCRYPT the kept sector's tail as
        # zeros -- otherwise stale pre-shrink bytes resurface after a
        # later grow (the plain path's exact truncate + zero-pad
        # guarantees zeros there)
        aligned = (size + SECTOR - 1) // SECTOR * SECTOR
        out = await self.ioctx.truncate(oid, aligned)
        if aligned != size:
            await self.write(oid, b"\x00" * (aligned - size),
                             offset=size)
        return out

    async def zero(self, oid, off: int, n: int):
        """Deallocate/zero a range.  Whole sectors go down as RAW
        zeros (which reads already interpret as holes -- see the
        all-zero heuristic), so discard stays a deallocation; partial
        edge sectors must be re-encrypted with zeroed bytes."""
        from ..client.rados import RadosError
        end = off + n
        s_start = (off + SECTOR - 1) // SECTOR * SECTOR
        s_end = end // SECTOR * SECTOR
        try:
            if off % SECTOR and off < min(s_start, end):
                await self.write(oid, b"\x00" * (min(s_start, end)
                                                 - off), offset=off)
            if s_end > s_start:
                await self.ioctx.zero(oid, s_start, s_end - s_start)
            if end % SECTOR and end > max(s_end, off) \
                    and s_end >= s_start:
                await self.write(oid, b"\x00" * (end - max(s_end,
                                                           off)),
                                 offset=max(s_end, off))
        except RadosError as e:
            if e.errno_name != "ENOENT":
                raise            # nothing there: discard is a no-op

    def __getattr__(self, name):
        return getattr(self.ioctx, name)


async def format_encryption(ioctx, header_oid: str,
                            passphrase: str) -> bytes:
    """Write the LUKS-style envelope onto the image header; returns
    the unwrapped data key.  Must run before any data is written."""
    envelope, key = make_envelope(passphrase)
    await ioctx.set_xattr(header_oid, ENVELOPE_XATTR,
                          json.dumps(envelope).encode())
    return key


async def load_key(ioctx, header_oid: str,
                   passphrase: str) -> bytes | None:
    """The image's data key, or None when the image is unencrypted."""
    from ..client.rados import RadosError
    try:
        raw = await ioctx.get_xattr(header_oid, ENVELOPE_XATTR)
    except RadosError:
        return None
    if raw is None:
        return None
    return unwrap_key(json.loads(raw), passphrase)
