"""Block images on RADOS (librbd analog).

Layout follows rbd format 2 (src/librbd/image/CreateRequest.cc):

    rbd_directory                  pool-wide name <-> id registry (omap)
    rbd_children                   parent(pool,image,snap) -> child ids
    rbd_header.<id>                image metadata omap (cls_rbd methods)
    rbd_data.<id>.<objectno:016x>  data objects, 2^order bytes each

The I/O path mirrors src/librbd/io/ImageRequest.cc: an image extent is
cut into per-object extents (the striper's map_extents with
su=2^order, sc=1 by default; fancy striping supported), object ops are
issued concurrently through the objecter, and clone reads fall back to
the parent snapshot within the overlap (ObjectReadRequest's copyup
path, src/librbd/io/CopyupRequest.cc does the write-side copyup).

Image snapshots ARE RADOS self-managed snapshots: snap ids come from
the pool (librbd takes them from the mon the same way), the header's
snap table (cls_rbd get_snapcontext) provides the write snap context,
and snap reads pass the snap id down the rados read
(src/librbd/Operations.cc snap_create -> cls_rbd snapshot_add).

Exclusive-lock feature: a cls_lock exclusive lock on the header with
periodic renewal (ManagedLock.cc semantics without the blacklist --
expiry substitutes for blocklisting a dead holder).
"""

from __future__ import annotations

import asyncio
import json
import os

from ..client.rados import IoCtx, RadosError
from ..client.striper import Layout, map_extents

RBD_DIRECTORY = "rbd_directory"
RBD_CHILDREN = "rbd_children"
LOCK_NAME = "rbd_lock"
LOCK_RENEW_S = 10.0
LOCK_DURATION_S = 30.0


class RbdError(Exception):
    def __init__(self, errno_name: str, detail: str = "") -> None:
        super().__init__(f"{errno_name}{': ' + detail if detail else ''}")
        self.errno_name = errno_name


def _wrap(e: RadosError) -> RbdError:
    return RbdError(e.errno_name, str(e))


def _header(iid: str) -> str:
    return f"rbd_header.{iid}"


class RBD:
    """Image management entry points (librbd.h rbd_create/list/remove)."""

    async def create(self, ioctx, name: str, size: int, order: int = 22,
                     stripe_unit: int | None = None,
                     stripe_count: int = 1,
                     features: list[str] | None = None) -> str:
        iid = os.urandom(8).hex()
        try:
            await ioctx.exec(RBD_DIRECTORY, "rbd", "dir_add_image",
                             json.dumps({"name": name,
                                         "id": iid}).encode())
        except RadosError as e:
            raise _wrap(e) from e
        try:
            await ioctx.exec(_header(iid), "rbd", "create", json.dumps({
                "size": int(size), "order": order,
                "object_prefix": f"rbd_data.{iid}",
                "features": features or ["layering"],
                "stripe_unit": stripe_unit or (1 << order),
                "stripe_count": stripe_count}).encode())
        except RadosError as e:
            # roll the directory entry back so a failed create does not
            # leave a dangling name
            await ioctx.exec(RBD_DIRECTORY, "rbd", "dir_remove_image",
                             json.dumps({"name": name}).encode())
            raise _wrap(e) from e
        return iid

    async def list(self, ioctx) -> list[str]:
        try:
            out = await ioctx.exec(RBD_DIRECTORY, "rbd", "dir_list", b"")
        except RadosError as e:
            if e.errno_name == "ENOENT":
                return []
            raise _wrap(e) from e
        return sorted(json.loads(out))

    async def remove(self, ioctx, name: str) -> None:
        img = await Image.open(ioctx, name, read_only=True,
                               admin=True)
        try:
            from .migration import (MIG_DST_XATTR, MIG_SRC_XATTR,
                                    _get_marker)
            for xattr in (MIG_SRC_XATTR, MIG_DST_XATTR):
                if await _get_marker(ioctx, img.id, xattr):
                    raise RbdError(
                        "EBUSY", "image is migrating "
                                 "(commit or abort first)")
            if any(s.get("protected") for s in img.meta["snapshots"]):
                raise RbdError("EBUSY", "image has protected snapshots")
            if img.meta["snapshots"]:
                raise RbdError("ENOTEMPTY",
                               "image has snapshots (remove them first)")
            if img.meta.get("parent"):
                p = img.meta["parent"]
                await ioctx.exec(RBD_CHILDREN, "rbd", "remove_child",
                                 json.dumps({**p,
                                             "child_id": img.id}).encode())
            n_objs = img._object_count(img.meta["size"])
            await _gather_bounded(
                [img._remove_data_obj(i) for i in range(n_objs)])
        finally:
            await img.close()
        # feature sidecars die with the image (journal payloads and
        # object maps have no other owner)
        from .features import journal_oid, object_map_oid
        for oid in (journal_oid(img.id), object_map_oid(img.id)):
            try:
                await ioctx.remove(oid)
            except RadosError:
                pass
        try:
            await ioctx.remove(_header(img.id))
            await ioctx.exec(RBD_DIRECTORY, "rbd", "dir_remove_image",
                             json.dumps({"name": name}).encode())
        except RadosError as e:
            raise _wrap(e) from e

    async def clone(self, parent_ioctx, parent_name: str,
                    snap_name: str, child_ioctx, child_name: str,
                    order: int | None = None) -> str:
        """COW clone of a PROTECTED parent snapshot
        (librbd::clone, src/librbd/image/CloneRequest.cc)."""
        p = await Image.open(parent_ioctx, parent_name, read_only=True)
        try:
            snap = p._snap_by_name(snap_name)
            if not snap.get("protected"):
                raise RbdError("EINVAL", "parent snap is not protected")
            child_order = order or p.meta["order"]
            iid = await self.create(child_ioctx, child_name,
                                    snap["size"], order=child_order)
            await child_ioctx.exec(
                _header(iid), "rbd", "set_parent", json.dumps({
                    "pool_id": parent_ioctx.pool_id, "image_id": p.id,
                    "snap_id": snap["id"],
                    "overlap": snap["size"]}).encode())
            await parent_ioctx.exec(
                RBD_CHILDREN, "rbd", "add_child", json.dumps({
                    "pool_id": parent_ioctx.pool_id, "image_id": p.id,
                    "snap_id": snap["id"], "child_id": iid}).encode())
            return iid
        except RadosError as e:
            raise _wrap(e) from e
        finally:
            await p.close()


async def _gather_bounded(coros, limit: int = 16):
    """Bounded-concurrency gather: image-wide sweeps (remove, flatten,
    rollback) touch every object and would otherwise flood the cluster
    with one op per object at once."""
    sem = asyncio.Semaphore(limit)

    async def one(c):
        async with sem:
            return await c
    return await asyncio.gather(*(one(c) for c in coros))


class Image:
    """An open image handle (librbd::Image).

    Use ``await Image.open(ioctx, name)``; close() releases the
    exclusive lock and stops its renewal.
    """

    def __init__(self, ioctx, name: str, iid: str, meta: dict,
                 read_only: bool, snap_id: int | None) -> None:
        self.ioctx = ioctx
        self.name = name
        self.id = iid
        self.meta = meta
        self.read_only = read_only
        self.snap_id = snap_id
        self._cookie = os.urandom(4).hex()
        self._watch_cookie = None
        self._renew_task: asyncio.Task | None = None
        self._parent: Image | None = None
        self._closed = False
        self._fenced = False
        # write-back cache (ObjectCacher), bound at open(cache=True)
        self.cacher = None
        # DATA-path ioctx: plain, or a CryptoIoCtx when the image is
        # encrypted (crypto sits below the cache, above the wire)
        self._dio = ioctx
        self._no_data_key = False
        # live migration: destination images fall through to the
        # source for not-yet-copied data (librbd/migration)
        self._mig_marker: dict | None = None
        self._mig_src: "Image | None" = None
        # feature handles (object-map / journaling), bound at open
        from .features import (FEATURE_JOURNALING, FEATURE_OBJECT_MAP,
                               ImageJournal, ObjectMap)
        feats = set(meta.get("features", []))
        self.object_map = (ObjectMap(self)
                           if FEATURE_OBJECT_MAP in feats else None)
        self.journal = (ImageJournal(ioctx, iid)
                        if FEATURE_JOURNALING in feats else None)

    # -- open/close ---------------------------------------------------------
    @staticmethod
    async def open(ioctx, name: str, snapshot: str | None = None,
                   read_only: bool = False,
                   exclusive: bool = True,
                   cache: bool = False,
                   passphrase: str | None = None,
                   admin: bool = False) -> "Image":
        """``exclusive=False`` opens writable WITHOUT taking the image
        lock -- for snapshot-only administrative handles (rbd-mirror
        snapshots a live image without stealing the client's lock; the
        header mutations are atomic cls ops).  Data writes through a
        non-exclusive handle forgo single-writer protection.

        ``cache=True`` puts an ObjectCacher under the data path
        (rbd_cache): writes ack from cache and flush in the
        background or at barriers (flush/close/snap/lock-loss); safe
        only under the exclusive lock, which guarantees the single
        writer the cache assumes."""
        try:
            iid = (await ioctx.exec(
                RBD_DIRECTORY, "rbd", "dir_get_id",
                json.dumps({"name": name}).encode())).decode()
            meta = json.loads(await ioctx.exec(
                _header(iid), "rbd", "get_image_meta", b""))
        except RadosError as e:
            raise _wrap(e) from e
        # every image gets a PRIVATE ioctx: the snap context installed
        # by _refresh_snapc is per-ioctx state, and a second image
        # opened on a shared ioctx would clobber the first image's
        # write snapc (silently skipping COW for its snapshots)
        ioctx = IoCtx(ioctx.rados, ioctx.pool_name, ioctx.pool_id)
        snap_id = None
        img = Image(ioctx, name, iid, meta, read_only or bool(snapshot),
                    snap_id)
        # encryption gate BEFORE any data I/O: an encrypted image
        # without its passphrase must refuse, not serve ciphertext
        from .crypto import (CryptoIoCtx, ENVELOPE_XATTR,
                             WrongPassphrase, unwrap_key)
        try:
            env_raw = await ioctx.get_xattr(_header(iid),
                                            ENVELOPE_XATTR)
        except RadosError as e:
            # ONLY absence means unencrypted; a transient error must
            # not bypass the gate and serve ciphertext as plaintext
            if e.errno_name not in ("ENOENT", "ENODATA"):
                raise _wrap(e) from e
            env_raw = None
        if env_raw and passphrase is None:
            if not admin:
                raise RbdError(
                    "EPERM", "image is encrypted; passphrase required")
            # administrative handle (remove, status): may touch
            # metadata and delete objects, but data I/O is refused --
            # it would serve ciphertext as plaintext
            img._no_data_key = True
        if passphrase is not None:
            if not env_raw:
                raise RbdError("EINVAL", "image is not encrypted")
            try:
                key = unwrap_key(json.loads(env_raw), passphrase)
            except WrongPassphrase as e:
                raise RbdError("EPERM", str(e)) from e
            img._dio = CryptoIoCtx(img.ioctx, key)
        if snapshot is not None:
            img.snap_id = img._snap_by_name(snapshot)["id"]
        from .migration import (MIG_DST_XATTR, MIG_SRC_XATTR,
                                _get_marker)
        img._mig_marker, mig_dst = await asyncio.gather(
            _get_marker(ioctx, iid, MIG_SRC_XATTR),
            _get_marker(ioctx, iid, MIG_DST_XATTR))
        if not img.read_only and mig_dst:
            # this image is the SOURCE of a live migration: clients
            # must use the destination; the source serves reads only
            img.read_only = True
        if not img.read_only and exclusive:
            await img._acquire_lock()
            if img.journal is not None:
                # the journal is AUTHORITATIVE: events appended by a
                # writer that died before applying them locally replay
                # on the next open (librbd journal::Replay), so the
                # primary can never lag its own journal (and never
                # diverge from a mirror that already replayed them)
                await img._journal_local_replay()
            # header watch (librbd's ImageWatcher): another client's
            # snap/resize refreshes OUR snap context before their op
            # completes -- writing with a stale snapc would skip the
            # COW that keeps the new snapshot frozen
            img._watch_cookie = await img.ioctx.watch(
                _header(img.id), img._on_header_notify)
            if cache:
                from ..client.object_cacher import ObjectCacher
                img.cacher = ObjectCacher(img._dio)
        await img._refresh_snapc()
        return img

    async def encryption_format(self, passphrase: str) -> None:
        """Format THIS image for encryption (rbd encryption format):
        writes the LUKS-style envelope and switches the data path to
        AES-XTS.  Only valid on a fresh image -- existing plaintext
        data is not re-encrypted (the reference has the same rule)."""
        from .crypto import (CryptoIoCtx, ENVELOPE_XATTR,
                             format_encryption)
        self._writable_or_raise()
        try:
            existing = await self.ioctx.get_xattr(_header(self.id),
                                                  ENVELOPE_XATTR)
        except RadosError as e:
            if e.errno_name not in ("ENOENT", "ENODATA"):
                raise _wrap(e) from e
            existing = None
        if existing:
            raise RbdError("EEXIST", "image is already encrypted")
        key = await format_encryption(self.ioctx, _header(self.id),
                                      passphrase)
        self._dio = CryptoIoCtx(self.ioctx, key)
        if self.cacher is not None:
            self.cacher.ioctx = self._dio

    async def _on_header_notify(self, payload: bytes) -> None:
        try:
            if self.cacher is not None:
                # another client changed the header (snap/resize): our
                # buffered writes must land under the OLD snapc before
                # we refresh, and cached cleans may be stale
                await self.cacher.invalidate()
            await self._refresh_meta()
            await self._refresh_snapc()
        except RadosError:
            pass                   # next header op retries the refresh

    async def _notify_header(self) -> None:
        """Tell every open handle the header changed (snap created/
        removed, resized); waits for their refresh acks."""
        try:
            await self.ioctx.notify(_header(self.id), b"header-update",
                                    timeout=5.0)
        except RadosError:
            pass                   # no watchers / transient: best effort

    async def flush(self) -> None:
        """Durability barrier (rbd_flush): buffered writes are at the
        OSDs on return."""
        if self.cacher is not None:
            await self.cacher.flush()

    async def close(self) -> None:
        if self._closed:
            return
        flush_err = None
        if self.cacher is not None:
            if self._fenced:
                # a fenced handle's dirty data must DIE: the new lock
                # owner's view wins, and our writes would be refused
                # at the OSDs anyway
                self.cacher.discard_all()
            try:
                await self.cacher.close()
            except BaseException as e:
                # the final flush failed: STILL tear down (lock, watch,
                # renew task -- leaking them blocks other clients), but
                # surface the data loss to the caller
                flush_err = e
        self._closed = True
        if self._renew_task:
            self._renew_task.cancel()
            try:
                await self._renew_task
            except asyncio.CancelledError:
                pass
        if getattr(self, "_watch_cookie", None) is not None:
            try:
                await self.ioctx.unwatch(_header(self.id),
                                         self._watch_cookie)
            except RadosError:
                pass
        if not self.read_only:
            try:
                await self.ioctx.exec(
                    _header(self.id), "lock", "unlock", json.dumps({
                        "name": LOCK_NAME,
                        "cookie": self._cookie}).encode())
            except RadosError:
                pass
        if self._parent is not None:
            await self._parent.close()
            self._parent = None
        if self._mig_src is not None:
            await self._mig_src.close()
            self._mig_src = None
        if flush_err is not None:
            # teardown completed, but the final flush did not land:
            # the caller must know its last writes may be lost
            raise flush_err

    # -- exclusive lock (ManagedLock / cls_lock) ----------------------------
    async def _acquire_lock(self) -> None:
        try:
            await self.ioctx.exec(
                _header(self.id), "lock", "lock", json.dumps({
                    "name": LOCK_NAME, "type": "exclusive",
                    "cookie": self._cookie,
                    "duration": LOCK_DURATION_S,
                    "flags": 1}).encode())       # MAY_RENEW
        except RadosError as e:
            raise RbdError("EBUSY" if e.errno_name == "EBUSY"
                           else e.errno_name,
                           "image is locked by another client") from e
        self._renew_task = asyncio.ensure_future(self._renew_loop())

    JOURNAL_MASTER = "master"

    async def _journal_local_replay(self) -> None:
        await self.journal.register_client(self.JOURNAL_MASTER)
        clients = {c["id"]: c for c in await self.journal.clients()}
        pos = clients[self.JOURNAL_MASTER]["position"]
        entries = await self.journal.entries_after(pos, limit=10000)
        for seq, ev, payload in entries:
            await self._apply_journal_event(ev, payload)
            pos = seq
        if entries:
            await self.journal.commit(self.JOURNAL_MASTER, pos)
            await self.journal.trim()

    async def _apply_journal_event(self, ev: dict,
                                   payload: bytes) -> None:
        """Re-apply one journaled event WITHOUT re-journaling it."""
        jr, self.journal = self.journal, None
        try:
            op = ev.get("op")
            if op == "write":
                if ev["off"] + len(payload) > self.meta["size"]:
                    await self.resize(ev["off"] + len(payload))
                await self.write(ev["off"], payload)
            elif op == "discard":
                await self.discard(ev["off"], ev["len"])
            elif op == "resize":
                await self.resize(ev["size"])
            elif op == "snap_create":
                try:
                    await self.create_snap(ev["name"])
                except RbdError as e:
                    if e.errno_name != "EEXIST":
                        raise
        finally:
            self.journal = jr

    async def _journal_commit(self, seq: int) -> None:
        """The local apply landed: the master client is caught up."""
        try:
            await self.journal.commit(self.JOURNAL_MASTER, seq)
            await self.journal.trim()
        except RadosError:
            pass          # next open's replay re-applies idempotently

    def _writable_or_raise(self) -> None:
        if self.read_only:
            raise RbdError("EROFS")
        if self._fenced:
            raise RbdError("EBLOCKLISTED",
                           "exclusive lock lost; handle is fenced")

    async def _renew_once(self) -> None:
        try:
            await self.ioctx.exec(
                _header(self.id), "lock", "lock", json.dumps({
                    "name": LOCK_NAME, "type": "exclusive",
                    "cookie": self._cookie,
                    "duration": LOCK_DURATION_S,
                    "flags": 1}).encode())
        except RadosError as e:
            # EBUSY: our lease expired and ANOTHER client holds the
            # lock; ENOENT: the lock/header vanished.  Either way we
            # are no longer the single writer -- fence the handle so
            # no further data write can race the new owner (librbd
            # pairs lock loss with an OSD blocklist of the old client;
            # ManagedLock.cc / image_watcher).
            if e.errno_name in ("EBUSY", "ENOENT"):
                self._fenced = True
                if self.cacher is not None:
                    # lock lost: buffered writes must not land late
                    self.cacher.discard_all()
            # other errors (transient): retried next period
        except (ConnectionError, OSError):
            pass                      # retried next period; expiry wins

    async def _renew_loop(self) -> None:
        while not self._fenced:
            await asyncio.sleep(LOCK_RENEW_S)
            await self._renew_once()

    @staticmethod
    async def break_lock(ioctx, name: str,
                         blocklist: bool = True) -> None:
        """Evict a dead client's exclusive lock (rbd lock break).

        The deposed holder is BLOCKLISTED at the OSDs first: if it is
        wedged rather than dead, its delayed writes must not land on
        an image someone else now owns (rbd lock break pairs with
        'osd blocklist' exactly like this; ManagedLock.cc
        break_lock + blacklist)."""
        iid = (await ioctx.exec(RBD_DIRECTORY, "rbd", "dir_get_id",
                                json.dumps({"name": name}).encode())
               ).decode()
        info = json.loads(await ioctx.exec(
            _header(iid), "lock", "get_info",
            json.dumps({"name": LOCK_NAME}).encode()))
        for lk in info["lockers"]:
            if blocklist:
                await ioctx.rados.mon_command(
                    "osd blocklist", {"id": lk["entity"],
                                      "duration": 600})
            await ioctx.exec(_header(iid), "lock", "break_lock",
                             json.dumps({"name": LOCK_NAME,
                                         "locker": lk["entity"],
                                         "cookie": lk["cookie"]}).encode())

    # -- geometry -----------------------------------------------------------
    @property
    def _layout(self) -> Layout:
        osz = 1 << self.meta["order"]
        return Layout(stripe_unit=self.meta.get("stripe_unit", osz),
                      stripe_count=self.meta.get("stripe_count", 1),
                      object_size=osz)

    def _data_obj(self, objectno: int) -> str:
        return f"{self.meta['object_prefix']}.{objectno:016x}"

    def _object_count(self, size: int) -> int:
        if size == 0:
            return 0
        return max(e[0] for e in map_extents(self._layout, 0, size)) + 1

    def _snap_by_name(self, snap_name: str) -> dict:
        for s in self.meta["snapshots"]:
            if s["name"] == snap_name:
                return s
        raise RbdError("ENOENT", f"no snapshot {snap_name}")

    async def _refresh_meta(self) -> None:
        self.meta = json.loads(await self.ioctx.exec(
            _header(self.id), "rbd", "get_image_meta", b""))

    async def _refresh_snapc(self) -> None:
        """Install the image's snap context on the data ioctx so every
        write COWs against the image's snapshots."""
        snapc = json.loads(await self.ioctx.exec(
            _header(self.id), "rbd", "get_snapcontext", b""))
        self.ioctx.set_snap_context(snapc["seq"], snapc["snaps"])

    async def size(self) -> int:
        if self.snap_id is not None:
            for s in self.meta["snapshots"]:
                if s["id"] == self.snap_id:
                    return s["size"]
        return self.meta["size"]

    def stat(self) -> dict:
        return {"size": self.meta["size"], "order": self.meta["order"],
                "id": self.id, "object_prefix": self.meta["object_prefix"],
                "num_objs": self._object_count(self.meta["size"]),
                "parent": self.meta.get("parent"),
                "snapshots": self.meta["snapshots"]}

    # -- parent (clone) plumbing -------------------------------------------
    async def _get_parent(self) -> "Image | None":
        pref = self.meta.get("parent")
        if pref is None:
            return None
        if self._parent is None:
            pools = self.ioctx.objecter.osdmap.pool_names
            pname = next((n for n, i in pools.items()
                          if i == pref["pool_id"]), None)
            if pname is None:
                raise RbdError("ENOENT", "parent pool vanished")
            pioctx = await self.ioctx.rados.open_ioctx(pname)
            meta = json.loads(await pioctx.exec(
                _header(pref["image_id"]), "rbd", "get_image_meta", b""))
            self._parent = Image(pioctx, "", pref["image_id"], meta,
                                 True, pref["snap_id"])
        return self._parent

    async def _mig_source_img(self) -> "Image | None":
        if self._mig_marker is None:
            return None
        if self._mig_src is None:
            from .migration import _open_source
            self._mig_src = await _open_source(self)
        return self._mig_src

    async def _read_below(self, off: int, length: int) -> bytes:
        """Data for a hole: the live-migration source if one exists,
        else the clone parent, else zeros."""
        src = await self._mig_source_img()
        if src is not None:
            n = min(length, max(0, src.meta["size"] - off))
            buf = await src.read(off, n) if n else b""
            return buf + b"\0" * (length - len(buf))
        if self.meta.get("parent"):
            return await self._read_parent(off, length)
        return b"\0" * length

    async def _read_parent(self, off: int, length: int) -> bytes:
        """Read [off, off+length) from the parent snapshot, clipped to
        the overlap; beyond-overlap reads are zeros."""
        parent = await self._get_parent()
        # a shrink below the overlap implicitly truncates it (the
        # reference updates the overlap on resize; clipping reads the
        # same way keeps one source of truth -- the current size)
        overlap = min(self.meta["parent"]["overlap"], self.meta["size"])
        if parent is None or off >= overlap:
            return b"\0" * length
        n = min(length, overlap - off)
        buf = await parent.read(off, n)
        return buf + b"\0" * (length - len(buf))

    # -- data path ----------------------------------------------------------
    async def read(self, off: int, length: int) -> bytes:
        if self._no_data_key:
            raise RbdError("EPERM", "encrypted image opened without "
                                    "its passphrase (admin handle)")
        size = await self.size()
        if off >= size:
            return b""
        length = min(length, size - off)
        lay = self._layout
        extents = map_extents(lay, off, length)

        async def read_one(idx, objectno, obj_off, n):
            if self.cacher is not None and self.snap_id is None:
                logical0 = logical[idx]

                async def miss(o, ln):
                    # miss path inside the cacher: object read with
                    # hole -> parent/zero fallback (clone reads)
                    try:
                        got = await self._dio.read(
                            self._data_obj(objectno), length=ln,
                            offset=o)
                        return got
                    except RadosError as e:
                        if e.errno_name != "ENOENT":
                            raise
                    return await self._read_below(
                        logical0 + (o - obj_off), ln)

                buf = await self.cacher.read(
                    self._data_obj(objectno), obj_off, n, reader=miss)
                return idx, buf, False
            try:
                buf = await self._dio.read(
                    self._data_obj(objectno), length=n, offset=obj_off,
                    snap=self.snap_id)
                return idx, buf + b"\0" * (n - len(buf)), False
            except RadosError as e:
                if e.errno_name != "ENOENT":
                    raise
                return idx, None, True      # hole: maybe parent data

        jobs = []
        logical = []                        # per-extent image offset
        pos = off
        for i, (objectno, obj_off, n) in enumerate(extents):
            jobs.append(read_one(i, objectno, obj_off, n))
            logical.append(pos)
            pos += n
        done = await asyncio.gather(*jobs)
        pieces: list[bytes] = [b""] * len(extents)
        for idx, buf, hole in done:
            if hole:
                n = extents[idx][2]
                buf = await self._read_below(logical[idx], n)
            pieces[idx] = buf
        return b"".join(pieces)

    async def _copyup(self, objectno: int) -> None:
        """First write to a clone's missing object: materialize the
        parent's bytes for the whole object first (CopyupRequest)."""
        lay = self._layout
        obj_logical = objectno * lay.object_size   # sc==1 path
        if self._mig_marker is not None:
            bound = self.meta["size"]
        else:
            bound = min(self.meta["parent"]["overlap"],
                        self.meta["size"])
        if obj_logical >= bound:
            return
        n = min(lay.object_size, bound - obj_logical)
        buf = await self._read_below(obj_logical, n)
        if buf.strip(b"\0"):
            try:
                await self._copyup_atomic(self._data_obj(objectno),
                                          buf)
            except RadosError as e:
                raise _wrap(e) from e

    async def _copyup_atomic(self, oid: str, buf: bytes) -> None:
        """Materialize an object from below-data ONLY if still absent
        (cls rbd copyup): atomic at the OSD, so a migration copier and
        a live client writer can race -- first creator wins, the other
        no-ops and never clobbers newer data.  Encrypted images ship
        the payload pre-encrypted (the cls path bypasses CryptoIoCtx)."""
        if self._dio is not self.ioctx:
            buf = self._dio.encrypt_full(oid, buf)
        await self.ioctx.exec(oid, "rbd", "copyup", bytes(buf))

    async def write(self, off: int, data: bytes) -> int:
        if self._no_data_key:
            raise RbdError("EPERM", "encrypted image opened without "
                                    "its passphrase (admin handle)")
        self._writable_or_raise()
        size = self.meta["size"]
        if off + len(data) > size:
            raise RbdError("EINVAL", "write past end of image")
        lay = self._layout
        has_parent = bool(self.meta.get("parent")) \
            or self._mig_marker is not None
        jseq = None
        if self.journal is not None:
            # journal-safe ordering: the event is durable BEFORE the
            # image mutates; the master position commits after the
            # local apply, so a crash in between replays it on reopen
            jseq = await self.journal.append(
                {"op": "write", "off": off, "len": len(data)},
                bytes(data))

        async def write_one(objectno, obj_off, piece):
            if self.object_map is not None:
                await self.object_map.mark_written(objectno)
            if has_parent and lay.stripe_count == 1:
                try:
                    await self.ioctx.stat(self._data_obj(objectno))
                except RadosError as e:
                    if e.errno_name == "ENOENT":
                        await self._copyup(objectno)
                    else:
                        raise
            if self.cacher is not None:
                await self.cacher.write(self._data_obj(objectno),
                                        obj_off, piece)
            else:
                await self._dio.write(self._data_obj(objectno),
                                      piece, offset=obj_off)

        jobs = []
        pos = 0
        for objectno, obj_off, n in map_extents(lay, off, len(data)):
            jobs.append(write_one(objectno, obj_off,
                                  data[pos:pos + n]))
            pos += n
        try:
            await asyncio.gather(*jobs)
        except RadosError as e:
            raise _wrap(e) from e
        if jseq is not None:
            await self._journal_commit(jseq)
        return len(data)

    async def discard(self, off: int, length: int) -> None:
        """Deallocate a range: whole objects are removed, partial
        ranges zeroed (ImageRequest discard)."""
        self._writable_or_raise()
        if self.cacher is not None:
            # buffered writes ordered BEFORE the discard must land
            # first; cached extents in the range are then stale (the
            # flusher must never resurrect a discarded object)
            await self.cacher.flush()
            lay0 = self._layout
            for objectno, _, _ in map_extents(lay0, off, length):
                self.cacher.discard(self._data_obj(objectno))
        lay = self._layout
        has_parent = bool(self.meta.get("parent")) \
            or self._mig_marker is not None
        jseq = None
        if self.journal is not None:
            jseq = await self.journal.append(
                {"op": "discard", "off": off, "len": length})

        async def one(objectno, obj_off, n):
            oid = self._data_obj(objectno)
            try:
                if obj_off == 0 and n == lay.object_size \
                        and not has_parent:
                    await self.ioctx.remove(oid)
                    if self.object_map is not None:
                        await self.object_map.mark_removed(objectno)
                    return
                if has_parent and lay.stripe_count == 1:
                    # an absent clone object must copyup first: a bare
                    # zero() is a no-op on a missing object and reads
                    # would fall through to PARENT bytes, not zeros
                    try:
                        await self.ioctx.stat(oid)
                    except RadosError as e:
                        if e.errno_name != "ENOENT":
                            raise
                        await self._copyup(objectno)
                await self._dio.zero(oid, obj_off, n)
            except RadosError as e:
                if e.errno_name != "ENOENT":
                    raise
        try:
            await _gather_bounded(
                [one(*e) for e in map_extents(lay, off, length)])
        except RadosError as e:
            raise _wrap(e) from e
        if jseq is not None:
            await self._journal_commit(jseq)

    async def _remove_data_obj(self, objectno: int) -> None:
        try:
            await self.ioctx.remove(self._data_obj(objectno))
        except RadosError as e:
            if e.errno_name != "ENOENT":
                raise

    # -- resize -------------------------------------------------------------
    async def resize(self, new_size: int) -> None:
        self._writable_or_raise()
        if self.cacher is not None and new_size < self.meta["size"]:
            # flush buffered writes, then drop cached state for every
            # object past the new boundary (and the boundary object:
            # its cached tail is gone)
            await self.cacher.flush()
            for i in range(max(0, self._object_count(new_size) - 1),
                           self._object_count(self.meta["size"])):
                self.cacher.discard(self._data_obj(i))
        jseq = None
        if self.journal is not None:
            jseq = await self.journal.append(
                {"op": "resize", "size": int(new_size)})
        old = self.meta["size"]
        if new_size < old:
            lay = self._layout
            keep = self._object_count(new_size)
            total = self._object_count(old)
            # trim the boundary object, drop the rest
            if new_size % lay.object_size and keep:
                boundary = self._data_obj(keep - 1)
                try:
                    await self._dio.truncate(
                        boundary, new_size % lay.object_size)
                except RadosError as e:
                    if e.errno_name != "ENOENT":
                        raise _wrap(e) from e
            await _gather_bounded(
                [self._remove_data_obj(i) for i in range(keep, total)])
            if self.object_map is not None:
                await self.object_map.truncate(keep)
        await self.ioctx.exec(_header(self.id), "rbd", "set_size",
                              json.dumps({"size": new_size}).encode())
        if jseq is not None:
            await self._journal_commit(jseq)
        await self._refresh_meta()
        await self._notify_header()

    # -- snapshots -----------------------------------------------------------
    async def create_snap(self, snap_name: str) -> int:
        self._writable_or_raise()
        if self._mig_marker is not None:
            # a snap of a half-materialized destination would change
            # content after commit (holes fall through to the source
            # HEAD, which then disappears)
            raise RbdError("EBUSY",
                           "cannot snapshot a migrating image")
        if self.cacher is not None:
            # the snapshot must contain every write acked before it:
            # cached dirty data lands under the PRE-snap snapc first
            await self.cacher.flush()
        jseq = None
        if self.journal is not None:
            jseq = await self.journal.append(
                {"op": "snap_create", "name": snap_name})
        sid = await self.ioctx.selfmanaged_snap_create()
        try:
            await self.ioctx.exec(
                _header(self.id), "rbd", "snapshot_add",
                json.dumps({"snap_id": sid,
                            "name": snap_name}).encode())
        except RadosError as e:
            await self.ioctx.selfmanaged_snap_remove(sid)
            raise _wrap(e) from e
        if self.object_map is not None:
            # freeze the map under this snap id; head entries go CLEAN
            await self.object_map.snapshot(sid)
        if jseq is not None:
            await self._journal_commit(jseq)
        await self._refresh_meta()
        await self._refresh_snapc()
        await self._notify_header()
        return sid

    async def remove_snap(self, snap_name: str) -> None:
        self._writable_or_raise()
        snap = self._snap_by_name(snap_name)
        kids = json.loads(await self.ioctx.exec(
            RBD_CHILDREN, "rbd", "list_children", json.dumps({
                "pool_id": self.ioctx.pool_id, "image_id": self.id,
                "snap_id": snap["id"]}).encode()))
        if kids:
            raise RbdError("EBUSY", f"snap has {len(kids)} children")
        if self.object_map is not None:
            from .features import object_map_oid
            try:
                await self.ioctx.remove(
                    object_map_oid(self.id, snap["id"]))
            except RadosError:
                pass
        try:
            await self.ioctx.exec(
                _header(self.id), "rbd", "snapshot_remove",
                json.dumps({"snap_id": snap["id"]}).encode())
        except RadosError as e:
            raise _wrap(e) from e
        await self.ioctx.selfmanaged_snap_remove(snap["id"])
        await self._refresh_meta()
        await self._refresh_snapc()
        await self._notify_header()

    async def protect_snap(self, snap_name: str) -> None:
        snap = self._snap_by_name(snap_name)
        await self.ioctx.exec(_header(self.id), "rbd",
                              "snapshot_protect",
                              json.dumps({"snap_id": snap["id"]}).encode())
        await self._refresh_meta()

    async def unprotect_snap(self, snap_name: str) -> None:
        snap = self._snap_by_name(snap_name)
        kids = json.loads(await self.ioctx.exec(
            RBD_CHILDREN, "rbd", "list_children", json.dumps({
                "pool_id": self.ioctx.pool_id, "image_id": self.id,
                "snap_id": snap["id"]}).encode()))
        if kids:
            raise RbdError("EBUSY", f"snap has {len(kids)} children")
        await self.ioctx.exec(_header(self.id), "rbd",
                              "snapshot_unprotect",
                              json.dumps({"snap_id": snap["id"]}).encode())
        await self._refresh_meta()

    def list_snaps(self) -> list[dict]:
        return list(self.meta["snapshots"])

    async def rollback_snap(self, snap_name: str) -> None:
        """Rewrite head data from the snapshot (Operations::snap_rollback).
        Object-by-object copy of the snap content over the head."""
        self._writable_or_raise()
        snap = self._snap_by_name(snap_name)
        lay = self._layout
        await self.resize(snap["size"])
        n_objs = self._object_count(snap["size"])

        async def roll(objectno):
            oid = self._data_obj(objectno)
            try:
                buf = await self.ioctx.read(oid, snap=snap["id"])
                await self.ioctx.write_full(oid, buf)
            except RadosError as e:
                if e.errno_name != "ENOENT":
                    raise
                await self._remove_data_obj(objectno)
        try:
            await _gather_bounded([roll(i) for i in range(n_objs)])
        except RadosError as e:
            raise _wrap(e) from e

    # -- flatten -------------------------------------------------------------
    async def flatten(self) -> None:
        """Copy all parent data up, then sever the parent link
        (librbd::Operations::flatten)."""
        self._writable_or_raise()
        pref = self.meta.get("parent")
        if pref is None:
            raise RbdError("EINVAL", "image has no parent")
        n_objs = self._object_count(
            min(pref["overlap"], self.meta["size"]))

        async def up(objectno):
            try:
                await self.ioctx.stat(self._data_obj(objectno))
            except RadosError as e:
                if e.errno_name == "ENOENT":
                    await self._copyup(objectno)
                else:
                    raise
        try:
            await _gather_bounded([up(i) for i in range(n_objs)])
            await self.ioctx.exec(_header(self.id), "rbd",
                                  "remove_parent", b"")
            parent = await self._get_parent()
            await parent.ioctx.exec(
                RBD_CHILDREN, "rbd", "remove_child", json.dumps({
                    **pref, "child_id": self.id}).encode())
        except RadosError as e:
            raise _wrap(e) from e
        if self._parent is not None:
            await self._parent.close()
            self._parent = None
        await self._refresh_meta()

    # -- import/export helpers (rbd CLI) ------------------------------------
    async def export(self, chunk: int = 1 << 22):
        """Async iterator of (offset, bytes) over the whole image."""
        size = await self.size()
        off = 0
        while off < size:
            n = min(chunk, size - off)
            yield off, await self.read(off, n)
            off += n
