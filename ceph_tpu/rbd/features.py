"""librbd object-map and journaling features.

Object map (src/librbd/object_map/): one state byte per data object in
``rbd_object_map.<iid>`` (head) and ``rbd_object_map.<iid>.<snapid>``
(per snapshot) -- the reference packs 2 bits per object; a byte here
keeps the same state machine legible.  Writes mark objects EXISTS
(dirty) BEFORE touching data, whole-object discards mark NONEXISTENT,
and snap_create freezes a copy then downgrades head entries to
EXISTS_CLEAN -- which is exactly what fast-diff needs: an object
changed since a snapshot iff its head state is dirty EXISTS or its
existence differs from the snap map (DiffIterate's fast path).

Journaling (src/librbd/journal/): every image mutation appends an
event to ``rbd_journal.<iid>`` BEFORE it applies (the reference's
journal-safe ordering), through the cls journal class so sequence
allocation is atomic across writers.  rbd-mirror's journal mode tails
this: a registered client replays write/discard/resize/snap events
onto the secondary and commits its position; trim reclaims what every
client consumed.
"""

from __future__ import annotations

import json

from ..client.rados import RadosError

OBJ_NONEXISTENT = 0
OBJ_EXISTS = 1            # written since the last snapshot (dirty)
OBJ_EXISTS_CLEAN = 3      # exists, unchanged since the last snapshot

FEATURE_EXCLUSIVE_LOCK = "exclusive-lock"
FEATURE_OBJECT_MAP = "object-map"
FEATURE_JOURNALING = "journaling"


def object_map_oid(iid: str, snap_id: int | None = None) -> str:
    base = f"rbd_object_map.{iid}"
    return f"{base}.{snap_id}" if snap_id is not None else base


def journal_oid(iid: str) -> str:
    return f"rbd_journal.{iid}"


class ObjectMap:
    """Head object-map handle for one open image."""

    def __init__(self, img) -> None:
        self.img = img
        self._map: bytearray | None = None

    async def load(self) -> bytearray:
        if self._map is None:
            try:
                raw = await self.img.ioctx.read(
                    object_map_oid(self.img.id))
            except RadosError:
                raw = b""
            n = self.img._object_count(self.img.meta["size"])
            self._map = bytearray(raw.ljust(n, b"\x00"))
        return self._map

    async def _save(self) -> None:
        await self.img.ioctx.write_full(object_map_oid(self.img.id),
                                        bytes(self._map))

    async def set_state(self, objectno: int, state: int) -> None:
        m = await self.load()
        if objectno >= len(m):
            m.extend(b"\x00" * (objectno + 1 - len(m)))
        if m[objectno] != state:
            m[objectno] = state
            await self._save()

    async def mark_written(self, objectno: int) -> None:
        """BEFORE the data write (a crash must err toward EXISTS --
        claiming NONEXISTENT for written data loses it on fast-diff
        copies; the reverse only costs a read)."""
        m = await self.load()
        if objectno >= len(m) or m[objectno] != OBJ_EXISTS:
            await self.set_state(objectno, OBJ_EXISTS)

    async def mark_removed(self, objectno: int) -> None:
        await self.set_state(objectno, OBJ_NONEXISTENT)

    async def truncate(self, n_objects: int) -> None:
        """Shrink the map (image resize down): dropped objects are
        gone, their states must not linger."""
        m = await self.load()
        if len(m) > n_objects:
            del m[n_objects:]
            await self._save()

    async def snapshot(self, snap_id: int) -> None:
        """Freeze the map for a snapshot; head entries downgrade to
        CLEAN so future fast-diff sees exactly the post-snap dirt."""
        m = await self.load()
        await self.img.ioctx.write_full(
            object_map_oid(self.img.id, snap_id), bytes(m))
        for i, st in enumerate(m):
            if st == OBJ_EXISTS:
                m[i] = OBJ_EXISTS_CLEAN
        await self._save()

    async def states(self) -> bytes:
        return bytes(await self.load())


async def fast_diff(img, from_snap: str | None = None) -> list[int]:
    """Object numbers changed since ``from_snap`` (or since creation):
    DiffIterate's fast path -- object maps only, no data scans."""
    head = bytearray()
    try:
        head = bytearray(await img.ioctx.read(object_map_oid(img.id)))
    except RadosError:
        pass
    if from_snap is None:
        return [i for i, st in enumerate(head)
                if st in (OBJ_EXISTS, OBJ_EXISTS_CLEAN)]
    sid = img._snap_by_name(from_snap)["id"]
    try:
        base = await img.ioctx.read(object_map_oid(img.id, sid))
    except RadosError as e:
        raise RadosError("ENOENT",
                         f"no object map for snap {from_snap}") from e
    out = []
    n = max(len(head), len(base))
    for i in range(n):
        h = head[i] if i < len(head) else OBJ_NONEXISTENT
        b = base[i] if i < len(base) else OBJ_NONEXISTENT
        if h == OBJ_EXISTS or (h == OBJ_NONEXISTENT) != \
                (b == OBJ_NONEXISTENT):
            out.append(i)
    return out


async def disk_usage(img) -> dict:
    """rbd du via the object map: provisioned vs allocated bytes."""
    states = bytearray()
    try:
        states = bytearray(await img.ioctx.read(
            object_map_oid(img.id)))
    except RadosError:
        pass
    osz = 1 << img.meta["order"]
    used = sum(1 for st in states
               if st in (OBJ_EXISTS, OBJ_EXISTS_CLEAN))
    return {"provisioned": img.meta["size"], "used": used * osz}


class ImageJournal:
    """Append/replay handle for one image's journal."""

    def __init__(self, ioctx, iid: str) -> None:
        self.ioctx = ioctx
        self.oid = journal_oid(iid)

    async def append(self, event: dict, payload: bytes = b"") -> int:
        blob = json.dumps(event).encode() + b"\x00" + payload
        seq = await self.ioctx.exec(self.oid, "journal", "append", blob)
        return int(seq)

    async def entries_after(self, position: int,
                            limit: int = 64) -> list[tuple[int, dict,
                                                           bytes]]:
        raw = json.loads(await self.ioctx.exec(
            self.oid, "journal", "get_entries",
            json.dumps({"after": position, "max": limit}).encode()))
        out = []
        for seq, hexblob in raw["entries"]:
            blob = bytes.fromhex(hexblob)
            meta, _, payload = blob.partition(b"\x00")
            out.append((seq, json.loads(meta), payload))
        return out

    async def register_client(self, client_id: str,
                              position: int = -1) -> dict:
        return json.loads(await self.ioctx.exec(
            self.oid, "journal", "client_register",
            json.dumps({"id": client_id,
                        "position": position}).encode()))

    async def commit(self, client_id: str, position: int) -> None:
        await self.ioctx.exec(
            self.oid, "journal", "client_commit",
            json.dumps({"id": client_id,
                        "position": position}).encode())

    async def clients(self) -> list[dict]:
        return json.loads(await self.ioctx.exec(
            self.oid, "journal", "client_list", b""))

    async def trim(self) -> int:
        return int(await self.ioctx.exec(self.oid, "journal", "trim",
                                         b""))

    async def head_seq(self) -> int:
        """Sequence of the newest appended entry (-1 when empty);
        reads only the allocator key, never payloads."""
        nxt = int(await self.ioctx.exec(self.oid, "journal",
                                        "get_seq", b""))
        return nxt - 1
