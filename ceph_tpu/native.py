"""ctypes bindings for the native C++ runtime library.

Builds lazily with make on first use if the .so is absent; every entry
point has a numpy fallback so the framework stays functional without a
toolchain.  The native GF path is also the CPU baseline the TPU kernels
are measured against in bench.py (the ISA-L-technique stand-in).
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "libceph_tpu_native.so"

_lib = None
_lib_lock = threading.Lock()
_build_attempted = False


def _load():
    global _lib, _build_attempted
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not _LIB_PATH.exists() and not _build_attempted:
            _build_attempted = True
            try:
                subprocess.run(["make", "-C", str(_NATIVE_DIR), "-j4"],
                               check=True, capture_output=True, timeout=120)
            except Exception:
                return None
        if not _LIB_PATH.exists():
            return None
        lib = ctypes.CDLL(str(_LIB_PATH))
        if not hasattr(lib, "crush_oracle_select") \
                or not hasattr(lib, "ceph_crc32c_batch_ptrs"):
            # stale .so from before the oracle / batched crc landed:
            # rebuild once; if that fails, keep serving the symbols it
            # DOES have
            try:
                subprocess.run(["make", "-C", str(_NATIVE_DIR), "clean"],
                               check=True, capture_output=True, timeout=60)
                subprocess.run(["make", "-C", str(_NATIVE_DIR), "-j4"],
                               check=True, capture_output=True, timeout=120)
                lib = ctypes.CDLL(str(_LIB_PATH))
            except Exception:
                pass
        lib.gf8_matmul.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_size_t,
        ]
        lib.ceph_crc32c.restype = ctypes.c_uint32
        lib.ceph_crc32c.argtypes = [
            ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t]
        lib.rjenkins_hash3.restype = ctypes.c_uint32
        lib.rjenkins_hash3.argtypes = [ctypes.c_uint32] * 3
        if hasattr(lib, "ceph_crc32c_batch"):
            u64p = ctypes.POINTER(ctypes.c_uint64)
            lib.ceph_crc32c_batch.restype = None
            lib.ceph_crc32c_batch.argtypes = [
                ctypes.POINTER(ctypes.c_uint32),
                ctypes.POINTER(ctypes.c_uint8), u64p, u64p,
                ctypes.c_int]
        if hasattr(lib, "ceph_crc32c_batch_ptrs"):
            u64p = ctypes.POINTER(ctypes.c_uint64)
            lib.ceph_crc32c_batch_ptrs.restype = None
            lib.ceph_crc32c_batch_ptrs.argtypes = [
                ctypes.POINTER(ctypes.c_uint32),
                ctypes.POINTER(ctypes.c_char_p), u64p, ctypes.c_int]
        if hasattr(lib, "crush_oracle_select"):
            i32p = ctypes.POINTER(ctypes.c_int32)
            lib.crush_oracle_select.restype = ctypes.c_int
            lib.crush_oracle_select.argtypes = [
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int, i32p, i32p, i32p, i32p, i32p, i32p,
                ctypes.c_int, ctypes.c_int, ctypes.c_int32,
                ctypes.c_uint32, ctypes.c_int, ctypes.c_int,
                ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_int, i32p,
            ]
        _lib = lib
        return _lib


_dencfast = None
_dencfast_attempted = False


def get_dencfast():
    """The C denc tagged-value codec (native/denc_value.cc), built
    lazily; None when no toolchain -- callers keep the pure-Python
    reference implementation as fallback."""
    global _dencfast, _dencfast_attempted
    if _dencfast is not None or _dencfast_attempted:
        return _dencfast
    with _lib_lock:
        if _dencfast_attempted:
            return _dencfast
        _dencfast_attempted = True
        so = _NATIVE_DIR / "ceph_tpu_dencfast.so"
        if not so.exists():
            try:
                subprocess.run(
                    ["make", "-C", str(_NATIVE_DIR),
                     "ceph_tpu_dencfast.so"],
                    check=True, capture_output=True, timeout=120)
            except Exception:
                return None
        if not so.exists():
            return None
        try:
            import importlib.util
            spec = importlib.util.spec_from_file_location(
                "ceph_tpu_dencfast", so)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
        except Exception:
            return None
        _dencfast = mod
    return _dencfast


def available() -> bool:
    return _load() is not None


def gf8_matmul(matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
    """(r,k) GF(2^8) coeff matrix x (k,n) bytes -> (r,n), native path."""
    lib = _load()
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    r, k = matrix.shape
    n = data.shape[1]
    if lib is None:
        from .gf import gf_matmul
        return gf_matmul(matrix, data)
    out = np.empty((r, n), dtype=np.uint8)
    lib.gf8_matmul(
        matrix.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), r, k,
        data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n)
    return out


# scalar-call accounting: the batched integrity pipeline
# (ops/crc32c_batch.py) owns the "integrity" perf counter set; every
# per-buffer call through here is counted against it so perf dumps and
# bench.py --integrity can prove the hot paths ride the batched API.
# Resolved lazily: processes that never checksum never import ops.
_integrity_perf = None


def _count_scalar(nbytes: int) -> None:
    global _integrity_perf
    perf = _integrity_perf
    if perf is None:
        try:
            from .ops.crc32c_batch import PERF as perf
        except Exception:
            return
        _integrity_perf = perf
    perf.inc("scalar_calls")
    perf.inc("scalar_bytes", nbytes)


def crc32c(data: bytes, crc: int = 0xFFFFFFFF) -> int:
    """CRC32-C; default initial value matches the common -1 seed."""
    _count_scalar(len(data))
    lib = _load()
    if lib is None:
        return _crc32c_py(data, crc)
    buf = np.frombuffer(data, dtype=np.uint8)
    if len(buf) == 0:
        return crc
    return int(lib.ceph_crc32c(
        ctypes.c_uint32(crc),
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(buf)))


def crc32c_batch_native(crcs: np.ndarray, flat: np.ndarray,
                        offsets: np.ndarray,
                        lens: np.ndarray) -> bool:
    """One library call checksumming ``len(crcs)`` buffers laid out in
    ``flat`` (buffer i at ``offsets[i]``, ``lens[i]`` bytes); ``crcs``
    carries seeds in and results out, in place.  Returns False when the
    native lib (or a pre-batch stale .so) is unavailable -- the caller
    (ops/crc32c_batch.py) falls back to the numpy engine."""
    lib = _load()
    if lib is None or not hasattr(lib, "ceph_crc32c_batch"):
        return False
    assert crcs.dtype == np.uint32 and crcs.flags.c_contiguous
    assert flat.dtype == np.uint8 and flat.flags.c_contiguous
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.ceph_crc32c_batch(
        crcs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        np.ascontiguousarray(offsets, np.uint64).ctypes.data_as(u64p),
        np.ascontiguousarray(lens, np.uint64).ctypes.data_as(u64p),
        len(crcs))
    return True


def crc32c_batch_native_ptrs(crcs: np.ndarray, bufs: list,
                             lens: np.ndarray) -> bool:
    """Scattered-buffer variant of :func:`crc32c_batch_native`: one
    library call over a pointer table built straight from the bytes
    objects -- no concatenation memcpy at all.  ``bufs`` must be a
    list of ``bytes`` (the pointer table borrows their storage for the
    duration of the call)."""
    lib = _load()
    if lib is None or not hasattr(lib, "ceph_crc32c_batch_ptrs"):
        return False
    assert crcs.dtype == np.uint32 and crcs.flags.c_contiguous
    ptrs = (ctypes.c_char_p * len(bufs))(*bufs)
    lib.ceph_crc32c_batch_ptrs(
        crcs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)), ptrs,
        np.ascontiguousarray(lens, np.uint64).ctypes.data_as(
            ctypes.POINTER(ctypes.c_uint64)),
        len(bufs))
    return True


def _crc32c_py(data: bytes, crc: int) -> int:
    """No-toolchain fallback: numpy table-driven slice-by-8 via the
    batched engine (the seed's per-byte Python loop made EVERY
    frame/block/scrub digest a ~10 MB/s interpreter walk whenever
    libceph_native was absent)."""
    from .ops.crc32c_batch import crc32c_numpy_one
    return crc32c_numpy_one(data, crc)


class NativeBackend:
    """RSMatrixCodec backend over the C++ library (CPU baseline)."""

    name = "native"

    def matmul(self, matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
        return gf8_matmul(matrix, data)


def crush_oracle_do_rule(crush_map, ruleno: int, x: int, numrep: int,
                         osd_weights) -> list[int] | None:
    """Independent C oracle for straw2 TAKE->CHOOSE(LEAF)->EMIT rules
    (native/crush_oracle.cc); None when the native lib is unavailable
    or the rule shape is outside the oracle's scope."""
    lib = _load()
    if lib is None or not hasattr(lib, "crush_oracle_select"):
        return None
    from .crush.ln import RH_LH_TBL, LL_TBL
    from .crush.types import (
        CRUSH_BUCKET_STRAW2, CRUSH_RULE_TAKE, CRUSH_RULE_CHOOSE_FIRSTN,
        CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_CHOOSELEAF_FIRSTN,
        CRUSH_RULE_CHOOSELEAF_INDEP, CRUSH_RULE_EMIT,
        CRUSH_RULE_SET_CHOOSE_TRIES, CRUSH_RULE_SET_CHOOSELEAF_TRIES,
    )
    rule = crush_map.rules.get(ruleno)
    if rule is None or not (1 <= numrep <= 64):
        return None
    choose_tries_override = None
    leaf_tries_override = None
    steps = []
    for s in rule.steps:
        if s.op == CRUSH_RULE_SET_CHOOSE_TRIES:
            choose_tries_override = s.arg1
        elif s.op == CRUSH_RULE_SET_CHOOSELEAF_TRIES:
            leaf_tries_override = s.arg1
        else:
            steps.append(s)
    if len(steps) != 3:
        return None
    take, choose, emit = steps
    if take.op != CRUSH_RULE_TAKE or emit.op != CRUSH_RULE_EMIT:
        return None
    shapes = {
        CRUSH_RULE_CHOOSE_FIRSTN: (1, 0),
        CRUSH_RULE_CHOOSELEAF_FIRSTN: (1, 1),
        CRUSH_RULE_CHOOSE_INDEP: (0, 0),
        CRUSH_RULE_CHOOSELEAF_INDEP: (0, 1),
    }
    if choose.op not in shapes:
        return None
    if choose.arg1 != 0:
        return None   # rule-capped numrep: outside the oracle's scope
    if (choose_tries_override or 0) < 0 or (leaf_tries_override or 0) < 0:
        return None
    firstn, leaf = shapes[choose.op]
    t = crush_map.tunables
    if t.chooseleaf_vary_r != 1 or not t.chooseleaf_stable \
            or t.choose_local_tries or t.choose_local_fallback_tries:
        return None                   # oracle implements jewel profile
    buckets = list(crush_map.buckets.values())
    if any(b.alg != CRUSH_BUCKET_STRAW2 for b in buckets):
        return None
    ids = np.array([b.id for b in buckets], np.int32)
    types = np.array([b.type for b in buckets], np.int32)
    off = np.zeros(len(buckets) + 1, np.int32)
    items, weights = [], []
    for i, b in enumerate(buckets):
        items.extend(b.items)
        weights.extend(b.item_weights)
        off[i + 1] = len(items)
    items = np.array(items, np.int32)
    weights = np.array(weights, np.int32)
    osd_w = np.asarray(osd_weights, np.int32)
    out = np.full(max(numrep, 1), 0x7FFFFFFF, np.int32)
    rh = np.ascontiguousarray(RH_LH_TBL, np.int64)
    ll = np.ascontiguousarray(LL_TBL, np.int64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    # default counts tries (total_tries + 1); an explicit SET step is
    # used as-is (crush_do_rule's compatibility quirk)
    choose_tries = choose_tries_override or (t.choose_total_tries + 1)
    if leaf_tries_override:
        recurse_tries = leaf_tries_override
    elif firstn:
        recurse_tries = 1 if t.chooseleaf_descend_once else choose_tries
    else:
        recurse_tries = 1
    n = lib.crush_oracle_select(
        rh.ctypes.data_as(i64p), ll.ctypes.data_as(i64p),
        len(buckets), ids.ctypes.data_as(i32p),
        types.ctypes.data_as(i32p), off.ctypes.data_as(i32p),
        items.ctypes.data_as(i32p), weights.ctypes.data_as(i32p),
        osd_w.ctypes.data_as(i32p), len(osd_w),
        crush_map.max_devices, take.arg1, ctypes.c_uint32(x & 0xFFFFFFFF),
        numrep, choose.arg2, firstn, leaf,
        choose_tries, recurse_tries, 1,
        out.ctypes.data_as(i32p))
    return [int(v) for v in out[:n]] if firstn else \
        [int(v) for v in out[:numrep]]
