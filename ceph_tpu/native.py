"""ctypes bindings for the native C++ runtime library.

Builds lazily with make on first use if the .so is absent; every entry
point has a numpy fallback so the framework stays functional without a
toolchain.  The native GF path is also the CPU baseline the TPU kernels
are measured against in bench.py (the ISA-L-technique stand-in).
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "libceph_tpu_native.so"

_lib = None
_lib_lock = threading.Lock()
_build_attempted = False


def _load():
    global _lib, _build_attempted
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not _LIB_PATH.exists() and not _build_attempted:
            _build_attempted = True
            try:
                subprocess.run(["make", "-C", str(_NATIVE_DIR), "-j4"],
                               check=True, capture_output=True, timeout=120)
            except Exception:
                return None
        if not _LIB_PATH.exists():
            return None
        lib = ctypes.CDLL(str(_LIB_PATH))
        lib.gf8_matmul.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_size_t,
        ]
        lib.ceph_crc32c.restype = ctypes.c_uint32
        lib.ceph_crc32c.argtypes = [
            ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t]
        lib.rjenkins_hash3.restype = ctypes.c_uint32
        lib.rjenkins_hash3.argtypes = [ctypes.c_uint32] * 3
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def gf8_matmul(matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
    """(r,k) GF(2^8) coeff matrix x (k,n) bytes -> (r,n), native path."""
    lib = _load()
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    r, k = matrix.shape
    n = data.shape[1]
    if lib is None:
        from .gf import gf_matmul
        return gf_matmul(matrix, data)
    out = np.empty((r, n), dtype=np.uint8)
    lib.gf8_matmul(
        matrix.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), r, k,
        data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n)
    return out


def crc32c(data: bytes, crc: int = 0xFFFFFFFF) -> int:
    """CRC32-C; default initial value matches the common -1 seed."""
    lib = _load()
    if lib is None:
        return _crc32c_py(data, crc)
    buf = np.frombuffer(data, dtype=np.uint8)
    if len(buf) == 0:
        return crc
    return int(lib.ceph_crc32c(
        ctypes.c_uint32(crc),
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(buf)))


_CRC_TABLE = None


def _crc32c_py(data: bytes, crc: int) -> int:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        poly = 0x82F63B78
        tbl = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            tbl.append(c)
        _CRC_TABLE = tbl
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc & 0xFFFFFFFF


class NativeBackend:
    """RSMatrixCodec backend over the C++ library (CPU baseline)."""

    name = "native"

    def matmul(self, matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
        return gf8_matmul(matrix, data)
