"""ObjectCacher: client-side write-back cache over object extents.

src/osdc/ObjectCacher.cc role, async-native: librbd (and the CephFS
client) buffer writes as DIRTY extents and ack immediately; a
background flusher writes them back within ``flush_interval``; writers
throttle when dirty bytes pass ``max_dirty`` (ObjectCacher's
dirty/tx state machine + flusher thread, compressed to the extent
granularity this client actually uses).

States per extent: dirty (in cache only) -> tx (flush in flight;
concurrent writes copy-on-write a fresh dirty extent, never mutate an
in-flight buffer) -> clean (readable, evictable).  Reads overlay
dirty/tx/clean extents over a read-through of the missing ranges.

Flush barriers -- fsync/close, snapshot create, exclusive-lock loss --
call ``flush()``; fencing calls ``discard_all()`` (a fenced client's
dirty data must die, not land).
"""

from __future__ import annotations

import asyncio
import time
from collections import OrderedDict

DIRTY = "dirty"
TX = "tx"
CLEAN = "clean"


class _Extent:
    __slots__ = ("off", "data", "state")

    def __init__(self, off: int, data: bytes, state: str) -> None:
        self.off = off
        self.data = bytearray(data)
        self.state = state

    @property
    def end(self) -> int:
        return self.off + len(self.data)


class ObjectCacher:
    def __init__(self, ioctx, *,
                 max_dirty: int = 32 << 20,
                 target_dirty: int = 16 << 20,
                 max_clean: int = 64 << 20,
                 flush_interval: float = 1.0) -> None:
        self.ioctx = ioctx
        self.max_dirty = max_dirty
        self.target_dirty = target_dirty
        self.max_clean = max_clean
        self.flush_interval = flush_interval
        # oid -> sorted list of non-overlapping extents
        self.objects: dict[str, list[_Extent]] = {}
        # incremental accounting: per-object byte sums roll up into
        # totals so the write-path throttle check is O(1), not a walk
        # of every cached extent
        self._obj_dirty: dict[str, int] = {}
        self._obj_total: dict[str, int] = {}
        self._dirty_total = 0
        self._cached_total = 0
        self._clean_lru: OrderedDict[str, None] = OrderedDict()
        self._flush_locks: dict[str, asyncio.Lock] = {}
        self._dirty_waiters: list[asyncio.Future] = []
        self._flusher: asyncio.Task | None = None
        self._stopped = False
        self.stats = {"hit_bytes": 0, "miss_bytes": 0,
                      "write_bytes": 0, "flush_ops": 0}

    # -- accounting -----------------------------------------------------------
    def dirty_bytes(self) -> int:
        return self._dirty_total

    def cached_bytes(self) -> int:
        return self._cached_total

    def _reaccount(self, oid: str) -> None:
        """Recompute one object's byte sums after its extent list
        changed; totals adjust by the delta (every mutation of
        self.objects[oid] must be followed by this)."""
        exts = self.objects.get(oid, [])
        dirty = sum(len(e.data) for e in exts
                    if e.state in (DIRTY, TX))
        total = sum(len(e.data) for e in exts)
        self._dirty_total += dirty - self._obj_dirty.get(oid, 0)
        self._cached_total += total - self._obj_total.get(oid, 0)
        if exts:
            self._obj_dirty[oid] = dirty
            self._obj_total[oid] = total
        else:
            self._obj_dirty.pop(oid, None)
            self._obj_total.pop(oid, None)

    # -- write ----------------------------------------------------------------
    async def write(self, oid: str, off: int, data: bytes) -> None:
        """Stage a write in cache; returns once buffered (NOT
        durable -- flush() is the durability barrier).  Throttles when
        dirty bytes exceed the cap (ObjectCacher::wait_for_write)."""
        self._ensure_flusher()
        self._merge(oid, off, bytes(data), DIRTY)
        self.stats["write_bytes"] += len(data)
        while self.dirty_bytes() > self.max_dirty:
            fut = asyncio.get_event_loop().create_future()
            self._dirty_waiters.append(fut)
            self._kick()
            await fut

    def _merge(self, oid: str, off: int, data: bytes,
               state: str) -> None:
        """Insert an extent, trimming whatever it overlaps.  A DIRTY
        insert never mutates a TX buffer (it is in flight): overlapped
        TX/CLEAN extents are trimmed out of the visible range and the
        new bytes win reads immediately."""
        exts = self.objects.setdefault(oid, [])
        end = off + len(data)
        keep: list[_Extent] = []
        for e in exts:
            if e.end <= off or e.off >= end:
                keep.append(e)
                continue
            # overlap: keep non-overlapping head/tail pieces
            if e.off < off:
                keep.append(_Extent(e.off,
                                    e.data[:off - e.off], e.state))
            if e.end > end:
                keep.append(_Extent(end, e.data[end - e.off:],
                                    e.state))
        keep.append(_Extent(off, data, state))
        keep.sort(key=lambda e: e.off)
        # coalesce adjacent same-state extents (bounds flush op count)
        out: list[_Extent] = []
        for e in keep:
            if out and out[-1].state == e.state \
                    and out[-1].end == e.off:
                out[-1].data += e.data
            else:
                out.append(e)
        self.objects[oid] = out
        self._reaccount(oid)
        if state == CLEAN:
            self._touch_clean(oid)

    # -- read -----------------------------------------------------------------
    async def read(self, oid: str, off: int, length: int,
                   reader=None) -> bytes:
        """Cached read: cache extents overlay a read-through of the
        missing ranges.  ``reader(off, length) -> bytes`` customizes
        the miss path (e.g. librbd's parent/clone fallback); default
        reads the object via the ioctx (short reads zero-fill)."""
        end = off + length
        exts = [e for e in self.objects.get(oid, [])
                if e.off < end and e.end > off]
        # missing ranges
        holes: list[tuple[int, int]] = []
        pos = off
        for e in sorted(exts, key=lambda e: e.off):
            if e.off > pos:
                holes.append((pos, e.off))
            pos = max(pos, e.end)
        if pos < end:
            holes.append((pos, end))
        buf = bytearray(length)
        hit = length
        for h0, h1 in holes:
            hit -= h1 - h0
            got = await self._read_through(oid, h0, h1 - h0, reader)
            buf[h0 - off:h0 - off + len(got)] = got
            # cache the miss as CLEAN -- but a concurrent write() may
            # have landed in this hole DURING the await, and stale
            # CLEAN bytes must never trim an acked DIRTY extent: clip
            # the insert to the ranges still uncovered right now
            for g0, g1 in self._uncovered(oid, h0, h1):
                self._merge(oid, g0, bytes(buf[g0 - off:g1 - off]),
                            CLEAN)
        self.stats["hit_bytes"] += hit
        for e in exts:
            s = max(e.off, off)
            t = min(e.end, end)
            buf[s - off:t - off] = e.data[s - e.off:t - e.off]
        # racing writes win the returned view too (read-your-writes
        # for a writer that overlapped our read-through)
        for e in self.objects.get(oid, []):
            if e.state in (DIRTY, TX) and e.off < end and e.end > off:
                s = max(e.off, off)
                t = min(e.end, end)
                buf[s - off:t - off] = e.data[s - e.off:t - e.off]
        self._touch_clean(oid)
        self._evict_clean()
        return bytes(buf)

    def _uncovered(self, oid: str, start: int,
                   end: int) -> list[tuple[int, int]]:
        """Sub-ranges of [start, end) no current extent covers."""
        out: list[tuple[int, int]] = []
        pos = start
        for e in sorted((e for e in self.objects.get(oid, [])
                         if e.off < end and e.end > start),
                        key=lambda e: e.off):
            if e.off > pos:
                out.append((pos, e.off))
            pos = max(pos, e.end)
        if pos < end:
            out.append((pos, end))
        return out

    async def _read_through(self, oid, off, length, reader) -> bytes:
        self.stats["miss_bytes"] += length
        if reader is not None:
            got = await reader(off, length)
        else:
            from .rados import RadosError
            try:
                got = await self.ioctx.read(oid, length=length,
                                            offset=off)
            except RadosError as e:
                if e.errno_name == "ENOENT":
                    got = b""
                else:
                    raise
        return bytes(got).ljust(length, b"\x00")

    # -- flush / invalidate ----------------------------------------------------
    async def flush(self, oid: str | None = None) -> None:
        """Write back every dirty extent (of one object or all);
        returns when the data is at the OSDs.  Per-object flushes
        serialize (in-flight TX buffers are never re-sent)."""
        oids = [oid] if oid is not None else list(self.objects)
        await asyncio.gather(*(self._flush_one(o) for o in oids))
        self._wake_waiters()

    async def _flush_one(self, oid: str) -> None:
        lock = self._flush_locks.setdefault(oid, asyncio.Lock())
        async with lock:
            dirty = [e for e in self.objects.get(oid, [])
                     if e.state == DIRTY]
            if not dirty:
                return
            for e in dirty:
                e.state = TX
            try:
                # offset order: overlapping writes were merged at
                # write time, so extents are disjoint and order is
                # only a determinism nicety
                for e in sorted(dirty, key=lambda e: e.off):
                    await self.ioctx.write(oid, bytes(e.data),
                                           offset=e.off)
                    self.stats["flush_ops"] += 1
            except BaseException:
                # flush failed: the data is NOT at the OSDs; put it
                # back to dirty so the next barrier retries (never
                # silently drop acked-to-app writes).  Scan the LIVE
                # list too: a racing write may have trimmed a TX
                # extent into fragments not in our snapshot
                for e in dirty + self.objects.get(oid, []):
                    if e.state == TX:
                        e.state = DIRTY
                self._reaccount(oid)
                raise
            for e in dirty + self.objects.get(oid, []):
                # every TX piece (snapshot originals AND fragments a
                # racing write trimmed them into) holds bytes the
                # writes above put on the OSDs: clean, evictable
                if e.state == TX:
                    e.state = CLEAN
            self._reaccount(oid)
            self._touch_clean(oid)
        self._evict_clean()

    async def invalidate(self, oid: str | None = None) -> None:
        """Flush dirty data, then drop the cache (watch/notify told us
        another client may have written: cached cleans are stale).
        Loops until no dirty remains: a write buffered DURING the
        flush must reach the OSDs, never be dropped by the pop."""
        while True:
            for o in ([oid] if oid is not None
                      else list(self.objects)):
                kept = [e for e in self.objects.get(o, [])
                        if e.state != CLEAN]
                if kept:
                    self.objects[o] = kept
                else:
                    self.objects.pop(o, None)
                    self._clean_lru.pop(o, None)
                self._reaccount(o)
            targets = ([oid] if oid is not None
                       else list(self.objects))
            if not any(e.state in (DIRTY, TX)
                       for o in targets
                       for e in self.objects.get(o, [])):
                return
            await self.flush(oid)

    def discard(self, oid: str) -> None:
        """Drop everything INCLUDING dirty data (object deleted, or
        this client was fenced -- its buffered writes must die)."""
        self.objects.pop(oid, None)
        self._clean_lru.pop(oid, None)
        self._reaccount(oid)
        self._wake_waiters()

    def discard_all(self) -> None:
        self.objects.clear()
        self._clean_lru.clear()
        self._obj_dirty.clear()
        self._obj_total.clear()
        self._dirty_total = 0
        self._cached_total = 0
        self._wake_waiters()

    async def close(self) -> None:
        self._stopped = True
        if self._flusher is not None:
            self._flusher.cancel()
            try:
                await self._flusher
            except (asyncio.CancelledError, Exception):
                pass
        await self.flush()

    # -- background flusher ----------------------------------------------------
    def _ensure_flusher(self) -> None:
        if self._stopped:
            return
        if self._flusher is None or self._flusher.done():
            self._flusher = asyncio.ensure_future(self._flush_loop())

    def _kick(self) -> None:
        self._ensure_flusher()

    async def _flush_loop(self) -> None:
        try:
            while not self._stopped:
                await asyncio.sleep(self.flush_interval
                                    if self.dirty_bytes()
                                    <= self.max_dirty else 0)
                try:
                    await self.flush()
                except Exception:
                    await asyncio.sleep(self.flush_interval)
                if not self.dirty_bytes() and not self._dirty_waiters:
                    return            # idle: next write restarts us
        except asyncio.CancelledError:
            pass

    def _wake_waiters(self) -> None:
        if self.dirty_bytes() <= self.target_dirty:
            waiters, self._dirty_waiters = self._dirty_waiters, []
            for fut in waiters:
                if not fut.done():
                    fut.set_result(None)

    # -- clean-side bound ------------------------------------------------------
    def _touch_clean(self, oid: str) -> None:
        self._clean_lru[oid] = None
        self._clean_lru.move_to_end(oid)

    def _evict_clean(self) -> None:
        over = self.cached_bytes() - self.max_clean \
            - self.dirty_bytes()
        if over <= 0:
            return
        for oid in list(self._clean_lru):
            exts = self.objects.get(oid, [])
            kept = [e for e in exts if e.state != CLEAN]
            over -= sum(len(e.data) for e in exts) \
                - sum(len(e.data) for e in kept)
            if kept:
                self.objects[oid] = kept
            else:
                self.objects.pop(oid, None)
            self._clean_lru.pop(oid, None)
            self._reaccount(oid)
            if over <= 0:
                return


class CachingIoCtx:
    """Duck-typed ioctx wrapper: object read/write go through an
    ObjectCacher, data-shape ops (truncate/remove) flush-then-
    invalidate, everything else passes through.  Lets any consumer
    that talks to an ioctx (the cephfs striper, tools) gain the
    write-back cache without knowing it exists."""

    def __init__(self, ioctx, cacher: ObjectCacher | None = None,
                 **kw) -> None:
        self.ioctx = ioctx
        self.cacher = cacher or ObjectCacher(ioctx, **kw)

    async def write(self, oid, data, offset: int = 0):
        await self.cacher.write(oid, offset, bytes(data))
        return len(data)

    async def read(self, oid, length=None, offset: int = 0, **kw):
        if kw.get("snap") is not None or length is None:
            # snap reads and whole-object reads bypass the cache (the
            # cache indexes head extents of known length); dirty data
            # must land first so the passthrough sees it
            await self.cacher.flush(oid)
            return await self.ioctx.read(oid, length=length,
                                         offset=offset, **kw)
        return await self.cacher.read(oid, offset, length)

    async def truncate(self, oid, size: int):
        # earlier buffered writes land BEFORE the truncate (a flush
        # after it would resurrect dropped bytes), later cached state
        # is dropped
        await self.cacher.flush(oid)
        out = await self.ioctx.truncate(oid, size)
        self.cacher.discard(oid)
        return out

    async def remove(self, oid):
        self.cacher.discard(oid)       # dying object's dirty data dies
        return await self.ioctx.remove(oid)

    async def flush(self, oid=None):
        await self.cacher.flush(oid)

    def __getattr__(self, name):
        return getattr(self.ioctx, name)
