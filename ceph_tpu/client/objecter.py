"""Objecter: client-side op engine with resend-on-map-change.

Mirrors src/osdc/Objecter.cc: _calc_target (:2783) computes
object -> PG -> primary OSD from the client's OSDMap; ops that land on
a stale primary (ENOTPRIMARY / EAGAIN / timeout) are re-targeted
against the refreshed map and resent — the client rides out failover
without the application noticing (:2866 pg_to_up_acting_osds and the
resend-on-epoch-change machinery around op_submit).
"""

from __future__ import annotations

import asyncio
import itertools

from ..mon.osdmap import OSDMap, Incremental
from ..msg import Message, Messenger
from ..osd.backend import pack_mutations

RETRYABLE = {"ENOTPRIMARY", "EAGAIN", "ENXIO no such pg"}


class ObjecterError(Exception):
    pass


class Objecter:
    def __init__(self, name: str = "client.objecter",
                 secret: bytes | None = None,
                 msgr_opts: dict | None = None) -> None:
        self.msgr = Messenger(name, secret=secret, **(msgr_opts or {}))
        self.osdmap = OSDMap()
        self.mon_addr: tuple[str, int] | None = None
        self._tid = itertools.count(1)
        self._reqid_serial = itertools.count(1)
        self._waiters: dict[int, asyncio.Future] = {}
        self._cmd_waiters: dict[int, asyncio.Future] = {}
        self._refresh_tasks: set[asyncio.Task] = set()
        self._watches: dict[tuple, object] = {}
        self.msgr.add_dispatcher(self._dispatch)

    # -- lifecycle ----------------------------------------------------------
    async def start(self, mon_addr: tuple[str, int]) -> None:
        self.mon_addr = tuple(mon_addr)
        await self.msgr.bind()
        await self._refresh_map()

    async def shutdown(self) -> None:
        await self.msgr.shutdown()

    # -- cephx ---------------------------------------------------------------
    async def authenticate(self, entity: str, key_hex: str,
                           services: tuple = ("osd",)) -> None:
        """Prove our entity key to the mon and hold live tickets for
        the given service classes; OSD connections then authenticate
        with the ticket's session key instead of the cluster PSK
        (CephxClientHandler role)."""
        from ..common.cephx import fetch_ticket
        self._auth = (entity, key_hex, tuple(services))
        for svc in services:
            await fetch_ticket(self.msgr, self.mon_addr, entity,
                               key_hex, svc)

    async def _maybe_refresh_tickets(self) -> None:
        """Re-fetch any ticket at (or within 30s of) expiry so long-
        lived clients ride rotations without a failed handshake."""
        auth = getattr(self, "_auth", None)
        if auth is None:
            return
        import time as _time
        from ..common.cephx import fetch_ticket
        entity, key_hex, services = auth
        for svc in services:
            t = self.msgr.tickets.get(svc)
            if t is None or t["expires"] - _time.time() < 30.0:
                try:
                    await fetch_ticket(self.msgr, self.mon_addr,
                                       entity, key_hex, svc)
                except Exception:
                    pass         # retried on the next op

    async def _refresh_map(self, timeout: float = 10,
                           force: bool = True) -> None:
        """Fetch the full map -- COALESCED: concurrent callers share
        one in-flight fetch, and (``force=False``, the op-retry path)
        back-to-back fetches inside _REFRESH_MIN_S reuse the map we
        just got.  During cluster churn every op attempt of every
        client retries through here; un-coalesced, 32 clients
        serialized a full 64-OSD map out of the mon several times per
        second EACH, and that fetch storm (on the shared event loop)
        was a leg of the peering-cascade collapse the degraded-phase
        bench caught.  Explicit callers (open_ioctx after a pool
        create, the start() subscribe) keep ``force=True``: they need
        CURRENT state, not recent state."""
        loop = asyncio.get_event_loop()
        inflight = getattr(self, "_refresh_inflight", None)
        if inflight is not None and not inflight.done():
            await asyncio.wait_for(asyncio.shield(inflight), timeout)
            return
        if not force and loop.time() - getattr(self, "_refresh_at",
                                               -1e9) \
                < self._REFRESH_MIN_S:
            return
        task = loop.create_task(self._refresh_map_once(timeout))
        self._refresh_inflight = task
        try:
            await task
        finally:
            if getattr(self, "_refresh_inflight", None) is task:
                self._refresh_inflight = None

    _REFRESH_MIN_S = 0.5

    async def _refresh_map_once(self, timeout: float = 10) -> None:
        q: asyncio.Queue = asyncio.Queue()

        async def d(conn, msg):
            if msg.type == "osdmap_full":
                await q.put(("full", msg.data["map"]))
            elif msg.type == "osdmap_incs":
                await q.put(("incs", msg.data.get("incs", [])))

        self.msgr.add_dispatcher(d)
        try:
            # delta catch-up: the mon answers with the incremental
            # chain while it still holds it, the full map otherwise
            await self.msgr.send(self.mon_addr, "mon.0",
                                 Message("get_osdmap",
                                         {"since": self.osdmap.epoch}))
            kind, payload = await asyncio.wait_for(q.get(), timeout)
            self._refresh_at = asyncio.get_event_loop().time()
            if kind == "incs":
                for inc_d in payload:
                    inc = Incremental.from_dict(inc_d)
                    # _dispatch may have applied some while we waited
                    if inc.epoch == self.osdmap.epoch + 1:
                        self.osdmap.apply_incremental(inc)
                return
            new_map = OSDMap.from_dict(payload)
            # a slow full-map reply must not regress past incrementals
            # _dispatch applied while we waited
            if new_map.epoch >= self.osdmap.epoch:
                # placement counters are per-client, not per-map object
                new_map._placement_perf = self.osdmap._placement_perf
                self.osdmap = new_map
        finally:
            self.msgr.dispatchers.remove(d)

    # -- watch/notify (linger ops) ------------------------------------------
    def register_watch(self, pool_id: int, oid: str, cookie: int,
                       callback, nspace: str = "") -> None:
        """Track a watch; it re-registers itself whenever its PG's
        primary moves (the linger-op resend, Objecter::linger_watch)."""
        self._watches[(pool_id, oid, cookie)] = {
            "cb": callback, "nspace": nspace,
            "target": self.calc_target(pool_id, oid, nspace)}

    def unregister_watch(self, pool_id: int, oid: str,
                         cookie: int) -> None:
        self._watches.pop((pool_id, oid, cookie), None)

    async def _rewatch_all(self) -> None:
        """Re-register watches whose primary moved, concurrently --
        unrelated map churn must not trigger K serial round trips."""
        stale = []
        for key, w in list(self._watches.items()):
            pool_id, oid, cookie = key
            target = self.calc_target(pool_id, oid, w["nspace"])
            if target != w["target"]:
                stale.append((key, w, target))

        async def one(key, w, target):
            pool_id, oid, cookie = key
            try:
                await self.op_submit(
                    pool_id, oid,
                    [{"op": "watch", "cookie": cookie,
                      "addr": list(self.msgr.addr)}],
                    nspace=w["nspace"], timeout=10)
                # only a SUCCESSFUL re-registration settles the target;
                # a failure leaves it stale so the next map change (or
                # repeated attempt) retries
                w["target"] = target
            except ObjecterError:
                pass
        if stale:
            await asyncio.gather(*(one(*s) for s in stale))

    async def _handle_watch_notify(self, conn, msg: Message) -> None:
        payload = msg.segments[0] if msg.segments else b""
        for (pool_id, oid, cookie), w in list(self._watches.items()):
            if pool_id == msg.data.get("pool") \
                    and oid == msg.data.get("oid") \
                    and cookie == msg.data.get("cookie"):
                try:
                    res = w["cb"](payload)
                    if asyncio.iscoroutine(res):
                        await res
                except Exception:
                    pass
        try:
            await conn.send(Message(
                "watch_notify_ack",
                {"notify_id": msg.data.get("notify_id")}))
        except (ConnectionError, OSError):
            pass

    # -- dispatch -----------------------------------------------------------
    async def _dispatch(self, conn, msg: Message) -> None:
        if msg.type == "watch_notify":
            await self._handle_watch_notify(conn, msg)
            return
        if msg.type == "osd_op_reply":
            fut = self._waiters.pop(msg.data.get("tid"), None)
            if fut is not None and not fut.done():
                fut.set_result(msg)
        elif msg.type == "osdmap_inc":
            inc = Incremental.from_dict(msg.data["inc"])
            if inc.epoch == self.osdmap.epoch + 1:
                self.osdmap.apply_incremental(inc)
                if self._watches:
                    t = asyncio.ensure_future(self._rewatch_all())
                    self._refresh_tasks.add(t)
                    t.add_done_callback(self._refresh_tasks.discard)
            elif inc.epoch > self.osdmap.epoch:
                t = asyncio.ensure_future(self._guarded_refresh())
                self._refresh_tasks.add(t)
                t.add_done_callback(self._refresh_tasks.discard)
        elif msg.type == "mon_command_reply":
            fut = self._cmd_waiters.pop(msg.data.get("tid"), None)
            if fut is not None and not fut.done():
                fut.set_result(msg.data)

    async def _guarded_refresh(self) -> None:
        try:
            await self._refresh_map(timeout=5)
            if self._watches:
                await self._rewatch_all()
        except (asyncio.TimeoutError, ConnectionError, OSError):
            pass     # next op's retry path refreshes again

    # -- placement ----------------------------------------------------------
    def calc_target(self, pool_id: int, oid: str, nspace: str = "",
                    ps: int | None = None) -> tuple[str, int | None]:
        """(pgid, primary osd) for an object — Objecter.cc:2783.

        Pass ``ps`` to target a specific PG (pgls-style ops that
        address a placement group, not an object).

        No CRUSH runs here: pg_to_up_acting_osds reads the epoch-
        memoized placement table (mon/pg_mapping.py), recomputed in
        bulk only when a new map epoch lands — per-op cost no longer
        scales with map size, and a hot client does zero placement
        math between epochs.
        """
        if ps is None:
            _, ps = self.osdmap.object_to_pg(pool_id, oid, nspace)
        up = self.osdmap.pg_to_up_acting_osds(pool_id, ps)
        return self.osdmap.pg_name(pool_id, ps), self.osdmap.pg_primary(up)

    # -- op submission ------------------------------------------------------
    async def op_submit(self, pool_id: int, oid: str, ops: list[dict],
                        nspace: str = "", timeout: float = 30,
                        attempt_timeout: float = 5,
                        ps: int | None = None,
                        extra: dict | None = None) -> Message:
        """Run ops on the object's primary, retrying through map churn."""
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout
        # reqid is stable across RESENDS of this op (unlike the per-
        # attempt tid) so the PG can detect and absorb duplicates
        # (osd_reqid_t semantics)
        reqid = [f"{self.msgr.name}:{self.msgr.incarnation}",
                 next(self._reqid_serial)]
        await self._maybe_refresh_tickets()
        from ..common.tracing import get_tracer
        span = get_tracer(self.msgr.name).start(
            "client.osd_op", oid=oid, pool=pool_id)
        try:
            return await self._op_attempts(
                span, pool_id, oid, ops, nspace, deadline, timeout,
                attempt_timeout, ps, extra, reqid, loop)
        finally:
            span.finish()

    async def _op_attempts(self, span, pool_id, oid, ops, nspace,
                           deadline, timeout, attempt_timeout, ps,
                           extra, reqid, loop):
        last_err = None
        while loop.time() < deadline:
            pgid, primary = self.calc_target(pool_id, oid, nspace, ps=ps)
            if primary is None:
                await self._pause_and_refresh()
                continue
            info = self.osdmap.osds.get(primary)
            if info is None or info.addr is None:
                await self._pause_and_refresh()
                continue
            tid = next(self._tid)
            fut = loop.create_future()
            self._waiters[tid] = fut
            meta, segs = pack_mutations(ops)
            try:
                await self.msgr.send(
                    tuple(info.addr), f"osd.{primary}",
                    Message("osd_op", {"pgid": pgid, "oid": oid,
                                       "ops": meta, "tid": tid,
                                       "reqid": reqid,
                                       "trace": span.ctx(),
                                       **(extra or {})},
                            segments=segs))
                reply = await asyncio.wait_for(
                    fut, min(attempt_timeout, deadline - loop.time()))
            except (ConnectionError, OSError, asyncio.TimeoutError) as e:
                self._waiters.pop(tid, None)
                last_err = e
                await self._pause_and_refresh()
                continue
            err = reply.data.get("err")
            if err in RETRYABLE:
                last_err = ObjecterError(err)
                await self._pause_and_refresh()
                continue
            return reply
        raise ObjecterError(
            f"op on {oid} timed out after {timeout}s: {last_err!r}")

    async def _pause_and_refresh(self) -> None:
        await asyncio.sleep(0.25)
        try:
            # rate-limited: the retry storm must not serialize a full
            # map out of the mon per attempt per client
            await self._refresh_map(timeout=5, force=False)
        except (asyncio.TimeoutError, ConnectionError, OSError):
            pass

    # -- mon commands -------------------------------------------------------
    async def mon_command(self, cmd: str, args: dict | None = None,
                          timeout: float = 15) -> dict | list | int | str:
        tid = next(self._tid)
        fut = asyncio.get_event_loop().create_future()
        self._cmd_waiters[tid] = fut
        try:
            await self.msgr.send(
                self.mon_addr, "mon.0",
                Message("mon_command", {"cmd": cmd, "args": args or {},
                                        "tid": tid}))
            data = await asyncio.wait_for(fut, timeout)
        finally:
            self._cmd_waiters.pop(tid, None)
        if not data["ok"]:
            raise ObjecterError(data["error"])
        return data["result"]
