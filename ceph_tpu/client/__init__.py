"""Client layer: Objecter (op engine) + librados-shaped API.

The client computes placement locally from its own OSDMap copy — no
central metadata service — exactly the property the reference's client
stack is built around (doc/architecture.rst:53-55, Objecter._calc_target
src/osdc/Objecter.cc:2783).
"""

from .objecter import Objecter
from .rados import Rados, IoCtx, RadosError
from .striper import Layout, RadosStriper

__all__ = ["Objecter", "Rados", "IoCtx", "RadosError", "Layout",
           "RadosStriper"]
