"""librados-shaped client API: Rados (cluster handle) + IoCtx (pool I/O).

The surface mirrors the reference's C++ librados (src/librados/
librados_cxx.cc IoCtx::{write,append,read,remove,stat,...}) in
idiomatic asyncio; the op engine underneath is the Objecter, as in the
reference (IoCtxImpl -> Objecter::op_submit).
"""

from __future__ import annotations

import asyncio

from .objecter import Objecter, ObjecterError
from ..osd.pg import CALL_OPS as _CALL_OPS, WRITE_OPS as _WRITE_OPS


class RadosError(Exception):
    def __init__(self, errno_name: str, detail: str = "") -> None:
        super().__init__(f"{errno_name}{': ' + detail if detail else ''}")
        self.errno_name = errno_name


def _check(results: list[dict], idx: int = 0) -> dict:
    r = results[idx]
    if "err" in r:
        raise RadosError(r["err"])
    return r


class Rados:
    """Cluster handle (librados ``rados_t`` analog)."""

    def __init__(self, mon_addr: tuple[str, int],
                 name: str | None = None,
                 secret: bytes | None = None,
                 msgr_opts: dict | None = None) -> None:
        self.mon_addr = tuple(mon_addr)
        if name is None:
            # entity names must be unique per client instance: two
            # messengers sharing a name evict each other's connections
            # at the peer (the reference gets unique client.<gid> ids
            # from the mon's auth handshake)
            import os
            name = f"client.{os.urandom(4).hex()}"
        self.objecter = Objecter(name=name, secret=secret,
                                 msgr_opts=msgr_opts)
        self.connected = False

    async def connect(self) -> "Rados":
        await self.objecter.start(self.mon_addr)
        self.connected = True
        return self

    async def authenticate(self, entity: str, key_hex: str,
                           services: tuple = ("osd",)) -> None:
        """cephx: hold live service tickets (see Objecter.authenticate)."""
        await self.objecter.authenticate(entity, key_hex, services)

    async def shutdown(self) -> None:
        await self.objecter.shutdown()
        self.connected = False

    async def mon_command(self, cmd: str, args: dict | None = None):
        """Monitor command with errors normalized to RadosError."""
        try:
            return await self.objecter.mon_command(cmd, args)
        except ObjecterError as e:
            raise RadosError("EINVAL", str(e)) from e
        except asyncio.TimeoutError as e:
            raise RadosError("ETIMEDOUT", "monitor unreachable") from e

    # -- pool ops -----------------------------------------------------------
    async def pool_create(self, name: str, pg_num: int = 32,
                          pool_type: str = "replicated",
                          size: int = 3, min_size: int = 2,
                          erasure_code_profile: str = "default") -> int:
        args = {"name": name, "pg_num": pg_num, "type": pool_type,
                "size": size, "min_size": min_size}
        if pool_type == "erasure":
            args["erasure_code_profile"] = erasure_code_profile
        return await self.mon_command("osd pool create", args)

    async def pool_delete(self, name: str) -> int:
        return await self.mon_command("osd pool rm", {"name": name})

    async def pool_list(self) -> list[str]:
        return await self.mon_command("osd pool ls")

    async def status(self) -> dict:
        return await self.mon_command("status")

    async def open_ioctx(self, pool_name: str) -> "IoCtx":
        await self.objecter._refresh_map()
        pool_id = self.objecter.osdmap.pool_names.get(pool_name)
        if pool_id is None:
            raise RadosError("ENOENT", f"no pool {pool_name}")
        return IoCtx(self, pool_name, pool_id)


class IoCtx:
    """Pool I/O context (librados ``IoCtx`` analog)."""

    def __init__(self, rados: Rados, pool_name: str, pool_id: int) -> None:
        self.rados = rados
        self.objecter = rados.objecter
        self.pool_name = pool_name
        self.pool_id = pool_id
        self.nspace = ""

    def set_namespace(self, nspace: str) -> None:
        self.nspace = nspace

    # -- self-managed snapshots (librados selfmanaged_snap_* API) -----------
    def set_snap_context(self, seq: int, snaps: list[int]) -> None:
        """Snap context stamped on subsequent writes (newest first)."""
        self._snapc = {"seq": int(seq),
                       "snaps": sorted((int(s) for s in snaps),
                                       reverse=True)}

    async def selfmanaged_snap_create(self) -> int:
        """Allocate a snap id from the mon and fold it into the io
        context (rados_ioctx_selfmanaged_snap_create)."""
        sid = await self.rados.mon_command(
            "osd pool selfmanaged-snap create", {"pool": self.pool_name})
        old = getattr(self, "_snapc", {"seq": 0, "snaps": []})
        self.set_snap_context(sid, [sid] + list(old["snaps"]))
        return sid

    async def selfmanaged_snap_remove(self, snapid: int) -> None:
        await self.rados.mon_command(
            "osd pool selfmanaged-snap rm",
            {"pool": self.pool_name, "snap": int(snapid)})
        old = getattr(self, "_snapc", {"seq": 0, "snaps": []})
        self.set_snap_context(
            old["seq"], [s for s in old["snaps"] if s != int(snapid)])

    async def _op(self, oid: str, ops: list[dict],
                  extra: dict | None = None,
                  timeout: float | None = None) -> tuple[dict, list]:
        snapc = getattr(self, "_snapc", None)
        if snapc and any(o["op"] in _WRITE_OPS or o["op"] in _CALL_OPS
                         for o in ops):
            extra = {**(extra or {}), "snapc": snapc}
        kwargs = {}
        if timeout is not None:
            kwargs = {"timeout": timeout + 5,
                      "attempt_timeout": timeout + 3}
        try:
            reply = await self.objecter.op_submit(self.pool_id, oid, ops,
                                                  nspace=self.nspace,
                                                  extra=extra, **kwargs)
        except ObjecterError as e:
            raise RadosError("ETIMEDOUT", str(e)) from e
        if "err" in reply.data:
            raise RadosError(reply.data["err"],
                             reply.data.get("detail", ""))
        return reply.data, reply.segments

    # -- data ---------------------------------------------------------------
    async def write(self, oid: str, data: bytes, offset: int = 0) -> None:
        await self._op(oid, [{"op": "write", "off": offset, "data": data}])

    async def write_full(self, oid: str, data: bytes) -> None:
        await self._op(oid, [{"op": "writefull", "data": data}])

    async def append(self, oid: str, data: bytes) -> None:
        await self._op(oid, [{"op": "append", "data": data}])

    async def read(self, oid: str, length: int | None = None,
                   offset: int = 0, snap: int | None = None) -> bytes:
        extra = {"snapid": int(snap)} if snap else None
        data, segs = await self._op(oid, [{"op": "read", "off": offset,
                                           "len": length}], extra=extra)
        r = _check(data["results"])
        return segs[r["seg"]] if "seg" in r else b""

    async def list_snaps(self, oid: str) -> dict:
        data, _ = await self._op(oid, [{"op": "list_snaps"}])
        return _check(data["results"])["snapset"]

    # -- watch/notify (rados_watch3/rados_notify2) --------------------------
    async def watch(self, oid: str, callback) -> int:
        """Register a watch; ``callback(payload: bytes)`` fires on every
        notify.  Survives primary moves via the objecter's linger
        resend.  Returns the watch cookie."""
        # cookies must be unique across every ioctx of this client: the
        # PG keys watchers by (client entity, cookie)
        cookie = next(self.objecter._tid)
        await self._op(oid, [{"op": "watch", "cookie": cookie,
                              "addr": list(self.objecter.msgr.addr)}])
        self.objecter.register_watch(self.pool_id, oid, cookie, callback,
                                     nspace=self.nspace)
        return cookie

    async def unwatch(self, oid: str, cookie: int) -> None:
        self.objecter.unregister_watch(self.pool_id, oid, cookie)
        await self._op(oid, [{"op": "unwatch", "cookie": cookie}])

    async def notify(self, oid: str, payload: bytes = b"",
                     timeout: float = 5.0) -> dict:
        """Send ``payload`` to every watcher; returns {acks, timeouts}
        after all watchers answered or the timeout lapsed."""
        # the server waits up to `timeout` for watcher acks before
        # replying: the op attempt window must outlast it or the
        # objecter would resend and duplicate deliveries
        data, _ = await self._op(oid, [
            {"op": "notify", "data": payload, "timeout": timeout}],
            timeout=timeout)
        return _check(data["results"])

    async def list_watchers(self, oid: str) -> list:
        data, _ = await self._op(oid, [{"op": "list_watchers"}])
        return _check(data["results"])["watchers"]

    # -- object classes (rados_exec / IoCtx::exec) --------------------------
    async def exec(self, oid: str, cls: str, method: str,
                   data: bytes = b"") -> bytes:
        """Run a server-side cls method on the object; returns its
        output bytes (rados_exec, src/librados/librados_c.cc)."""
        reply, segs = await self._op(oid, [
            {"op": "call", "cls": cls, "method": method, "data": data}])
        r = _check(reply["results"])
        return segs[r["seg"]] if "seg" in r else b""

    def op_call(self, cls: str, method: str, data: bytes = b"") -> dict:
        """A call op for composing into operate() vectors."""
        return {"op": "call", "cls": cls, "method": method, "data": data}

    async def operate(self, oid: str,
                      ops: list[dict]) -> tuple[dict, list]:
        """Atomic multi-op vector on one object (ObjectWriteOperation)."""
        return await self._op(oid, ops)

    async def remove(self, oid: str) -> None:
        await self._op(oid, [{"op": "remove"}])

    async def truncate(self, oid: str, size: int) -> None:
        await self._op(oid, [{"op": "truncate", "size": size}])

    async def zero(self, oid: str, off: int, length: int) -> None:
        await self._op(oid, [{"op": "zero", "off": off, "len": length}])

    async def stat(self, oid: str) -> dict:
        data, _ = await self._op(oid, [{"op": "stat"}])
        return _check(data["results"])

    # -- xattrs -------------------------------------------------------------
    async def set_xattr(self, oid: str, name: str, value: bytes) -> None:
        await self._op(oid, [{"op": "setxattr", "name": name,
                              "value": value}])

    async def get_xattr(self, oid: str, name: str) -> bytes:
        data, segs = await self._op(oid, [{"op": "getxattr",
                                           "name": name}])
        r = _check(data["results"])
        return segs[r["seg"]] if "seg" in r else b""

    async def rm_xattr(self, oid: str, name: str) -> None:
        await self._op(oid, [{"op": "rmxattr", "name": name}])

    async def get_xattrs(self, oid: str) -> dict[str, bytes]:
        data, _ = await self._op(oid, [{"op": "getxattrs"}])
        r = _check(data["results"])
        return {k: bytes.fromhex(v) for k, v in r["attrs"].items()}

    # -- omap ---------------------------------------------------------------
    async def set_omap(self, oid: str, kv: dict[str, bytes]) -> None:
        await self._op(oid, [{"op": "omap_set", "kv": kv}])

    async def get_omap(self, oid: str) -> dict[str, bytes]:
        data, _ = await self._op(oid, [{"op": "omap_get"}])
        r = _check(data["results"])
        return {k: bytes.fromhex(v) for k, v in r["omap"].items()}

    async def rm_omap_keys(self, oid: str, keys: list[str]) -> None:
        await self._op(oid, [{"op": "omap_rm", "keys": keys}])

    # -- listing ------------------------------------------------------------
    async def list_objects(self) -> list[str]:
        """Union of per-PG listings across the pool (pgls analog)."""
        pool = self.objecter.osdmap.pools[self.pool_id]
        oids: set[str] = set()
        for ps in range(pool.pg_num):
            # the 'list' op addresses a PG, not an object
            reply = await self.objecter.op_submit(
                self.pool_id, "_pgls_", [{"op": "list"}], ps=ps)
            if "results" in reply.data:
                r = reply.data["results"][0]
                if r.get("ok"):
                    oids.update(r.get("oids", []))
        return sorted(oids)
