"""libradosstriper + SimpleRADOSStriper: locked striped-object APIs.

Two layers over the raw striping math (client/striper.py):

* ``RadosStriperCtx`` -- the libradosstriper analog
  (src/libradosstriper/RadosStriperImpl.cc): every op takes a cls_lock
  on the striped object's FIRST rados object -- SHARED for read/write
  (concurrent I/O from many clients is fine; what must be excluded is
  a concurrent remove/truncate yanking objects mid-op), EXCLUSIVE for
  remove/truncate.  Each op gets its OWN lock cookie (two concurrent
  ops on one handle must not release each other's lock), size updates
  go through the atomic cls grow_size op so concurrent growers never
  lose a read-modify-write race, and remove deletes the lock-bearing
  first object LAST so the exclusion holds for the whole teardown.

* ``SimpleRADOSStriper`` -- the src/SimpleRADOSStriper.cc analog (the
  libcephsqlite backing store): ONE writer holds a persistent
  exclusive lock on the striped file for the whole open (renewed in
  the background, fenced on loss); recovering a file from a previous
  holder BLOCKLISTS that holder first, so a wedged-but-alive previous
  writer's late I/O is refused at the OSDs instead of corrupting the
  new owner's data (exactly the reference's recover-with-blocklist).
"""

from __future__ import annotations

import asyncio
import json
import os

from .rados import RadosError
from .striper import Layout, RadosStriper, map_extents

LOCK_NAME = "striper.lock"
OP_LOCK_DURATION = 60.0       # per-op lease; ops must finish inside
SRS_LOCK = "simplerados.lock"
SRS_OWNER_XATTR = "srs.owner"
SRS_LOCK_DURATION = 30.0
SRS_LOCK_RENEW = 10.0


class StriperError(Exception):
    def __init__(self, errno_name: str, detail: str = "") -> None:
        super().__init__(f"{errno_name}"
                         f"{': ' + detail if detail else ''}")
        self.errno_name = errno_name


class RadosStriperCtx:
    """Multi-client striped-object API with per-op locking."""

    def __init__(self, ioctx, layout: Layout | None = None) -> None:
        self.ioctx = ioctx
        self.striper = RadosStriper(ioctx, layout, atomic_size=True)

    def _first(self, soid: str) -> str:
        return self.striper._obj(soid, 0)

    async def _lock(self, soid: str, exclusive: bool) -> str:
        """Acquire; returns this op's cookie.  Waits out a crashed
        holder's full lease before giving up."""
        cookie = os.urandom(6).hex()
        deadline = (asyncio.get_event_loop().time()
                    + OP_LOCK_DURATION + 5.0)
        while True:
            try:
                await self.ioctx.exec(
                    self._first(soid), "lock", "lock", json.dumps({
                        "name": LOCK_NAME,
                        "type": "exclusive" if exclusive else "shared",
                        "cookie": cookie,
                        "duration": OP_LOCK_DURATION}).encode())
                return cookie
            except RadosError as e:
                if e.errno_name != "EBUSY":
                    raise StriperError(e.errno_name, str(e)) from e
                if asyncio.get_event_loop().time() > deadline:
                    raise StriperError(
                        "EBUSY", f"{soid} locked too long") from e
                await asyncio.sleep(0.05)

    async def _unlock(self, soid: str, cookie: str) -> None:
        try:
            await self.ioctx.exec(
                self._first(soid), "lock", "unlock", json.dumps({
                    "name": LOCK_NAME, "cookie": cookie}).encode())
        except RadosError:
            pass             # lease expiry already released it

    async def write(self, soid: str, data: bytes,
                    off: int = 0) -> None:
        cookie = await self._lock(soid, exclusive=False)
        try:
            await self.striper.write(soid, data, off)
        finally:
            await self._unlock(soid, cookie)

    async def read(self, soid: str, length: int | None = None,
                   off: int = 0) -> bytes:
        cookie = await self._lock(soid, exclusive=False)
        try:
            return await self.striper.read(soid, length, off)
        finally:
            await self._unlock(soid, cookie)

    async def stat(self, soid: str) -> dict:
        return {"size": await self.striper.size(soid)}

    async def truncate(self, soid: str, size: int) -> None:
        cookie = await self._lock(soid, exclusive=True)
        try:
            if size == 0:
                # striper.truncate(0) would remove the FIRST object --
                # the lock's home -- letting another client in while
                # we still run.  Keep object 0, drop the rest, zero
                # the size (object 0 keeps only lock/xattr state).
                await self._remove_tail(soid, keep_first=True)
                await self.ioctx.exec(
                    self._first(soid), "striper", "set_size",
                    json.dumps({"size": 0}).encode())
            else:
                await self.striper.truncate(soid, size)
        finally:
            await self._unlock(soid, cookie)

    async def _remove_tail(self, soid: str,
                           keep_first: bool) -> None:
        size = await self.striper.size(soid)
        n_objs = max((e[0] for e in map_extents(
            self.striper.layout, 0, max(size, 1))), default=0) + 1

        async def rm(objectno):
            try:
                await self.ioctx.remove(
                    self.striper._obj(soid, objectno))
            except RadosError as e:
                if e.errno_name != "ENOENT":
                    raise
        await asyncio.gather(*(rm(o)
                               for o in range(1, n_objs)))
        if not keep_first:
            await rm(0)

    async def remove(self, soid: str) -> None:
        # EXCLUSIVE: a reader/writer mid-op must finish first.  Data
        # objects go first; the lock-bearing FIRST object goes LAST,
        # so nobody can acquire a fresh lock and start writing while
        # our deletes are still in flight
        cookie = await self._lock(soid, exclusive=True)
        try:
            await self._remove_tail(soid, keep_first=False)
        finally:
            await self._unlock(soid, cookie)

    async def get_xattr(self, soid: str, name: str):
        return await self.ioctx.get_xattr(self._first(soid), name)

    async def set_xattr(self, soid: str, name: str,
                        value: bytes) -> None:
        await self.ioctx.set_xattr(self._first(soid), name, value)


class SimpleRADOSStriper:
    """Single-writer striped file under a persistent exclusive lock
    (the libcephsqlite backing-store contract)."""

    def __init__(self, ioctx, soid: str,
                 layout: Layout | None = None) -> None:
        self.ioctx = ioctx
        self.soid = soid
        self.striper = RadosStriper(ioctx, layout)
        self._cookie = os.urandom(4).hex()
        self._renew_task: asyncio.Task | None = None
        self._fenced = False
        self._opened = False

    def _first(self) -> str:
        return self.striper._obj(self.soid, 0)

    @property
    def _entity(self) -> str:
        return self.ioctx.objecter.msgr.name

    async def open(self) -> "SimpleRADOSStriper":
        """Take (or fail to take) the exclusive lock; holds until
        close(), renewing in the background.  Recovering the file
        from a DIFFERENT previous holder blocklists that holder: its
        lease lapsed, but it may be wedged with writes in flight
        (SimpleRADOSStriper::recover_lock + blocklist)."""
        try:
            await self.ioctx.exec(
                self._first(), "lock", "lock", json.dumps({
                    "name": SRS_LOCK, "type": "exclusive",
                    "cookie": self._cookie,
                    "duration": SRS_LOCK_DURATION,
                    "flags": 1}).encode())
        except RadosError as e:
            raise StriperError(e.errno_name,
                               "file is locked by another client") \
                from e
        try:
            prev = await self.ioctx.get_xattr(self._first(),
                                              SRS_OWNER_XATTR)
        except RadosError:
            prev = None
        # a CLEANLY closed file has no owner marker; one left behind
        # means the previous holder crashed or wedged mid-session
        if prev and prev.decode() != self._entity:
            try:
                await self.ioctx.rados.mon_command(
                    "osd blocklist", {"id": prev.decode(),
                                      "duration": 120})
            except Exception:
                pass         # mon unreachable: lease expiry gates
        await self.ioctx.set_xattr(self._first(), SRS_OWNER_XATTR,
                                   self._entity.encode())
        self._opened = True
        self._renew_task = asyncio.ensure_future(self._renew_loop())
        return self

    async def _renew_loop(self) -> None:
        try:
            while not self._fenced:
                await asyncio.sleep(SRS_LOCK_RENEW)
                try:
                    await self.ioctx.exec(
                        self._first(), "lock", "lock", json.dumps({
                            "name": SRS_LOCK, "type": "exclusive",
                            "cookie": self._cookie,
                            "duration": SRS_LOCK_DURATION,
                            "flags": 1}).encode())
                except RadosError as e:
                    if e.errno_name in ("EBUSY", "ENOENT"):
                        # lease lapsed and someone else owns the file:
                        # fence this handle (the new owner also
                        # blocklisted us, so late writes bounce at the
                        # OSDs too)
                        self._fenced = True
                except (ConnectionError, OSError):
                    pass
        except asyncio.CancelledError:
            pass

    def _ok(self) -> None:
        if not self._opened:
            raise StriperError("EBADF", "not open")
        if self._fenced:
            raise StriperError("EBLOCKLISTED",
                               "exclusive lock lost; handle fenced")

    async def write(self, data: bytes, off: int = 0) -> None:
        self._ok()
        await self.striper.write(self.soid, data, off)

    async def read(self, length: int | None = None,
                   off: int = 0) -> bytes:
        self._ok()
        return await self.striper.read(self.soid, length, off)

    async def truncate(self, size: int) -> None:
        self._ok()
        await self.striper.truncate(self.soid, size)

    async def size(self) -> int:
        self._ok()
        return await self.striper.size(self.soid)

    async def close(self) -> None:
        if self._renew_task:
            self._renew_task.cancel()
            try:
                await self._renew_task
            except asyncio.CancelledError:
                pass
        if self._opened and not self._fenced:
            try:
                # clean release: clear the owner marker FIRST so the
                # next opener does not fence an innocent holder, then
                # drop the lock
                await self.ioctx.set_xattr(self._first(),
                                           SRS_OWNER_XATTR, b"")
                await self.ioctx.exec(
                    self._first(), "lock", "unlock", json.dumps({
                        "name": SRS_LOCK,
                        "cookie": self._cookie}).encode())
            except RadosError:
                pass
        self._opened = False
