"""Client-side striping: one logical object -> many RADOS objects.

The Striper (src/osdc/Striper.cc) + libradosstriper semantics: a
logical byte stream is cut into stripe units of ``stripe_unit`` bytes,
dealt round-robin across ``stripe_count`` backing objects, each capped
at ``object_size`` bytes; a full set of stripe_count objects is an
object set, and the stream continues into the next set.  Backing
objects are named ``<soid>.<objectno:016x>`` and the logical size is
stored on the first object (the SimpleRADOSStriper discipline,
src/SimpleRADOSStriper.cc).

This is the long-context scaling axis of the stack: a huge logical
object fans out across many PGs/OSDs, and reads/writes of a range
become PARALLEL per-object ops (asyncio.gather here; the reference
issues them concurrently through the Objecter the same way).
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass

SIZE_XATTR = "striper.size"


@dataclass(frozen=True)
class Layout:
    """File layout (file_layout_t analog): su | os, sc >= 1."""
    stripe_unit: int = 1 << 22        # 4 MiB
    stripe_count: int = 1
    object_size: int = 1 << 22

    def __post_init__(self):
        if self.stripe_unit <= 0 or self.stripe_count <= 0 \
                or self.object_size <= 0:
            raise ValueError("layout fields must be positive")
        if self.object_size % self.stripe_unit:
            raise ValueError("stripe_unit must divide object_size")


def map_extents(layout: Layout, off: int,
                length: int) -> list[tuple[int, int, int]]:
    """[(objectno, object_off, len)] covering [off, off+length).

    Striper::file_to_extents: stripeno = off/su walks stripe units;
    the unit lands on object (set*sc + stripeno%sc) at offset
    ((stripeno/sc) % (os/su))*su + off%su."""
    su, sc, os_ = (layout.stripe_unit, layout.stripe_count,
                   layout.object_size)
    per_obj = os_ // su                 # stripe units per object column
    out: list[tuple[int, int, int]] = []
    pos = off
    end = off + length
    while pos < end:
        stripeno = pos // su
        within = pos % su
        n = min(su - within, end - pos)
        objectset = stripeno // (sc * per_obj)
        stripepos = stripeno % sc
        block = (stripeno // sc) % per_obj
        objectno = objectset * sc + stripepos
        obj_off = block * su + within
        if out and out[-1][0] == objectno \
                and out[-1][1] + out[-1][2] == obj_off:
            out[-1] = (objectno, out[-1][1], out[-1][2] + n)
        else:
            out.append((objectno, obj_off, n))
        pos += n
    return out


class RadosStriper:
    """Striped I/O over an IoCtx (libradosstriper analog)."""

    def __init__(self, ioctx, layout: Layout | None = None,
                 atomic_size: bool = False) -> None:
        self.ioctx = ioctx
        self.layout = layout or Layout()
        # atomic_size: size updates go through the cls striper
        # grow_size op (atomic at the OSD) so CONCURRENT CLIENTS never
        # lose a grow to a read-modify-write race; the default path is
        # cheaper and fine for single-writer users (cephfs, rbd)
        self.atomic_size = atomic_size
        # size-xattr updates are read-modify-write: serialize them per
        # logical object within this handle (SimpleRADOSStriper holds
        # an exclusive object lock for the same reason; cross-client
        # writers to ONE striped object need external coordination)
        self._size_locks: dict[str, asyncio.Lock] = {}

    def _size_lock(self, soid: str) -> asyncio.Lock:
        return self._size_locks.setdefault(soid, asyncio.Lock())

    def _obj(self, soid: str, objectno: int) -> str:
        return f"{soid}.{objectno:016x}"

    async def write(self, soid: str, data: bytes, off: int = 0) -> None:
        """Write a range; per-object pieces go out in parallel."""
        extents = map_extents(self.layout, off, len(data))
        pos = 0
        ops = []
        for objectno, obj_off, n in extents:
            piece = data[pos:pos + n]
            pos += n
            ops.append(self.ioctx.write(self._obj(soid, objectno),
                                        piece, offset=obj_off))
        await asyncio.gather(*ops)
        if self.atomic_size:
            import json as _json
            await self.ioctx.exec(
                self._obj(soid, 0), "striper", "grow_size",
                _json.dumps({"size": off + len(data)}).encode())
            return
        async with self._size_lock(soid):
            size = await self.size(soid)
            if off + len(data) > size:
                await self.ioctx.set_xattr(
                    self._obj(soid, 0), SIZE_XATTR,
                    str(off + len(data)).encode())

    async def read(self, soid: str, length: int | None = None,
                   off: int = 0, snap: int | None = None,
                   size_override: int | None = None) -> bytes:
        """``snap``/``size_override``: read a SNAPSHOT view -- data at
        the snap id, bounded by the frozen size (the head's size xattr
        moved on)."""
        size = (size_override if size_override is not None
                else await self.size(soid))
        if off >= size:
            return b""
        length = size - off if length is None else min(length,
                                                       size - off)
        extents = map_extents(self.layout, off, length)

        async def read_one(objectno, obj_off, n):
            from .rados import RadosError
            try:
                buf = await self.ioctx.read(self._obj(soid, objectno),
                                            length=n, offset=obj_off,
                                            snap=snap)
            except RadosError as e:
                if e.errno_name != "ENOENT":
                    raise             # timeouts etc. must surface,
                buf = b""             # only absence is a sparse hole
            return buf + b"\0" * (n - len(buf))

        pieces = await asyncio.gather(
            *(read_one(*e) for e in extents))
        return b"".join(pieces)

    async def size(self, soid: str) -> int:
        from .rados import RadosError
        try:
            raw = await self.ioctx.get_xattr(self._obj(soid, 0),
                                             SIZE_XATTR)
            return int(raw)
        except RadosError as e:
            if e.errno_name in ("ENOENT", "ENODATA"):
                return 0              # object absent = size 0
            raise                     # a timeout is NOT "empty"

    async def stat(self, soid: str) -> dict:
        return {"size": await self.size(soid),
                "layout": self.layout}

    async def truncate(self, soid: str, size: int) -> None:
        async with self._size_lock(soid):
            old = await self.size(soid)
            if size < old:
                # drop whole objects beyond the new end, trim boundary
                keep = map_extents(self.layout, 0, size) if size else []
                keep_max = max((e[0] for e in keep), default=-1)
                last = map_extents(self.layout, 0, old)
                n_objs = max((e[0] for e in last), default=-1) + 1
                from .rados import RadosError

                async def rm(objectno):
                    try:
                        await self.ioctx.remove(
                            self._obj(soid, objectno))
                    except RadosError as e:
                        if e.errno_name != "ENOENT":
                            raise
                await asyncio.gather(*(rm(o) for o in
                                       range(keep_max + 1, n_objs)))
                if size:
                    boundary = {}
                    for objectno, obj_off, n in keep:
                        boundary[objectno] = max(
                            boundary.get(objectno, 0), obj_off + n)

                    async def trunc(objectno, obj_end):
                        try:
                            await self.ioctx.truncate(
                                self._obj(soid, objectno), obj_end)
                        except RadosError as e:
                            if e.errno_name != "ENOENT":
                                raise
                    await asyncio.gather(*(trunc(o, e) for o, e in
                                           boundary.items()))
            await self.ioctx.set_xattr(self._obj(soid, 0), SIZE_XATTR,
                                       str(size).encode())

    async def remove(self, soid: str) -> None:
        size = await self.size(soid)
        n_objs = max((e[0] for e in map_extents(self.layout, 0,
                                                max(size, 1))),
                     default=0) + 1
        from .rados import RadosError

        async def rm(objectno):
            try:
                await self.ioctx.remove(self._obj(soid, objectno))
            except RadosError as e:
                if e.errno_name != "ENOENT":
                    raise
        await asyncio.gather(*(rm(o) for o in range(n_objs)))
