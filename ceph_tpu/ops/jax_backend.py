"""RSMatrixCodec backend that executes on the TPU via the MXU bit-matmul."""

from __future__ import annotations

import numpy as np

from .gf2kernels import gf_matmul_device, gf_matmul_batch_device

# below this many bytes per chunk the host round-trip dominates: do it on CPU
HOST_FALLBACK_BYTES = 0  # parity-critical: keep everything on one code path


class JaxBackend:
    name = "jax"

    def matmul(self, matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
        return gf_matmul_device(matrix, data, out_np=True)

    def matmul_batch(self, matrix: np.ndarray, data: np.ndarray,
                     out_np: bool = False):
        return gf_matmul_batch_device(matrix, data, out_np=out_np)
