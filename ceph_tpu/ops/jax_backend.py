"""RSMatrixCodec backend that executes on the TPU via the MXU bit-matmul."""

from __future__ import annotations

import numpy as np

from .gf2kernels import gf_matmul_device, gf_matmul_batch_device

# below this many bytes per chunk the host round-trip dominates: do it on CPU
HOST_FALLBACK_BYTES = 0  # parity-critical: keep everything on one code path


class JaxBackend:
    name = "jax"

    def matmul(self, matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
        return gf_matmul_device(matrix, data, out_np=True)

    def matmul_batch(self, matrix: np.ndarray, data: np.ndarray,
                     out_np: bool = False):
        return gf_matmul_batch_device(matrix, data, out_np=out_np)

    def matmul_batch_crc(self, matrix: np.ndarray, data: np.ndarray):
        """Batched stripes (B, k, L) -> ((B, r, L) parity, (B, k+r)
        uint32 chunk CRCs), all computed before anything crosses back
        to the host: the CRC kernel runs on the same device-resident
        tensors the matmul launch just touched (data chunks and fresh
        parity), so the shard checksums ride the round trip that
        produced the parity instead of a host re-scan.
        """
        from .crc32c_batch import crc32c_device_chunks
        parity = self.matmul_batch(matrix, data, out_np=False)
        crc_d = crc32c_device_chunks(data)
        crc_p = crc32c_device_chunks(parity)
        # lint: disable=device-path-host-sync -- the single post-launch materialization of the fused launch
        return (np.asarray(parity),
                # lint: disable=device-path-host-sync -- the single post-launch materialization of the fused launch
                np.concatenate([np.asarray(crc_d), np.asarray(crc_p)],
                               axis=1))
