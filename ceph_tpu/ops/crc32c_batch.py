"""Batched CRC32C: whole-batch checksums for the integrity pipeline.

PR 2 made integrity the default path -- a CRC rides every EC shard
write and is verified on every shard read, recovery payload and scrub
-- but each of those digests was a per-buffer host call (ctypes into
``native.ceph_crc32c``, or a per-byte Python loop without the lib).
This module makes the checksum side-path batch-shaped like the codec
itself (the same observation as arXiv:2108.02692: once the GF math is
amortized, the XOR/CRC side-path dominates):

* ``crc32c_batch`` / ``crc32c_rows``: checksum a whole (possibly
  ragged) batch of buffers in one pass.  Backend ladder: one call into
  ``native.ceph_crc32c_batch`` (amortizes the ~7 us/buffer ctypes
  marshaling that dominates small buffers), falling back to a numpy
  table-driven slice-by-8 engine that is always available (and is also
  what ``native._crc32c_py`` now delegates to).

* GF(2) register algebra (``crc32c_zeros`` / ``crc32c_combine`` /
  ``crc32c_strip_zeros`` / ``fold_chunk_crcs``): advancing a CRC over
  n zero bytes is multiplication by the 32x32 bit-matrix M^n (the same
  x^(8n) mod P math Ceph's crc32c combine uses), which makes CRC
  embarrassingly batch-parallel: ragged buffers are zero-padded,
  checksummed in lockstep, and un-padded by the INVERSE matrix; chunk
  CRCs from a device launch fold into whole-shard CRCs without
  re-reading a byte.

* ``crc32c_device_chunks``: the JAX kernel variant.  The codec batcher
  feeds it the same (B, k, L) tensors the encode/decode launch just
  touched, so shard CRCs come back from the device round trip that
  produced the parity -- no host re-scan.

Observability: the module-global ``PERF`` ("integrity") counts batched
vs scalar calls, bytes hashed and fused-launch hits; ``native.crc32c``
reports every remaining per-buffer call into the same set, so
``bench.py --integrity`` can prove the codec-batcher and deep-scrub
paths ride the batched API (scalar-call count ~ 0).

This module must stay importable without jax (blockstore/scrub/native
fallback are jax-free); the device kernel imports lazily.
"""

from __future__ import annotations

import functools
import os

import numpy as np

from .. import native
from ..common.perf import PerfCounters

SEED = 0xFFFFFFFF
_POLY = 0x82F63B78                  # reversed Castagnoli

# process-wide integrity counter set; OSDs adopt it into their perf
# dumps (PerfCountersCollection.adopt), native.crc32c counts scalar
# calls against it
PERF = PerfCounters("integrity")


# -- slice tables -----------------------------------------------------------

@functools.lru_cache(maxsize=1)
def _tables() -> np.ndarray:
    """(8, 256) uint32 slice-by-8 tables (t[0] = plain byte table)."""
    t = np.zeros((8, 256), np.uint32)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ _POLY if c & 1 else c >> 1
        t[0, i] = c
    for s in range(1, 8):
        t[s] = t[0][t[s - 1] & 0xFF] ^ (t[s - 1] >> 8)
    return t


# -- GF(2) register algebra -------------------------------------------------
# A 32x32 GF(2) matrix is a (32,) uint32 array of COLUMNS: applying it
# to a register XORs together the columns selected by the register's
# set bits.  The CRC update over data is affine in (register, data), so
# advancing over n zero bytes is purely linear: reg' = M^n . reg.

def _mat_apply(mat: np.ndarray, v) -> np.ndarray:
    """Apply a (32,) column-matrix to a scalar/array of registers."""
    # lint: disable=device-path-host-sync -- GF(2) register algebra on (n,) uint32 CRCs, not batch payload
    v = np.asarray(v, np.uint32)
    bits = ((v[..., None] >> np.arange(32, dtype=np.uint32)) & 1) != 0
    return np.bitwise_xor.reduce(
        np.where(bits, mat, np.uint32(0)), axis=-1)


def _mat_mul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(a . b): column i of the product is a applied to b's column i."""
    return _mat_apply(a, b)


@functools.lru_cache(maxsize=1)
def _zero_byte_matrix() -> np.ndarray:
    """M: one zero-byte register update, reg' = (reg >> 8) ^ T0[reg & 0xff]."""
    t0 = _tables()[0]
    cols = np.zeros(32, np.uint32)
    for i in range(32):
        v = np.uint32(1 << i)
        cols[i] = (v >> np.uint32(8)) ^ t0[v & 0xFF]
    return cols


def _mat_inv(mat: np.ndarray) -> np.ndarray:
    """GF(2) inverse by Gauss-Jordan on 64-bit augmented rows."""
    rows = []
    for r in range(32):
        row = 0
        for c in range(32):
            row |= ((int(mat[c]) >> r) & 1) << c
        rows.append(row | (1 << (32 + r)))
    for col in range(32):
        piv = next(r for r in range(col, 32) if (rows[r] >> col) & 1)
        rows[col], rows[piv] = rows[piv], rows[col]
        for r in range(32):
            if r != col and (rows[r] >> col) & 1:
                rows[r] ^= rows[col]
    inv = np.zeros(32, np.uint32)
    for c in range(32):
        col = 0
        for r in range(32):
            col |= ((rows[r] >> (32 + c)) & 1) << r
        inv[c] = col
    return inv


@functools.lru_cache(maxsize=64)
def _zeros_pow2(b: int) -> np.ndarray:
    """M^(2^b): advance over 2^b zero bytes."""
    if b == 0:
        return _zero_byte_matrix()
    m = _zeros_pow2(b - 1)
    return _mat_mul(m, m)


@functools.lru_cache(maxsize=64)
def _inv_zeros_pow2(b: int) -> np.ndarray:
    """(M^-1)^(2^b): strip 2^b trailing zero bytes."""
    if b == 0:
        return _mat_inv(_zero_byte_matrix())
    m = _inv_zeros_pow2(b - 1)
    return _mat_mul(m, m)


@functools.lru_cache(maxsize=256)
def _zeros_matrix(n: int) -> np.ndarray:
    """M^n via the binary ladder (few distinct n recur: segment and
    chunk lengths)."""
    assert n >= 0
    out = None
    b = 0
    while n:
        if n & 1:
            sq = _zeros_pow2(b)
            out = sq if out is None else _mat_mul(sq, out)
        n >>= 1
        b += 1
    if out is None:                  # n == 0: identity
        return (np.uint32(1) << np.arange(32, dtype=np.uint32))
    return out


def crc32c_zeros(crc, n: int):
    """Advance CRC register(s) over ``n`` zero bytes (raw register
    semantics: equivalent to ``native.crc32c(b"\\x00" * n, crc)``)."""
    out = _mat_apply(_zeros_matrix(int(n)), crc)
    return int(out) if np.ndim(crc) == 0 else out


def crc32c_combine(crc_a, crc_b, len_b: int):
    """``crc32c(a + b)`` from ``crc32c(a)`` and ``crc32c(b)`` (both
    with the default seed) without touching the bytes:
    M^len_b . (crc_a ^ seed) ^ crc_b."""
    a = np.asarray(crc_a, np.uint32) ^ np.uint32(SEED)
    out = _mat_apply(_zeros_matrix(int(len_b)), a) \
        ^ np.asarray(crc_b, np.uint32)
    return int(out) if np.ndim(crc_a) == 0 and np.ndim(crc_b) == 0 \
        else out


def crc32c_strip_zeros(crcs, nzeros):
    """Undo a zero suffix: given crc(buf + zeros), recover crc(buf).

    Zero-extension is the invertible linear map M^z, so the batched
    engines can pad ragged buffers to a common length, run in lockstep,
    and un-pad here; the codec batcher uses it to fix up fused CRCs
    computed at the padded lane width.  ``nzeros`` is a scalar or an
    array broadcastable to ``crcs``.
    """
    # lint: disable=device-path-host-sync -- GF(2) register algebra on (n,) uint32 CRCs, not batch payload
    crcs = np.asarray(crcs, np.uint32)
    # lint: disable=device-path-host-sync -- GF(2) register algebra on (n,) uint32 CRCs, not batch payload
    z = np.broadcast_to(np.asarray(nzeros, np.int64), crcs.shape)
    out = crcs.copy()
    maxz = int(z.max()) if z.size else 0
    b = 0
    while (1 << b) <= maxz:
        mask = ((z >> b) & 1) != 0
        if mask.any():
            out = np.where(mask, _mat_apply(_inv_zeros_pow2(b), out),
                           out)
        b += 1
    return out


def fold_chunk_crcs(chunk_crcs, chunk_len: int):
    """CRC of the concatenation along axis 0 of equal-length chunks,
    from their individual CRCs (default seed each): the host-side fold
    that turns a launch's per-stripe chunk CRCs into whole-shard CRCs
    without re-reading the bytes."""
    # lint: disable=device-path-host-sync -- host-side fold of per-chunk uint32 CRCs, not batch payload
    cc = np.asarray(chunk_crcs, np.uint32)
    if cc.shape[0] == 0:
        return np.full(cc.shape[1:], SEED, np.uint32)
    mat = _zeros_matrix(int(chunk_len))
    f = np.uint32(SEED)
    acc = cc[0]
    for s in range(1, cc.shape[0]):
        acc = _mat_apply(mat, acc ^ f) ^ cc[s]
    PERF.inc("combine_folds", max(0, cc.shape[0] - 1))
    return acc


# -- numpy lockstep engine --------------------------------------------------

def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _pick_seg(n_rows: int, lp: int) -> int:
    """Segment length for the chunk-split: shorter segments mean more
    parallel lanes (good for few rows) but more combine levels."""
    seg = 512
    while seg > 16 and n_rows * ((lp + seg - 1) // seg) < 1024:
        seg //= 2
    return seg


def _lockstep(lanes: np.ndarray, crc: np.ndarray) -> np.ndarray:
    """Slice-by-8 over (N, L) lanes in lockstep; L % 8 == 0.  ``crc``
    carries per-lane seeds and returns the raw registers."""
    t0, t1, t2, t3, t4, t5, t6, t7 = _tables()
    u64 = lanes.view("<u8")
    for j in range(lanes.shape[1] // 8):
        v = u64[:, j]
        x = crc.astype(np.uint64) ^ v
        crc = (t7[(x & 0xFF).astype(np.intp)]
               ^ t6[((x >> 8) & 0xFF).astype(np.intp)]
               ^ t5[((x >> 16) & 0xFF).astype(np.intp)]
               ^ t4[((x >> 24) & 0xFF).astype(np.intp)]
               ^ t3[((v >> 32) & 0xFF).astype(np.intp)]
               ^ t2[((v >> 40) & 0xFF).astype(np.intp)]
               ^ t1[((v >> 48) & 0xFF).astype(np.intp)]
               ^ t0[(v >> 56).astype(np.intp)])
    return crc


def _crc_rows_numpy(arr: np.ndarray, lengths: np.ndarray,
                    seed: int) -> np.ndarray:
    """Rows of a zero-padded (N, L) array -> (N,) uint32, pure numpy.

    Chunk-split + combine: each row splits into S power-of-two
    segments checksummed in lockstep across N*S lanes, a log2(S)-level
    tree of M^len combines folds them back, and the per-row zero
    padding is stripped by the inverse matrix.
    """
    n, l = arr.shape
    if n == 0:
        return np.zeros(0, np.uint32)
    seg = _pick_seg(n, max(l, 8))
    s = _next_pow2(max(1, -(-max(l, 1) // seg)))
    lp = s * seg
    if lp != l:
        padded = np.zeros((n, lp), np.uint8)
        padded[:, :l] = arr
        arr = padded
    lanes = np.ascontiguousarray(arr).reshape(n * s, seg)
    crc0 = np.zeros(n * s, np.uint32)
    crc0[::s] = np.uint32(seed)     # leftmost segment carries the seed
    crcs = _lockstep(lanes, crc0).reshape(n, s)
    width = seg
    while crcs.shape[1] > 1:        # combine pairs, doubling coverage
        mat = _zeros_matrix(width)
        crcs = _mat_apply(mat, crcs[:, 0::2]) ^ crcs[:, 1::2]
        width *= 2
    return crc32c_strip_zeros(
        crcs[:, 0],
        # lint: disable=device-path-host-sync -- (n,) length vector for the un-pad, not batch payload
        lp - np.asarray(lengths, np.int64))


def crc32c_numpy_one(data, crc: int = SEED) -> int:
    """Single-buffer numpy engine (``native._crc32c_py`` delegate)."""
    buf = np.frombuffer(data, np.uint8) if not isinstance(
        data, np.ndarray) else np.ascontiguousarray(data, np.uint8)
    if buf.size == 0:
        return crc & 0xFFFFFFFF
    return int(_crc_rows_numpy(buf.reshape(1, -1),
                               np.array([buf.size], np.int64), crc)[0])


# -- batched entry points ---------------------------------------------------

def crc32c_rows(arr, lengths=None, seed: int = SEED,
                backend: str | None = None) -> np.ndarray:
    """CRCs of the rows of a (N, L) uint8 array in one pass.

    ``lengths`` (optional, per-row) truncates row i to its first
    ``lengths[i]`` bytes -- the bytes beyond may be anything on the
    native path but are zeroed for the numpy engine.  ``backend``
    forces "native" or "numpy" (parity tests); default is the ladder.
    """
    arr = np.ascontiguousarray(arr, np.uint8)
    assert arr.ndim == 2, arr.shape
    n, l = arr.shape
    lens = (np.full(n, l, np.int64) if lengths is None
            # lint: disable=device-path-host-sync -- (n,) length vector of a host-engine call, not batch payload
            else np.asarray(lengths, np.int64))
    PERF.inc("batched_calls")
    PERF.inc("batched_bufs", n)
    PERF.inc("batched_bytes", int(lens.sum()))
    if backend != "numpy" and n:
        crcs = np.full(n, seed, np.uint32)
        offs = np.arange(n, dtype=np.uint64) * np.uint64(l)
        if native.crc32c_batch_native(crcs, arr.reshape(-1), offs,
                                      lens.astype(np.uint64)):
            PERF.inc("native_batches")
            return crcs
        if backend == "native":
            raise RuntimeError("native crc32c batch unavailable")
    PERF.inc("numpy_batches")
    if lengths is not None and bool((lens < l).any()):
        arr = arr.copy()
        arr[np.arange(l) >= lens[:, None]] = 0
    return _crc_rows_numpy(arr, lens, seed)


def crc32c_batch(bufs, seed: int = SEED,
                 backend: str | None = None) -> np.ndarray:
    """CRCs of a ragged sequence of buffers (bytes-like or uint8
    arrays) in one pass; empty buffers come back as the seed, exactly
    like the scalar call."""
    bufs = bufs if isinstance(bufs, (list, tuple)) else list(bufs)
    n = len(bufs)
    # fast marshal: one C-level join instead of a numpy view per
    # buffer (the per-buffer frombuffer was itself ~0.5 us -- most of
    # a scalar call's overhead smuggled back in)
    if all(type(b) is bytes for b in bufs):
        lens = np.fromiter((len(b) for b in bufs), np.int64, count=n)
        views = None
    else:
        views = []
        for b in bufs:
            if isinstance(b, np.ndarray):
                views.append(
                    np.ascontiguousarray(b, np.uint8).reshape(-1))
            else:
                views.append(np.frombuffer(b, np.uint8))
        lens = np.fromiter((v.size for v in views), np.int64, count=n)
    PERF.inc("batched_calls")
    PERF.inc("batched_bufs", n)
    PERF.inc("batched_bytes", int(lens.sum()))
    if n == 0:
        return np.zeros(0, np.uint32)
    if backend != "numpy":
        crcs = np.full(n, seed, np.uint32)
        # marshaling strategy: big buffers go by pointer table (zero
        # copy, per-buffer cost only), small ones by one C-level join
        # (per-byte memcpy beats 393k pointer-object conversions)
        if views is None and int(lens.sum()) >= 768 * n:
            if native.crc32c_batch_native_ptrs(crcs, bufs, lens):
                PERF.inc("native_batches")
                return crcs
        if views is None:
            flat = np.frombuffer(b"".join(bufs), np.uint8)
        else:
            flat = views[0] if n == 1 else np.concatenate(views)
        offs = np.zeros(n + 1, np.uint64)
        np.cumsum(lens, out=offs[1:])
        if native.crc32c_batch_native(crcs, flat, offs[:-1],
                                      offs[1:] - offs[:-1]):
            PERF.inc("native_batches")
            return crcs
        if backend == "native":
            raise RuntimeError("native crc32c batch unavailable")
    PERF.inc("numpy_batches")
    if views is None:
        views = [np.frombuffer(b, np.uint8) for b in bufs]
    # bucket by power-of-two padded length so one huge buffer cannot
    # blow the padded matrix up to N x max(L)
    out = np.empty(n, np.uint32)
    classes: dict[int, list[int]] = {}
    for i, ln in enumerate(lens):
        classes.setdefault(_next_pow2(max(int(ln), 64)), []).append(i)
    for cap, idx in sorted(classes.items()):
        rows = np.zeros((len(idx), cap), np.uint8)
        for r, i in enumerate(idx):
            rows[r, :lens[i]] = views[i]
        out[idx] = _crc_rows_numpy(rows, lens[idx], seed)
    return out


# -- JAX device kernel ------------------------------------------------------

def fused_enabled() -> bool:
    """Device-fused CRC allowed (CEPH_TPU_NO_FUSED_CRC gates it off)."""
    return not os.environ.get("CEPH_TPU_NO_FUSED_CRC")


@functools.lru_cache(maxsize=64)
def _crc_chunks_compiled(l: int):
    """Jitted (N, l) uint8 -> (N,) uint32 chunk CRCs (default seed),
    slice-by-8 fori_loop over the lane axis."""
    import jax
    import jax.numpy as jnp
    # host constant staged per trace: device-caching the tables here
    # would capture a tracer when the first call happens inside an
    # outer trace (the MeshCodec fused launch) and poison the cache
    tnp = _tables()
    n8 = l // 8

    def fn(x):
        t = jnp.asarray(tnp)
        crc = jnp.full((x.shape[0],), SEED, jnp.uint32)
        xu = x.astype(jnp.uint32)

        def body8(j, crc):
            b = jax.lax.dynamic_slice_in_dim(xu, 8 * j, 8, axis=1)
            lo = (crc ^ b[:, 0] ^ (b[:, 1] << 8)
                  ^ (b[:, 2] << 16) ^ (b[:, 3] << 24))
            hi = (b[:, 4] ^ (b[:, 5] << 8)
                  ^ (b[:, 6] << 16) ^ (b[:, 7] << 24))
            return (t[7][lo & 0xFF] ^ t[6][(lo >> 8) & 0xFF]
                    ^ t[5][(lo >> 16) & 0xFF] ^ t[4][lo >> 24]
                    ^ t[3][hi & 0xFF] ^ t[2][(hi >> 8) & 0xFF]
                    ^ t[1][(hi >> 16) & 0xFF] ^ t[0][hi >> 24])

        if n8:
            crc = jax.lax.fori_loop(0, n8, body8, crc)
        for j in range(8 * n8, l):       # static tail, < 8 steps
            crc = t[0][(crc ^ xu[:, j]) & 0xFF] ^ (crc >> 8)
        return crc

    return jax.jit(fn)


def crc32c_chunks_traced(x):
    """Trace-safe core of ``crc32c_device_chunks``: same math, no perf
    side effects, safe to INLINE inside a larger jitted program -- the
    MeshCodec fused path calls this so the chunk CRCs are part of the
    one sharded launch that produces the parity (the CRC math is
    row-independent, so GSPMD partitions it over the stripe axis with
    no collective)."""
    import jax.numpy as jnp
    xd = jnp.asarray(x, jnp.uint8)
    lead, l = xd.shape[:-1], xd.shape[-1]
    if l == 0:                      # zero-length chunks: seed, no kernel
        return jnp.full(lead, SEED, jnp.uint32)
    flat = xd.reshape((-1, l))
    return _crc_chunks_compiled(l)(flat).reshape(lead)


def crc32c_device_chunks(x):
    """(..., L) uint8 (host or device array) -> (...,) uint32 chunk
    CRCs computed on the accelerator.  Returns a DEVICE array so the
    caller fetches it together with the parity of the same launch
    window -- the fused path of the codec batcher."""
    out = crc32c_chunks_traced(x)
    PERF.inc("fused_launches")
    PERF.inc("fused_crcs", int(np.prod(out.shape, dtype=np.int64)))
    return out


def crc32c_resident(buf) -> int:
    """Whole-buffer CRC32C of a RESIDENT shard buffer as ONE device
    launch: the buffer splits into equal power-of-two chunks whose CRCs
    come back from the device kernel, the GF(2) fold combines them,
    and the inverse matrix strips the zero padding -- no host-side
    pass over the payload bytes.  This is how scrub re-verifies a
    cache-resident shard against its write-time tag without ever
    re-materializing it through the store."""
    # lint: disable=device-path-host-sync -- input view of an already-resident buffer, not a transfer
    arr = np.ascontiguousarray(
        np.frombuffer(buf, np.uint8) if isinstance(
            buf, (bytes, bytearray, memoryview))
        else np.asarray(buf, np.uint8).reshape(-1))
    n = arr.size
    if n == 0:
        return SEED
    # up to ~256 parallel lanes; the fold is log-free (linear scan of
    # few chunk registers), so chunk count stays small
    chunk = max(64, _next_pow2(-(-n // 256)))
    pad = (-n) % chunk
    if pad:
        padded = np.zeros(n + pad, np.uint8)
        padded[:n] = arr
        arr = padded
    rows = arr.reshape(-1, chunk)
    # lint: disable=device-path-host-sync -- the single post-launch materialization of the chunk CRCs
    crcs = np.asarray(crc32c_device_chunks(rows), np.uint32)
    out = np.asarray(fold_chunk_crcs(crcs, chunk), np.uint32).reshape(1)
    if pad:
        out = crc32c_strip_zeros(out, pad)
    PERF.inc("resident_crcs")
    return int(out[0])
