"""TPU compute kernels (JAX/XLA/Pallas) for the erasure-code hot path.

Re-exports resolve lazily (PEP 562): ``crc32c_batch`` is a numpy-only
module consumed by jax-free paths (native fallback, blockstore, scrub),
so importing the package must not pay the jax stack -- only touching a
GF kernel name pulls ``gf2kernels``.
"""

_GF_EXPORTS = ("gf_matmul_device", "gf_matmul_batch_device",
               "bitmatrix_i8", "clear_kernel_cache")
# the XOR-schedule compiler is numpy-only at import time (jax loads
# lazily inside its device executors), so these stay jax-free too
_XS_EXPORTS = ("compile_schedule", "schedule_for",
               "scheduled_xor_matmul")

__all__ = list(_GF_EXPORTS) + list(_XS_EXPORTS)


def __getattr__(name):
    if name in _GF_EXPORTS:
        from . import gf2kernels
        return getattr(gf2kernels, name)
    if name in _XS_EXPORTS:
        from . import xor_schedule
        return getattr(xor_schedule, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
