"""TPU compute kernels (JAX/XLA/Pallas) for the erasure-code hot path."""

from .gf2kernels import (  # noqa: F401
    gf_matmul_device,
    gf_matmul_batch_device,
    bitmatrix_i8,
    clear_kernel_cache,
)
