"""XOR-schedule compiler: CSE-minimized GF(2) bit-matrix kernels.

The dense bit-matmul family (ops/gf2kernels.py) multiplies by a matrix
that is mostly zeros: a Cauchy k=8,m=3 bitmatrix is ~half ones, and a
liberation RAID-6 matrix is minimal-density by construction.  The MXU
does not care (the systolic array runs the full contraction either
way), but every OTHER engine does -- the XLA:CPU fallback and the host
numpy path pay for every zero.  "Accelerating XOR-based Erasure Coding
using Program Optimization Techniques" (PAPERS.md) shows the fix: the
bit-matrix IS a set of XOR equations, and common-subexpression
elimination over those equations plus a good evaluation order cuts the
XOR count severalfold.

This module is that compiler:

  * ``compile_schedule`` lowers any (R, C) GF(2) 0/1 matrix to an
    ``XorSchedule``: greedy pairwise CSE (repeatedly extract the
    operand pair shared by the most equations into a temporary -- the
    paper's normalization+scheduling passes), then a just-in-time
    topological lowering into SSA XOR ops with temporaries scheduled
    immediately before first use and freed after last use, so the live
    register set stays small and REPORTED (``peak_registers``); a
    schedule whose peak exceeds ``max_registers`` is re-compiled with
    a smaller temp budget until the bound holds;
  * schedules are cached PROCESS-WIDE keyed by matrix digest (the
    VectorCrush one-compile-serves-all lesson): every OSD of an
    in-process cluster shares one compile;
  * three executors, all byte-identical by construction: ``apply_host``
    (numpy rows -- the BitMatrixCodec data path), ``apply_bits_traced``
    (a jax-traceable (k, N) bytes -> (r, N) bytes block shared by the
    jitted XLA family and the MeshCodec shard_map block), and a Pallas
    tile kernel behind the same ``_want_pallas`` gate as the dense
    family;
  * ``sched_matmul_batch_device`` is the batched kernel family itself:
    the same (B, k, L) signature, padding buckets and one-launch
    contract as the dense ``gN`` family, parity-gated on first use per
    (matrix, shape) against the host oracle with transparent fallback;
  * ``want_scheduled`` is the per-(matrix, shape) cost model: env
    override, then the autotuned winner recorded in ``gf2_tuned.json``
    (``tools/ec_autotune.py`` sweeps dense-vs-scheduled per
    (k, m, chunk)), then a backend heuristic comparing scheduled XOR
    terms against the dense contraction length.

Jax is imported lazily: the host executor serves jax-free paths (the
jerasure bitmatrix plugins) and must not pull the device stack in.
"""

from __future__ import annotations

import functools
import hashlib
import os
import threading
from collections import Counter
from dataclasses import dataclass

import numpy as np

# default register-file bound for compiled schedules: peak concurrently
# live temporaries.  64 matches a comfortable vector-register budget on
# every target; the compiler PROVES the bound (re-compiling with fewer
# temps if the first schedule exceeds it) rather than assuming it.
DEFAULT_MAX_REGISTERS = 64

# cost-model default: on CPU engines the dense contraction runs R*C
# multiply-accumulates per byte column while the schedule runs n_terms
# XORs; the MAC is not 1:1 with an XOR (XLA vectorizes both), so the
# schedule must beat the dense length by this factor to be picked.
CPU_DENSE_DISCOUNT = 0.35

# matrices beyond this many cells are not worth a Python-side CSE pass
# (nothing on the codec path is remotely this large)
MAX_COMPILE_CELLS = 1 << 18

# the SPECULATIVE compile bound: the backend heuristic and the
# build-time warms compile on the chance the schedule wins, and the
# greedy-CSE pass is quadratic in pair count -- a dense 20k-cell
# matrix (the pmsr k=5 parity bitmatrix) costs ~15s of pure Python,
# which would stall codec init / the first launch's event loop.
# Above this bound only an EXPLICIT opt-in compiles: a measured
# gf2_tuned.json entry or CEPH_TPU_XOR_SCHED=1 (both accept the
# one-time cost knowingly).
SPECULATIVE_MAX_CELLS = 1 << 14

# below this many bytes per plane row the naive xor_matmul's C-level
# gather+reduce beats the schedule's one-numpy-call-per-XOR dispatch
# overhead (measured crossover ~10 KiB; CEPH_TPU_XOR_SCHED=1 forces
# the scheduled engine anyway, e.g. for parity tests)
HOST_MIN_LANE = 16384


@dataclass(frozen=True)
class XorSchedule:
    """A compiled XOR evaluation plan for one GF(2) bit-matrix.

    Value ids are SSA: ids ``0..n_in-1`` are the input rows, id
    ``n_in + i`` is the value produced by ``ops[i] = (a, b)`` (the XOR
    of values ``a`` and ``b``).  ``outputs[j]`` names the value holding
    output row j -- possibly an input id (a single-one matrix row is a
    copy) or -1 (an all-zero row).
    """

    digest: str
    n_in: int
    n_out: int
    ops: tuple[tuple[int, int], ...]
    outputs: tuple[int, ...]
    naive_terms: int
    peak_registers: int
    max_registers: int

    @property
    def n_terms(self) -> int:
        return len(self.ops)

    @property
    def terms_saved(self) -> int:
        return self.naive_terms - self.n_terms

    @property
    def reduction(self) -> float:
        if not self.naive_terms:
            return 0.0
        return 1.0 - self.n_terms / self.naive_terms

    def last_uses(self) -> list[int]:
        """For each op value, the last OP index that reads it (its own
        definition index when no later op does).  Output stores happen
        eagerly at definition time (the executors write the output row
        the moment its value exists), so they do not extend a value's
        lifetime."""
        last = list(range(len(self.ops)))
        n_in = self.n_in
        for i, (a, b) in enumerate(self.ops):
            if a >= n_in:
                last[a - n_in] = i
            if b >= n_in:
                last[b - n_in] = i
        return last

    def outputs_by_value(self) -> dict[int, list[int]]:
        """value id -> output rows it serves (eager-store map)."""
        by_val: dict[int, list[int]] = {}
        for j, o in enumerate(self.outputs):
            by_val.setdefault(o, []).append(j)
        return by_val


def matrix_digest(matrix: np.ndarray) -> str:
    """Content digest of a 0/1 matrix; the process-wide schedule key."""
    m = np.ascontiguousarray(matrix, dtype=np.uint8)
    h = hashlib.sha256()
    h.update(b"%d,%d;" % m.shape)
    h.update(m.tobytes())
    return h.hexdigest()[:16]


def naive_xor_terms(matrix: np.ndarray) -> int:
    """XOR count of the row-by-row evaluation (ones - 1 per nonzero
    row): the baseline the schedule is measured against."""
    ones = (np.ascontiguousarray(matrix, np.uint8) != 0).sum(axis=1)
    return int(np.maximum(ones - 1, 0).sum())


# ---------------------------------------------------------------------------
# CSE + lowering
# ---------------------------------------------------------------------------

def _greedy_cse(rows: list[set[int]], n_in: int,
                max_temps: int) -> list[tuple[int, int]]:
    """Extract the most-shared operand pair into a temporary until no
    pair is shared by two equations (or the temp budget is spent).
    Deterministic: ties break to the smallest (a, b) pair.  Returns the
    temp definitions; ``rows`` is rewritten in place to reference them.
    """
    counts: Counter[tuple[int, int]] = Counter()
    for row in rows:
        ordered = sorted(row)
        for i, a in enumerate(ordered):
            for b in ordered[i + 1:]:
                counts[(a, b)] += 1
    temps: list[tuple[int, int]] = []
    while len(temps) < max_temps and counts:
        (a, b), n = min(counts.items(), key=lambda kv: (-kv[1], kv[0]))
        if n < 2:
            break
        t = n_in + len(temps)
        temps.append((a, b))
        for row in rows:
            if a in row and b in row:
                # incremental pair-count maintenance: pairs that lose a
                # member leave, pairs gaining the temp enter
                row.discard(a)
                row.discard(b)
                dropped = [(a, b)]
                for x in row:
                    for m in (a, b):
                        dropped.append((x, m) if x < m else (m, x))
                for pair in dropped:
                    counts[pair] -= 1
                    if not counts[pair]:
                        del counts[pair]
                for x in row:
                    counts[(x, t) if x < t else (t, x)] += 1
                row.add(t)
    return temps


def _lower(n_in: int, temps: list[tuple[int, int]],
           rows: list[set[int]]) -> tuple[tuple, tuple]:
    """Just-in-time topological lowering: a temporary's op is emitted
    immediately before its first use, outputs are left-to-right XOR
    chains.  Returns (ops, outputs) in SSA ids."""
    ops: list[tuple[int, int]] = []
    emitted: dict[int, int] = {}

    def resolve(x: int) -> int:
        if x < n_in:
            return x
        sid = emitted.get(x)
        if sid is None:
            a, b = temps[x - n_in]
            ia, ib = resolve(a), resolve(b)
            ops.append((ia, ib))
            sid = emitted[x] = n_in + len(ops) - 1
        return sid

    outputs: list[int] = []
    for row in rows:
        operands = sorted(row)
        if not operands:
            outputs.append(-1)
            continue
        acc = resolve(operands[0])
        for x in operands[1:]:
            ops.append((acc, resolve(x)))
            acc = n_in + len(ops) - 1
        outputs.append(acc)
    return tuple(ops), tuple(outputs)


def _peak_registers(n_in: int, ops: tuple, outputs: tuple) -> int:
    """Max concurrently-live computed values over the schedule.
    Inputs are free (they are the resident input array) and output
    stores happen at definition time, so a value lives from its op to
    its last OP use."""
    last = list(range(len(ops)))
    for i, (a, b) in enumerate(ops):
        for v in (a, b):
            if v >= n_in:
                last[v - n_in] = i
    deaths = Counter(last)
    live = peak = 0
    for i in range(len(ops)):
        live += 1
        peak = max(peak, live)
        live -= deaths.get(i, 0)
    return peak


def compile_schedule(matrix: np.ndarray, *,
                     max_registers: int = DEFAULT_MAX_REGISTERS,
                     max_temps: int | None = None) -> XorSchedule:
    """Lower a GF(2) 0/1 matrix to a CSE-minimized XOR schedule.

    Deterministic: the same matrix bytes always produce the identical
    schedule (pinned by tests/test_xor_schedule.py), so the digest is a
    complete cache key across processes and rounds.
    """
    m = np.ascontiguousarray(matrix, dtype=np.uint8)
    if m.ndim != 2:
        raise ValueError(f"bit-matrix must be 2-D, got {m.shape}")
    if m.size > MAX_COMPILE_CELLS:
        raise ValueError(f"matrix {m.shape} too large to schedule")
    n_out, n_in = m.shape
    digest = matrix_digest(m)
    naive = naive_xor_terms(m)
    budget = max_temps if max_temps is not None else m.size
    while True:
        rows = [set(np.flatnonzero(r).tolist()) for r in m]
        temps = _greedy_cse(rows, n_in, budget)
        ops, outputs = _lower(n_in, temps, rows)
        peak = _peak_registers(n_in, ops, outputs)
        if peak <= max_registers or budget == 0:
            break
        # too much sharing to hold in the register file: shrink the
        # temp budget (halving terminates at the naive schedule, whose
        # only live value is the running accumulator)
        budget = min(budget, len(temps)) // 2
    return XorSchedule(digest=digest, n_in=n_in, n_out=n_out, ops=ops,
                       outputs=outputs, naive_terms=naive,
                       peak_registers=peak, max_registers=max_registers)


# ---------------------------------------------------------------------------
# process-wide schedule cache + launch stats
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_SCHEDULES: dict[str, XorSchedule] = {}


class _Stats:
    """Process-wide scheduled-launch counters.  The per-OSD
    CodecBatcher samples deltas around every coalesced launch into its
    ``ec_batch`` perf set (xor_sched_launches / xor_sched_fallbacks /
    xor_terms_saved), so the dynamic counters stay live wherever the
    scheduled engine actually served."""

    __slots__ = ("launches", "fallbacks", "terms_saved")

    def __init__(self) -> None:
        self.launches = 0
        self.fallbacks = 0
        self.terms_saved = 0

    def snapshot(self) -> tuple[int, int, int]:
        with _LOCK:
            return (self.launches, self.fallbacks, self.terms_saved)

    def note_launch(self, sched: XorSchedule) -> None:
        with _LOCK:
            self.launches += 1
            self.terms_saved += sched.terms_saved

    def note_fallback(self) -> None:
        with _LOCK:
            self.fallbacks += 1


STATS = _Stats()


def schedule_for(matrix: np.ndarray, *,
                 compile_missing: bool = True) -> XorSchedule | None:
    """The cached schedule for a bit-matrix, compiling (and caching it
    process-wide) on miss unless ``compile_missing`` is False."""
    digest = matrix_digest(matrix)
    with _LOCK:
        sched = _SCHEDULES.get(digest)
    if sched is not None or not compile_missing:
        return sched
    sched = compile_schedule(matrix)
    with _LOCK:
        return _SCHEDULES.setdefault(digest, sched)


def cached_schedule(matrix: np.ndarray) -> XorSchedule | None:
    return schedule_for(matrix, compile_missing=False)


def registered(digest: str) -> XorSchedule:
    with _LOCK:
        return _SCHEDULES[digest]


def clear_schedule_cache() -> None:
    with _LOCK:
        _SCHEDULES.clear()
    _sched_health.clear()
    for fn in (_compiled_sched_batch, _compiled_sched_pallas):
        fn.cache_clear()


# ---------------------------------------------------------------------------
# executors
# ---------------------------------------------------------------------------

def apply_host(sched: XorSchedule, planes: np.ndarray) -> np.ndarray:
    """(n_in, N) byte rows -> (n_out, N) byte rows on the host.

    Output rows are stored the moment their value exists and
    temporaries are freed at last use, so the working set matches the
    schedule's ``peak_registers`` bound."""
    planes = np.ascontiguousarray(planes, dtype=np.uint8)
    assert planes.shape[0] == sched.n_in, (planes.shape, sched.n_in)
    n_in = sched.n_in
    last = sched.last_uses()
    by_val = sched.outputs_by_value()
    out = np.zeros((sched.n_out, planes.shape[1]), dtype=np.uint8)
    for o, js in by_val.items():
        if 0 <= o < n_in:                  # single-one rows: copies
            for j in js:
                out[j] = planes[o]
    vals: dict[int, np.ndarray] = {}
    for i, (a, b) in enumerate(sched.ops):
        v = np.bitwise_xor(planes[a] if a < n_in else vals[a - n_in],
                           planes[b] if b < n_in else vals[b - n_in])
        for j in by_val.get(n_in + i, ()):
            out[j] = v                     # eager store at definition
        if last[i] > i:                    # a later op still needs it
            vals[i] = v
        for x in (a, b):
            if x >= n_in and last[x - n_in] == i:
                vals.pop(x - n_in, None)
    return out


def scheduled_xor_matmul(matrix: np.ndarray, planes: np.ndarray, *,
                         allow_compile: bool = True) -> np.ndarray:
    """Drop-in ``gf.gf2w.xor_matmul`` with the scheduled engine: uses
    the cached (or, when allowed and profitable, freshly compiled)
    schedule, else the naive row-by-row XOR.  The BitMatrixCodec
    encode path compiles (the matrix is hot for the codec's lifetime);
    the repair path passes ``allow_compile=False`` and rides a
    schedule only when one is already cached (warmed at decode-matrix
    build time)."""
    env = os.environ.get("CEPH_TPU_XOR_SCHED")
    sched = cached_schedule(matrix)
    if sched is None and allow_compile \
            and matrix.size <= MAX_COMPILE_CELLS \
            and env != "0":
        sched = schedule_for(matrix)
    if sched is None or env == "0" \
            or sched.n_terms >= sched.naive_terms \
            or (env != "1" and planes.shape[1] < HOST_MIN_LANE):
        from ..gf.gf2w import xor_matmul
        return xor_matmul(np.ascontiguousarray(matrix, np.uint8),
                          planes)
    out = apply_host(sched, planes)
    STATS.note_launch(sched)
    return out


def warm_schedule(matrix: np.ndarray) -> XorSchedule | None:
    """Compile-and-cache when the matrix qualifies (called at decode-
    matrix build time, so subsequent repairs find a schedule cached and
    never pay the compile on the read path)."""
    if _env_off() or matrix.size > MAX_COMPILE_CELLS:
        return None
    sched = schedule_for(matrix)
    return sched if sched.n_terms < sched.naive_terms else None


def warm_gf8_schedule(matrix: np.ndarray) -> XorSchedule | None:
    """``warm_schedule`` for a GF(2^8) coefficient matrix: expand to
    the GF(2) bit-matrix the batched kernel family keys on
    (``gf2kernels.bitmatrix_i8``) and compile-and-cache its schedule.
    Called when a codec builds a repair/local-parity matrix, so the
    first batched launch with it finds the schedule cached and the
    read/recovery path never pays the CSE compile.  Matrices above
    the speculative bound are skipped -- codec init (which the
    monitor runs per profile validation) must never stall on a
    multi-second CSE pass for a matrix the cost model would not pick
    speculatively anyway."""
    if _env_off():
        return None
    from .gf2kernels import bitmatrix_i8
    bm = bitmatrix_i8(np.ascontiguousarray(matrix, np.uint8))
    if bm.size > SPECULATIVE_MAX_CELLS:
        return None
    return warm_schedule(bm)


def apply_bits_traced(sched: XorSchedule, data_u8):
    """(k, N) bytes -> (n_out//8, N) bytes under trace: unpack to bit
    planes, run the schedule, pack.  The jax-traceable core shared by
    the jitted XLA family, the MeshCodec shard_map block and the
    Pallas tile kernel -- same plane order as the dense family (plane
    8j+s = bit s of chunk j, matching ``bitmatrix_i8`` columns)."""
    import jax.numpy as jnp
    k = data_u8.shape[0]
    assert sched.n_in == 8 * k, (sched.n_in, k)
    assert sched.n_out % 8 == 0, sched.n_out
    d = data_u8.astype(jnp.int32)
    planes = [(d[j] >> s) & 1 for j in range(k) for s in range(8)]
    n_in = sched.n_in
    last = sched.last_uses()
    by_val = sched.outputs_by_value()
    outvals: list = [None] * sched.n_out
    for o, js in by_val.items():
        if 0 <= o < n_in:                  # single-one rows: copies
            for j in js:
                outvals[j] = planes[o]
    vals: dict[int, object] = {}
    for i, (a, b) in enumerate(sched.ops):
        v = (planes[a] if a < n_in else vals[a - n_in]) \
            ^ (planes[b] if b < n_in else vals[b - n_in])
        for j in by_val.get(n_in + i, ()):
            outvals[j] = v                 # eager store at definition
        if last[i] > i:
            vals[i] = v
        for x in (a, b):
            # free dead tracers so the unrolled graph's live set
            # matches the schedule's register bound
            if x >= n_in and last[x - n_in] == i:
                vals.pop(x - n_in, None)
    zero = jnp.zeros_like(planes[0])
    out_rows = []
    for r in range(sched.n_out // 8):
        acc = None
        for s in range(8):
            o = outvals[8 * r + s]
            if o is None:
                continue
            term = o << s if s else o
            acc = term if acc is None else acc | term
        out_rows.append(zero if acc is None else acc)
    return jnp.stack(out_rows).astype(jnp.uint8)


# ---------------------------------------------------------------------------
# the batched (B, k, L) kernel family
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=512)
def _compiled_sched_batch(digest: str, b: int, k: int, l: int):
    import jax
    sched = registered(digest)

    def fn(xd):  # (B, k, L) -> (B, r, L), whole path under one jit
        flat = xd.transpose(1, 0, 2).reshape(k, b * l)
        out = apply_bits_traced(sched, flat)
        return out.reshape(-1, b, l).transpose(1, 0, 2)

    return jax.jit(fn)


def _sched_pallas_kernel_body(sched: XorSchedule, k: int, tile: int):
    def kernel(data_ref, out_ref):
        import jax.numpy as jnp
        data = data_ref[...].reshape(k, tile)
        rows = apply_bits_traced(sched, data)
        out_ref[...] = rows.reshape(out_ref.shape).astype(jnp.uint8)
    return kernel


@functools.lru_cache(maxsize=256)
def _compiled_sched_pallas(digest: str, b: int, k: int, l: int,
                           tile: int):
    """Pallas tile path: the scheduled XOR chain fused per VMEM tile,
    same grid walk as the dense batch kernel."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    sched = registered(digest)
    r = sched.n_out // 8
    interpret = bool(os.environ.get("CEPH_TPU_PALLAS_INTERPRET"))
    fn = pl.pallas_call(
        _sched_pallas_kernel_body(sched, k, tile),
        out_shape=jax.ShapeDtypeStruct((b, r, l), np.uint8),
        grid=(b, l // tile),
        in_specs=[
            pl.BlockSpec((1, k, tile), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, r, tile), lambda i, j: (i, 0, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )
    return jax.jit(fn)


# per (digest, shape) health: None=untested (parity gate runs on first
# use), True=good, False=fall back to the dense family
_sched_health: dict[tuple, bool] = {}


def _env_off() -> bool:
    return os.environ.get("CEPH_TPU_XOR_SCHED") == "0"


def _tuned_engine(k: int, m: int, lane: int) -> str | None:
    """The autotuned dense-vs-scheduled winner for this (k, m) family
    from gf2_tuned.json (``tools/ec_autotune.py`` writes it), exact
    chunk first, family default second."""
    from .gf2kernels import _tuned_cfgs
    table = _tuned_cfgs().get("xor_sched")
    if not isinstance(table, dict):
        return None
    hit = table.get(f"{k},{m},{lane}") or table.get(f"{k},{m}")
    if isinstance(hit, dict):
        hit = hit.get("engine")
    return hit if hit in ("dense", "scheduled") else None


def want_scheduled(bitmatrix: np.ndarray, lane: int, backend: str,
                   have_packed: bool = False) -> XorSchedule | None:
    """The per-(matrix, shape) cost model: the schedule to launch with,
    or None (dense wins).  Precedence: CEPH_TPU_XOR_SCHED env override,
    the autotuned winner recorded in gf2_tuned.json, then the backend
    heuristic -- scheduled XOR terms vs the dense contraction length
    (R*C MACs per byte column), discounted because a vectorized MAC
    and a vectorized XOR are not 1:1.  MXU-bearing backends (and any
    caller whose packed pallas family is live, ``have_packed``)
    default dense: the systolic array runs the zeros for free, so
    only a measured tuned entry may override it there."""
    env = os.environ.get("CEPH_TPU_XOR_SCHED")
    if env == "0":
        return None
    if bitmatrix.size > MAX_COMPILE_CELLS \
            or bitmatrix.shape[0] % 8 or bitmatrix.shape[1] % 8:
        return None
    if env == "1":
        return schedule_for(bitmatrix)
    tuned = _tuned_engine(bitmatrix.shape[1] // 8,    # k data chunks
                          bitmatrix.shape[0] // 8,    # m parity rows
                          lane)
    if tuned == "scheduled":
        return schedule_for(bitmatrix)
    if tuned == "dense":
        return None
    if backend != "cpu" or have_packed:
        return None
    if bitmatrix.size > SPECULATIVE_MAX_CELLS:
        return None            # dense family serves; tune to opt in
    sched = schedule_for(bitmatrix)
    dense_macs = bitmatrix.shape[0] * bitmatrix.shape[1]
    if sched.n_terms <= CPU_DENSE_DISCOUNT * dense_macs:
        return sched
    return None


def sched_matmul_batch_device(sched: XorSchedule, matrix: np.ndarray,
                              xd, b: int, k: int, l: int):
    """Launch the scheduled kernel family for a (B, k, L) device batch
    of the (r, k) GF(2^8) coefficient ``matrix``; returns the (B, r, L)
    device output or None (failed / parity-rejected -> the caller's
    dense family serves).  Same padding buckets and one-launch contract
    as the dense path; the Pallas tile kernel serves behind the same
    ``_want_pallas`` gate."""
    from .gf2kernels import _pick_tile, _want_pallas
    key = (sched.digest, b, k, l)
    if _sched_health.get(key) is False:
        return None
    try:
        fn = None
        if _want_pallas():
            tile = _pick_tile(l)
            if tile:
                fn = _compiled_sched_pallas(sched.digest, b, k, l, tile)
        if fn is None:
            fn = _compiled_sched_batch(sched.digest, b, k, l)
        out = fn(xd)
        if key not in _sched_health:
            # one-time byte-parity gate vs the host oracle on a small
            # slice; a silently-wrong schedule must never serve
            from ..gf import gf_matmul
            ncheck = min(256, l)
            nb = min(b, 2)
            # lint: disable=device-path-host-sync -- one-time parity gate vs the host oracle, bounded slice
            got = np.asarray(out[:nb, :, :ncheck])
            # lint: disable=device-path-host-sync -- one-time parity gate vs the host oracle, bounded slice
            sample = np.asarray(xd[:nb, :, :ncheck])
            for i in range(nb):
                if not np.array_equal(got[i],
                                      gf_matmul(matrix, sample[i])):
                    _sched_health[key] = False
                    STATS.note_fallback()
                    return None
            _sched_health[key] = True
        STATS.note_launch(sched)
        return out
    except Exception:
        _sched_health[key] = False
        STATS.note_fallback()
        return None


def maybe_batch_scheduled(matrix: np.ndarray, xd, b: int, k: int,
                          l: int):
    """The gf2kernels routing hook: run the coefficient-matrix batch
    through the scheduled family when the cost model picks it.  Returns
    the device output or None (dense family serves)."""
    try:
        import jax
        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    from .gf2kernels import _want_pallas, bitmatrix_i8
    bm = bitmatrix_i8(matrix)
    sched = want_scheduled(bm, l, backend,
                           have_packed=_want_pallas())
    if sched is None:
        return None
    return sched_matmul_batch_device(sched, matrix, xd, b, k, l)
