"""GF(2^8) matrix multiply as GF(2) bit-matmul on the TPU MXU.

The reference's hot loop is ISA-L's ``ec_encode_data`` -- an (r,k) GF(2^8)
coefficient matrix applied to k data chunks (src/erasure-code/isa/
ErasureCodeIsa.cc:128, called from the OSD write path via ECUtil::encode,
src/osd/ECUtil.cc:134).  On TPU we reformulate: multiplication by a GF(2^8)
constant is linear over GF(2), so the whole stripe encode is

    parity_bits(8r, N) = W(8r, 8k) @ data_bits(8k, N)  (mod 2)

with W the bit-expanded coefficient matrix.  That is a plain int8 matmul --
exactly what the MXU does -- plus cheap VPU unpack/pack around it.  Batching
thousands of stripes makes N huge, which is the regime the systolic array
wants.  Byte-identical to the host/numpy path by construction.

Two executions are provided:
  * XLA path (`_gf_matmul_xla`): portable, used on CPU and as fallback.
  * Pallas path (`_gf_matmul_pallas`): fuses unpack+dot+pack per VMEM tile
    so HBM traffic is just bytes in / parity out.
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp

from ..gf.gf8 import matrix_to_bitmatrix

# column-tile width for the pallas kernel; also the padding bucket for the
# XLA path so recompiles stay bounded
LANE_TILE = 8192


def bucket_batch(b: int) -> int:
    """Round a batch dimension up to a power of two.

    The batch kernels compile per (B, k, L); a coalescing caller (the
    OSD CodecBatcher) produces near-arbitrary B values, which would
    churn the jit cache with single-use executables.  Zero-padding the
    batch axis to the bucket is byte-exact (GF matmul rows are
    independent) and bounds distinct shapes to log2(max_batch).
    """
    if b <= 1:
        return 1
    n = 1
    while n < b:
        n *= 2
    return n


@functools.lru_cache(maxsize=256)
def _bitmatrix_cached(mat_bytes: bytes, r: int, k: int) -> np.ndarray:
    mat = np.frombuffer(mat_bytes, dtype=np.uint8).reshape(r, k)
    return matrix_to_bitmatrix(mat).astype(np.int8)


def bitmatrix_i8(matrix: np.ndarray) -> np.ndarray:
    """(r,k) GF coefficient matrix -> (8r,8k) int8 GF(2) matrix (cached)."""
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    return _bitmatrix_cached(matrix.tobytes(), *matrix.shape)


@functools.lru_cache(maxsize=256)
def _bitmatrix_device(mat_bytes: bytes, r: int, k: int):
    """Device-resident W: one upload per coefficient matrix, ever (the
    per-call jnp.asarray upload is a tunnel round trip otherwise)."""
    import jax
    return jax.device_put(_bitmatrix_cached(mat_bytes, r, k))


def bitmatrix_device(matrix: np.ndarray):
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    return _bitmatrix_device(matrix.tobytes(), *matrix.shape)


def _unpack_bits(data: jnp.ndarray) -> jnp.ndarray:
    """(k, N) uint8 -> (8k, N) int8 bit planes.

    Plane order matches matrix_to_bitmatrix: row 8j+s is bit s of chunk j.
    (bit 0 of an arithmetic right shift by s == bit s, for any sign.)
    """
    k = data.shape[0]
    planes = [((data >> s) & 1) for s in range(8)]
    # interleave to (k, 8, N) then flatten; stacking then reshape keeps the
    # 8j+s row order
    stacked = jnp.stack(planes, axis=1)  # (k, 8, N)
    return stacked.reshape(8 * k, data.shape[1]).astype(jnp.int8)


def _pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(8r, N) int32 bit rows (already mod 2) -> (r, N) uint8."""
    r8, n = bits.shape
    r = r8 // 8
    b = bits.reshape(r, 8, n)
    shifts = jnp.arange(8, dtype=jnp.int32).reshape(1, 8, 1)
    return (b << shifts).sum(axis=1).astype(jnp.uint8)


def _gf_matmul_math(w: jnp.ndarray, data_u8: jnp.ndarray) -> jnp.ndarray:
    bits = _unpack_bits(data_u8.astype(jnp.uint8))
    acc = jax.lax.dot_general(
        w, bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return _pack_bits(acc & 1)


@functools.partial(jax.jit, static_argnames=())
def _gf_matmul_xla(w: jnp.ndarray, data_u8: jnp.ndarray) -> jnp.ndarray:
    return _gf_matmul_math(w, data_u8)


# ---------------------------------------------------------------------------
# Pallas fused kernel
# ---------------------------------------------------------------------------

def _pallas_kernel_body(r8: int, k: int, tile: int):
    def kernel(w_ref, data_ref, out_ref):
        # Mosaic has no i8 shrui; widen to i32 for the bit extraction
        data = data_ref[...].reshape(k, tile).astype(jnp.int32)
        planes = [((data >> s) & 1) for s in range(8)]
        stacked = jnp.stack(planes, axis=1).reshape(8 * k, tile).astype(jnp.int8)
        acc = jax.lax.dot_general(
            w_ref[:], stacked,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        ) & 1
        r = r8 // 8
        b = acc.reshape(r, 8, tile)
        shifts = jnp.arange(8, dtype=jnp.int32).reshape(1, 8, 1)
        out_ref[...] = ((b << shifts).sum(axis=1).astype(jnp.uint8)
                        .reshape(out_ref.shape))
    return kernel


def _make_pallas_fn(r8: int, k: int, n: int, tile: int,
                    interpret: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid = (n // tile,)
    fn = pl.pallas_call(
        _pallas_kernel_body(r8, k, tile),
        out_shape=jax.ShapeDtypeStruct((r8 // 8, n), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((r8, 8 * k), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, tile), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r8 // 8, tile), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )
    return jax.jit(fn)


def _make_pallas_batch_fn(r8: int, k: int, b: int, l: int, tile: int,
                          interpret: bool = False):
    """Batched stripes without the (B,k,L)->(k,B*L) transpose copy: the
    grid walks (stripe, tile) and each step reads a (1,k,tile) block.
    One dispatch, HBM traffic = bytes in + parity out."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid = (b, l // tile)
    fn = pl.pallas_call(
        _pallas_kernel_body(r8, k, tile),
        out_shape=jax.ShapeDtypeStruct((b, r8 // 8, l), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((r8, 8 * k), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k, tile), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, r8 // 8, tile), lambda i, j: (i, 0, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# MXU-packed kernel family (v2/v3): the v1 kernel keeps the systolic
# array ~9% utilized -- the bit-matmul contraction is only 8k<=64 of the
# MXU's 128 rows, and the int32-widened unpack plus the sublane-strided
# pack burn VPU cycles on relayouts.  This family is parameterized so
# the best point can be AUTOTUNED on real hardware (tools/ec_autotune.py
# writes ceph_tpu/ops/gf2_tuned.json):
#   * group g: stripes packed per grid step; contraction is 8*k*g (=128
#     for the headline k=8 at g=2) so every MXU column-cycle carries g
#     byte columns of work;
#   * unpack "concat" (8 mask-compares concatenated plane-major, no
#     int32 widening) or "bcast" (one broadcast compare + reshape);
#   * matmul dtype int8 (MXU int path) or bf16 (MXU native path; bit
#     counts <=128 are exact in bf16);
#   * pack "vpu" (shift+sum over an (r,8,T) view) or "mxu" (a second
#     tiny matmul against a power-of-two matrix, keeping the relayout
#     on the systolic array);
#   * lane tile T.
# Byte-identical to the host path; selected at runtime with a parity
# self-check and transparent fallback to the v1 kernel.

G2_DEFAULT = {"unpack": "concat", "mm": "int8", "pack": "vpu",
              "tile": LANE_TILE}
_TUNED_PATH = os.path.join(os.path.dirname(__file__), "gf2_tuned.json")


@functools.lru_cache(maxsize=1)
def _tuned_cfgs() -> dict:
    """{str(k): cfg} autotuned on hardware; absent file = defaults."""
    try:
        import json
        with open(_TUNED_PATH) as f:
            return json.load(f)
    except Exception:
        return {}


def _g2_cfg(k: int) -> dict:
    cfg = dict(G2_DEFAULT)
    cfg.update(_tuned_cfgs().get(str(k), {}))
    env = os.environ.get("CEPH_TPU_G2_CFG")
    if env:
        for part in env.split(","):
            key, _, val = part.partition("=")
            cfg[key.strip()] = (int(val) if val.strip().isdigit()
                                else val.strip())
    return cfg


def pick_group(k: int, b: int) -> int:
    """Largest g with contraction 8*k*g <= 128 that divides the batch."""
    g = max(1, 16 // k)
    while g > 1 and (b % g or 8 * k * g > 128):
        g //= 2
    return g


@functools.lru_cache(maxsize=64)
def _w_gN_planemajor(mat_bytes: bytes, r: int, k: int,
                     g: int) -> np.ndarray:
    """(g*8r, 8*g*k): block-diagonal-by-stripe W whose columns match the
    plane-major layout of the unpacked concat of g stripes' chunks:
    RHS row s*(g*k) + j  <->  bit s of chunk j (stripe = j // k)."""
    w = _bitmatrix_cached(mat_bytes, r, k)      # (8r, 8k), col 8j+s
    r8 = 8 * r
    gk = g * k
    out = np.zeros((g * r8, 8 * gk), np.int8)
    for s in range(8):
        for j in range(gk):
            stripe, jj = divmod(j, k)
            out[stripe * r8:(stripe + 1) * r8, s * gk + j] = \
                w[:, 8 * jj + s]
    return out


def _kernel_body_gN(r8: int, k: int, g: int, tile: int, unpack: str,
                    mm: str, pack: str):
    r = r8 // 8
    gk = g * k

    def _pack_mat_iota():
        # (g*r, g*8r) with P[i, 8i+s] = 2**s, built in-kernel (pallas
        # cannot capture array constants) from iotas
        rows = jax.lax.broadcasted_iota(jnp.int32, (g * r, g * r8), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (g * r, g * r8), 1)
        pow2 = (1 << (cols % 8))
        return jnp.where(cols // 8 == rows, pow2, 0).astype(jnp.bfloat16)

    def kernel(w_ref, d_ref, o_ref):
        x = d_ref[...].reshape(gk, tile)             # g stripes' chunks
        if unpack == "bcast":
            masks = (1 << jax.lax.broadcasted_iota(
                jnp.int32, (8, 1, 1), 0)).astype(jnp.uint8)
            bits = (x[None] & masks) != 0            # (8, gk, T)
            bits = bits.reshape(8 * gk, tile)
        else:
            ps = [(x & np.uint8(1 << s)).astype(jnp.bool_)
                  for s in range(8)]
            bits = jnp.concatenate(ps, axis=0)       # (8gk, T) plane-major
        if mm == "bf16":
            # 0/1 entries, contraction <=128: sums are exact in bf16
            acc = jax.lax.dot_general(
                w_ref[:].astype(jnp.bfloat16), bits.astype(jnp.bfloat16),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).astype(jnp.int32) & 1
        else:
            acc = jax.lax.dot_general(
                w_ref[:], bits.astype(jnp.int8),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32) & 1     # (g*8r, T)
        if pack == "mxu":
            out = jax.lax.dot_general(
                _pack_mat_iota(), acc.astype(jnp.bfloat16),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)       # (g*r, T) exact
            o_ref[...] = out.astype(jnp.uint8).reshape(g, r, tile)
        else:
            # global row stripe*8r + 8i + t == ((stripe*r + i)*8) + t,
            # so one reshape groups each output byte's 8 bit rows
            b = acc.reshape(g * r, 8, tile)
            shifts = jnp.arange(8, dtype=jnp.int32).reshape(1, 8, 1)
            o_ref[...] = ((b << shifts).sum(axis=1).astype(jnp.uint8)
                          .reshape(g, r, tile))
    return kernel


def _make_pallas_batch_fn_gN(r8: int, k: int, b: int, l: int, g: int,
                             tile: int, unpack: str, mm: str, pack: str,
                             interpret: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    r = r8 // 8
    fn = pl.pallas_call(
        _kernel_body_gN(r8, k, g, tile, unpack, mm, pack),
        out_shape=jax.ShapeDtypeStruct((b, r, l), jnp.uint8),
        grid=(b // g, l // tile),
        in_specs=[
            pl.BlockSpec((g * r8, 8 * g * k), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((g, k, tile), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((g, r, tile), lambda i, j: (i, 0, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=512)
def _compiled(r8: int, k: int, n_padded: int, use_pallas: bool):
    if use_pallas:
        interpret = bool(os.environ.get("CEPH_TPU_PALLAS_INTERPRET"))
        return _make_pallas_fn(r8, k, n_padded, min(LANE_TILE, n_padded),
                               interpret=interpret)
    return _gf_matmul_xla


def clear_kernel_cache() -> None:
    for fn in (_compiled, _compiled_batch, _compiled_batch_gN,
               _w_gN_device, _w_gN_planemajor, _bitmatrix_cached,
               _bitmatrix_device, _tuned_cfgs):
        getattr(fn, "cache_clear", lambda: None)()
    _g2_health.clear()
    from .xor_schedule import clear_schedule_cache
    clear_schedule_cache()


def _want_pallas() -> bool:
    if os.environ.get("CEPH_TPU_NO_PALLAS"):
        return False
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


def _pad_n(n: int) -> int:
    # bucket N so the jit cache stays small: pad to LANE_TILE multiples,
    # with a small-size bucket ladder below one tile
    if n >= LANE_TILE:
        return ((n + LANE_TILE - 1) // LANE_TILE) * LANE_TILE
    b = 512
    while b < n:
        b *= 2
    return b


def gf_matmul_device(matrix: np.ndarray, data, *, out_np: bool = True):
    """(r,k) GF(2^8) coeff matrix x (k,N) bytes -> (r,N) bytes, on device.

    ``data`` may be a numpy array or a device array; the result is returned
    as numpy when out_np (plugin path) or left on device (bench path).
    """
    w = bitmatrix_device(matrix)
    r8, k8 = w.shape
    k = k8 // 8
    n = data.shape[1]
    n_pad = _pad_n(n)
    use_pallas = _want_pallas() and n_pad % 128 == 0
    fn = _compiled(r8, k, n_pad, use_pallas)
    xd = jnp.asarray(data, dtype=jnp.uint8)
    if n_pad != n:
        xd = jnp.pad(xd, ((0, 0), (0, n_pad - n)))
    out = fn(w, xd)
    if n_pad != n:
        out = out[:, :n]
    return np.asarray(out) if out_np else out


@functools.lru_cache(maxsize=256)
def _w_gN_device(mat_bytes: bytes, r: int, k: int, g: int, mm: str):
    w = _w_gN_planemajor(mat_bytes, r, k, g)
    if mm == "bf16":
        w = w.astype(jnp.bfloat16)
    return jax.device_put(w)


def _pick_tile(l: int, want: int = LANE_TILE) -> int:
    """Lane-tile ladder shared by the batch kernels; 0 = ineligible."""
    if l % want == 0:
        return want
    if l % LANE_TILE == 0:
        return LANE_TILE
    if l <= LANE_TILE and l % 128 == 0:
        return l
    return 0


@functools.lru_cache(maxsize=512)
def _compiled_batch_gN(r8: int, k: int, b: int, l: int, g: int,
                       unpack: str, mm: str, pack: str, tile_want: int):
    interpret = bool(os.environ.get("CEPH_TPU_PALLAS_INTERPRET"))
    tile = _pick_tile(l, tile_want)
    if not tile:
        return None
    return _make_pallas_batch_fn_gN(r8, k, b, l, g, tile, unpack, mm,
                                    pack, interpret=interpret)


# per (matrix, shape, cfg) health of the packed kernel: None=untested
# (parity gate runs on first use), True=good, False=fall back to v1
_g2_health: dict[tuple, bool] = {}


def _try_g2(matrix: np.ndarray, xd, b: int, k: int, l: int,
            cfg: dict | None = None):
    """Run the MXU-packed kernel when eligible; returns the output or
    None (ineligible / failed / parity-rejected -> caller falls back)."""
    if os.environ.get("CEPH_TPU_NO_G2") or not _want_pallas():
        return None
    cfg = cfg or _g2_cfg(k)
    g = int(cfg.get("g") or pick_group(k, b))
    if 8 * k * g > 128 or b % g:
        # a tuned g incompatible with THIS batch (odd tail batch)
        # clamps to a compatible group instead of losing the packed
        # kernel entirely
        g = pick_group(k, b)
    if 8 * k * g > 128 or b % g or b < g:
        return None
    mat_bytes = matrix.tobytes()
    r = matrix.shape[0]
    key = (mat_bytes, b, l, tuple(sorted(cfg.items())), g)
    if _g2_health.get(key) is False:
        return None
    try:
        fn = _compiled_batch_gN(8 * r, k, b, l, g, cfg["unpack"],
                                cfg["mm"], cfg["pack"],
                                int(cfg.get("tile", LANE_TILE)))
        if fn is None:
            _g2_health[key] = False
            return None
        w2 = _w_gN_device(mat_bytes, r, k, g, cfg["mm"])
        out = fn(w2, xd)
        if key not in _g2_health:
            # one-time byte-parity gate vs the host oracle on a small
            # slice; a silently-wrong kernel must never serve
            from ..gf import gf_matmul
            ncheck = min(256, l)
            nb = min(g, 2)
            # lint: disable=device-path-host-sync -- one-time parity gate vs the host oracle, bounded slice
            got = np.asarray(out[:nb, :, :ncheck])
            # lint: disable=device-path-host-sync -- one-time parity gate vs the host oracle, bounded slice
            sample = np.asarray(xd[:nb, :, :ncheck])
            for i in range(nb):
                if not np.array_equal(got[i],
                                      gf_matmul(matrix, sample[i])):
                    _g2_health[key] = False
                    return None
            _g2_health[key] = True
        return out
    except Exception:
        _g2_health[key] = False
        return None


@functools.lru_cache(maxsize=512)
def _compiled_batch(r8: int, k: int, b: int, l: int, use_pallas: bool):
    interpret = bool(os.environ.get("CEPH_TPU_PALLAS_INTERPRET"))
    if use_pallas:
        tile = _pick_tile(l)
        if tile:
            return _make_pallas_batch_fn(r8, k, b, l, tile,
                                         interpret=interpret)

    def fn(w, xd):  # whole path under one jit: one dispatch per call
        flat = xd.transpose(1, 0, 2).reshape(k, b * l)
        out = _gf_matmul_math(w, flat)
        return out.reshape(r8 // 8, b, l).transpose(1, 0, 2)
    return jax.jit(fn)


def gf_matmul_batch_device(matrix: np.ndarray, data, *, out_np: bool = False):
    """Batched stripes: (B, k, L) -> (B, r, L), ONE device dispatch.

    Eager op-by-op dispatch is a tunnel round trip per op when the chip
    is remote; everything (including layout changes) lives under one jit.
    The MXU-packed v2 kernel serves when eligible (parity-gated, with
    transparent fallback to the v1 kernel / XLA path).
    """
    b, k, l = data.shape
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    xd = jnp.asarray(data, dtype=jnp.uint8)
    # CSE-minimized XOR schedule (ops/xor_schedule.py) when the
    # cost model picks it for this (matrix, shape) family; parity-
    # gated with transparent fallback to the dense ladder below
    from .xor_schedule import maybe_batch_scheduled
    out = maybe_batch_scheduled(matrix, xd, b, k, l)
    if out is None:
        out = _try_g2(matrix, xd, b, k, l)
    if out is None:
        w = bitmatrix_device(matrix)
        fn = _compiled_batch(w.shape[0], k, b, l, _want_pallas())
        out = fn(w, xd)
    # lint: disable=device-path-host-sync -- the single post-launch materialization (caller opts in via out_np)
    return np.asarray(out) if out_np else out
