"""GF(2^8) matrix multiply as GF(2) bit-matmul on the TPU MXU.

The reference's hot loop is ISA-L's ``ec_encode_data`` -- an (r,k) GF(2^8)
coefficient matrix applied to k data chunks (src/erasure-code/isa/
ErasureCodeIsa.cc:128, called from the OSD write path via ECUtil::encode,
src/osd/ECUtil.cc:134).  On TPU we reformulate: multiplication by a GF(2^8)
constant is linear over GF(2), so the whole stripe encode is

    parity_bits(8r, N) = W(8r, 8k) @ data_bits(8k, N)  (mod 2)

with W the bit-expanded coefficient matrix.  That is a plain int8 matmul --
exactly what the MXU does -- plus cheap VPU unpack/pack around it.  Batching
thousands of stripes makes N huge, which is the regime the systolic array
wants.  Byte-identical to the host/numpy path by construction.

Two executions are provided:
  * XLA path (`_gf_matmul_xla`): portable, used on CPU and as fallback.
  * Pallas path (`_gf_matmul_pallas`): fuses unpack+dot+pack per VMEM tile
    so HBM traffic is just bytes in / parity out.
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp

from ..gf.gf8 import matrix_to_bitmatrix

# column-tile width for the pallas kernel; also the padding bucket for the
# XLA path so recompiles stay bounded
LANE_TILE = 8192


@functools.lru_cache(maxsize=256)
def _bitmatrix_cached(mat_bytes: bytes, r: int, k: int) -> np.ndarray:
    mat = np.frombuffer(mat_bytes, dtype=np.uint8).reshape(r, k)
    return matrix_to_bitmatrix(mat).astype(np.int8)


def bitmatrix_i8(matrix: np.ndarray) -> np.ndarray:
    """(r,k) GF coefficient matrix -> (8r,8k) int8 GF(2) matrix (cached)."""
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    return _bitmatrix_cached(matrix.tobytes(), *matrix.shape)


@functools.lru_cache(maxsize=256)
def _bitmatrix_device(mat_bytes: bytes, r: int, k: int):
    """Device-resident W: one upload per coefficient matrix, ever (the
    per-call jnp.asarray upload is a tunnel round trip otherwise)."""
    import jax
    return jax.device_put(_bitmatrix_cached(mat_bytes, r, k))


def bitmatrix_device(matrix: np.ndarray):
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    return _bitmatrix_device(matrix.tobytes(), *matrix.shape)


def _unpack_bits(data: jnp.ndarray) -> jnp.ndarray:
    """(k, N) uint8 -> (8k, N) int8 bit planes.

    Plane order matches matrix_to_bitmatrix: row 8j+s is bit s of chunk j.
    (bit 0 of an arithmetic right shift by s == bit s, for any sign.)
    """
    k = data.shape[0]
    planes = [((data >> s) & 1) for s in range(8)]
    # interleave to (k, 8, N) then flatten; stacking then reshape keeps the
    # 8j+s row order
    stacked = jnp.stack(planes, axis=1)  # (k, 8, N)
    return stacked.reshape(8 * k, data.shape[1]).astype(jnp.int8)


def _pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(8r, N) int32 bit rows (already mod 2) -> (r, N) uint8."""
    r8, n = bits.shape
    r = r8 // 8
    b = bits.reshape(r, 8, n)
    shifts = jnp.arange(8, dtype=jnp.int32).reshape(1, 8, 1)
    return (b << shifts).sum(axis=1).astype(jnp.uint8)


def _gf_matmul_math(w: jnp.ndarray, data_u8: jnp.ndarray) -> jnp.ndarray:
    bits = _unpack_bits(data_u8.astype(jnp.uint8))
    acc = jax.lax.dot_general(
        w, bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return _pack_bits(acc & 1)


@functools.partial(jax.jit, static_argnames=())
def _gf_matmul_xla(w: jnp.ndarray, data_u8: jnp.ndarray) -> jnp.ndarray:
    return _gf_matmul_math(w, data_u8)


# ---------------------------------------------------------------------------
# Pallas fused kernel
# ---------------------------------------------------------------------------

def _pallas_kernel_body(r8: int, k: int, tile: int):
    def kernel(w_ref, data_ref, out_ref):
        # Mosaic has no i8 shrui; widen to i32 for the bit extraction
        data = data_ref[...].reshape(k, tile).astype(jnp.int32)
        planes = [((data >> s) & 1) for s in range(8)]
        stacked = jnp.stack(planes, axis=1).reshape(8 * k, tile).astype(jnp.int8)
        acc = jax.lax.dot_general(
            w_ref[:], stacked,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        ) & 1
        r = r8 // 8
        b = acc.reshape(r, 8, tile)
        shifts = jnp.arange(8, dtype=jnp.int32).reshape(1, 8, 1)
        out_ref[...] = ((b << shifts).sum(axis=1).astype(jnp.uint8)
                        .reshape(out_ref.shape))
    return kernel


def _make_pallas_fn(r8: int, k: int, n: int, tile: int,
                    interpret: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid = (n // tile,)
    fn = pl.pallas_call(
        _pallas_kernel_body(r8, k, tile),
        out_shape=jax.ShapeDtypeStruct((r8 // 8, n), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((r8, 8 * k), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, tile), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r8 // 8, tile), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )
    return jax.jit(fn)


def _make_pallas_batch_fn(r8: int, k: int, b: int, l: int, tile: int,
                          interpret: bool = False):
    """Batched stripes without the (B,k,L)->(k,B*L) transpose copy: the
    grid walks (stripe, tile) and each step reads a (1,k,tile) block.
    One dispatch, HBM traffic = bytes in + parity out."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid = (b, l // tile)
    fn = pl.pallas_call(
        _pallas_kernel_body(r8, k, tile),
        out_shape=jax.ShapeDtypeStruct((b, r8 // 8, l), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((r8, 8 * k), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k, tile), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, r8 // 8, tile), lambda i, j: (i, 0, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# MXU-packed kernel (v2): the original kernel keeps the systolic array
# ~9% utilized -- the bit-matmul's contraction is only 8k<=64 of the
# MXU's 128 rows, and the int32-widened unpack plus the sublane-strided
# pack burn VPU cycles on relayouts.  This variant:
#   * packs TWO stripes per grid step so the contraction is 16k (=128
#     for the headline k=8): every MXU column-cycle carries two byte
#     columns of work;
#   * unpacks with int8 mask-compares concatenated PLANE-MAJOR (no
#     int32 widening, no stack+reshape relayout) against a column-
#     permuted W;
#   * packs with the same (r,8,T) shift-sum but on the un-interleaved
#     row halves.
# Byte-identical to the host path; selected at runtime with a parity
# self-check and transparent fallback to the v1 kernel.

@functools.lru_cache(maxsize=64)
def _w_g2_planemajor(mat_bytes: bytes, r: int, k: int) -> np.ndarray:
    """(2*8r, 16k) int8: block-diagonal-by-stripe W whose columns match
    the plane-major concat layout of unpacked concat(stripeA, stripeB):
    RHS row s*2k + j  <->  bit s of chunk j (j<k: stripe A, else B)."""
    w = _bitmatrix_cached(mat_bytes, r, k)      # (8r, 8k), col 8j+s
    r8 = 8 * r
    out = np.zeros((2 * r8, 16 * k), np.int8)
    for s in range(8):
        for j in range(2 * k):
            stripe, jj = divmod(j, k)
            out[stripe * r8:(stripe + 1) * r8, s * 2 * k + j] = \
                w[:, 8 * jj + s]
    return out


def _unpack_planes_i8(x):
    """(nk, t) uint8 -> (8*nk, t) int8, plane-major, no i32 widening."""
    ps = [(x & np.uint8(1 << s)).astype(jnp.bool_).astype(jnp.int8)
          for s in range(8)]
    return jnp.concatenate(ps, axis=0)


def _pack_rows(acc, r: int):
    t = acc.shape[-1]
    b = acc.reshape(r, 8, t)
    shifts = jnp.arange(8, dtype=jnp.int32).reshape(1, 8, 1)
    return (b << shifts).sum(axis=1).astype(jnp.uint8)


def _make_pallas_batch_fn_g2(r8: int, k: int, b: int, l: int, tile: int,
                             interpret: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    r = r8 // 8

    def kernel(w_ref, d_ref, o_ref):
        x = jnp.concatenate([d_ref[0], d_ref[1]], axis=0)   # (2k, T)
        bits = _unpack_planes_i8(x)                  # (16k, T)
        acc = jax.lax.dot_general(
            w_ref[:], bits, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32) & 1           # (2*8r, T)
        o_ref[0] = _pack_rows(acc[:r8], r)
        o_ref[1] = _pack_rows(acc[r8:], r)

    fn = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, r, l), jnp.uint8),
        grid=(b // 2, l // tile),
        in_specs=[
            pl.BlockSpec((2 * r8, 16 * k), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, k, tile), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((2, r, tile), lambda i, j: (i, 0, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=512)
def _compiled(r8: int, k: int, n_padded: int, use_pallas: bool):
    if use_pallas:
        interpret = bool(os.environ.get("CEPH_TPU_PALLAS_INTERPRET"))
        return _make_pallas_fn(r8, k, n_padded, min(LANE_TILE, n_padded),
                               interpret=interpret)
    return _gf_matmul_xla


def clear_kernel_cache() -> None:
    for fn in (_compiled, _compiled_batch, _compiled_batch_g2,
               _w_g2_device, _w_g2_planemajor, _bitmatrix_cached,
               _bitmatrix_device):
        getattr(fn, "cache_clear", lambda: None)()
    _g2_health.clear()


def _want_pallas() -> bool:
    if os.environ.get("CEPH_TPU_NO_PALLAS"):
        return False
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


def _pad_n(n: int) -> int:
    # bucket N so the jit cache stays small: pad to LANE_TILE multiples,
    # with a small-size bucket ladder below one tile
    if n >= LANE_TILE:
        return ((n + LANE_TILE - 1) // LANE_TILE) * LANE_TILE
    b = 512
    while b < n:
        b *= 2
    return b


def gf_matmul_device(matrix: np.ndarray, data, *, out_np: bool = True):
    """(r,k) GF(2^8) coeff matrix x (k,N) bytes -> (r,N) bytes, on device.

    ``data`` may be a numpy array or a device array; the result is returned
    as numpy when out_np (plugin path) or left on device (bench path).
    """
    w = bitmatrix_device(matrix)
    r8, k8 = w.shape
    k = k8 // 8
    n = data.shape[1]
    n_pad = _pad_n(n)
    use_pallas = _want_pallas() and n_pad % 128 == 0
    fn = _compiled(r8, k, n_pad, use_pallas)
    xd = jnp.asarray(data, dtype=jnp.uint8)
    if n_pad != n:
        xd = jnp.pad(xd, ((0, 0), (0, n_pad - n)))
    out = fn(w, xd)
    if n_pad != n:
        out = out[:, :n]
    return np.asarray(out) if out_np else out


@functools.lru_cache(maxsize=256)
def _w_g2_device(mat_bytes: bytes, r: int, k: int):
    return jax.device_put(_w_g2_planemajor(mat_bytes, r, k))


def _pick_tile(l: int) -> int:
    """Lane-tile ladder shared by the batch kernels; 0 = ineligible."""
    if l % LANE_TILE == 0:
        return LANE_TILE
    if l <= LANE_TILE and l % 128 == 0:
        return l
    return 0


@functools.lru_cache(maxsize=512)
def _compiled_batch_g2(r8: int, k: int, b: int, l: int):
    interpret = bool(os.environ.get("CEPH_TPU_PALLAS_INTERPRET"))
    tile = _pick_tile(l)
    if not tile:
        return None
    return _make_pallas_batch_fn_g2(r8, k, b, l, tile,
                                    interpret=interpret)


# per (matrix, shape) health of the v2 kernel: None=untested (parity
# gate runs on first use), True=good, False=fall back to v1
_g2_health: dict[tuple, bool] = {}


def _try_g2(matrix: np.ndarray, xd, b: int, k: int, l: int):
    """Run the MXU-packed kernel when eligible; returns the output or
    None (ineligible / failed / parity-rejected -> caller falls back)."""
    if os.environ.get("CEPH_TPU_NO_G2") or not _want_pallas():
        return None
    if k > 8 or k < 1 or b % 2 or b < 2:
        return None                  # contraction 16k must fit 128 rows
    mat_bytes = matrix.tobytes()
    r = matrix.shape[0]
    key = (mat_bytes, b, l)
    if _g2_health.get(key) is False:
        return None
    try:
        fn = _compiled_batch_g2(8 * r, k, b, l)
        if fn is None:
            _g2_health[key] = False
            return None
        w2 = _w_g2_device(mat_bytes, r, k)
        out = fn(w2, xd)
        if key not in _g2_health:
            # one-time byte-parity gate vs the host oracle on a small
            # slice; a silently-wrong kernel must never serve
            from ..gf import gf_matmul
            ncheck = min(256, l)
            got = np.asarray(out[:2, :, :ncheck])
            sample = np.asarray(xd[:2, :, :ncheck])
            for i in range(2):
                if not np.array_equal(got[i],
                                      gf_matmul(matrix, sample[i])):
                    _g2_health[key] = False
                    return None
            _g2_health[key] = True
        return out
    except Exception:
        _g2_health[key] = False
        return None


@functools.lru_cache(maxsize=512)
def _compiled_batch(r8: int, k: int, b: int, l: int, use_pallas: bool):
    interpret = bool(os.environ.get("CEPH_TPU_PALLAS_INTERPRET"))
    if use_pallas:
        tile = _pick_tile(l)
        if tile:
            return _make_pallas_batch_fn(r8, k, b, l, tile,
                                         interpret=interpret)

    def fn(w, xd):  # whole path under one jit: one dispatch per call
        flat = xd.transpose(1, 0, 2).reshape(k, b * l)
        out = _gf_matmul_math(w, flat)
        return out.reshape(r8 // 8, b, l).transpose(1, 0, 2)
    return jax.jit(fn)


def gf_matmul_batch_device(matrix: np.ndarray, data, *, out_np: bool = False):
    """Batched stripes: (B, k, L) -> (B, r, L), ONE device dispatch.

    Eager op-by-op dispatch is a tunnel round trip per op when the chip
    is remote; everything (including layout changes) lives under one jit.
    The MXU-packed v2 kernel serves when eligible (parity-gated, with
    transparent fallback to the v1 kernel / XLA path).
    """
    b, k, l = data.shape
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    xd = jnp.asarray(data, dtype=jnp.uint8)
    out = _try_g2(matrix, xd, b, k, l)
    if out is None:
        w = bitmatrix_device(matrix)
        fn = _compiled_batch(w.shape[0], k, b, l, _want_pallas())
        out = fn(w, xd)
    return np.asarray(out) if out_np else out
