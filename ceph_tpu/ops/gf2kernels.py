"""GF(2^8) matrix multiply as GF(2) bit-matmul on the TPU MXU.

The reference's hot loop is ISA-L's ``ec_encode_data`` -- an (r,k) GF(2^8)
coefficient matrix applied to k data chunks (src/erasure-code/isa/
ErasureCodeIsa.cc:128, called from the OSD write path via ECUtil::encode,
src/osd/ECUtil.cc:134).  On TPU we reformulate: multiplication by a GF(2^8)
constant is linear over GF(2), so the whole stripe encode is

    parity_bits(8r, N) = W(8r, 8k) @ data_bits(8k, N)  (mod 2)

with W the bit-expanded coefficient matrix.  That is a plain int8 matmul --
exactly what the MXU does -- plus cheap VPU unpack/pack around it.  Batching
thousands of stripes makes N huge, which is the regime the systolic array
wants.  Byte-identical to the host/numpy path by construction.

Two executions are provided:
  * XLA path (`_gf_matmul_xla`): portable, used on CPU and as fallback.
  * Pallas path (`_gf_matmul_pallas`): fuses unpack+dot+pack per VMEM tile
    so HBM traffic is just bytes in / parity out.
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp

from ..gf.gf8 import matrix_to_bitmatrix

# column-tile width for the pallas kernel; also the padding bucket for the
# XLA path so recompiles stay bounded
LANE_TILE = 8192


@functools.lru_cache(maxsize=256)
def _bitmatrix_cached(mat_bytes: bytes, r: int, k: int) -> np.ndarray:
    mat = np.frombuffer(mat_bytes, dtype=np.uint8).reshape(r, k)
    return matrix_to_bitmatrix(mat).astype(np.int8)


def bitmatrix_i8(matrix: np.ndarray) -> np.ndarray:
    """(r,k) GF coefficient matrix -> (8r,8k) int8 GF(2) matrix (cached)."""
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    return _bitmatrix_cached(matrix.tobytes(), *matrix.shape)


@functools.lru_cache(maxsize=256)
def _bitmatrix_device(mat_bytes: bytes, r: int, k: int):
    """Device-resident W: one upload per coefficient matrix, ever (the
    per-call jnp.asarray upload is a tunnel round trip otherwise)."""
    import jax
    return jax.device_put(_bitmatrix_cached(mat_bytes, r, k))


def bitmatrix_device(matrix: np.ndarray):
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    return _bitmatrix_device(matrix.tobytes(), *matrix.shape)


def _unpack_bits(data: jnp.ndarray) -> jnp.ndarray:
    """(k, N) uint8 -> (8k, N) int8 bit planes.

    Plane order matches matrix_to_bitmatrix: row 8j+s is bit s of chunk j.
    (bit 0 of an arithmetic right shift by s == bit s, for any sign.)
    """
    k = data.shape[0]
    planes = [((data >> s) & 1) for s in range(8)]
    # interleave to (k, 8, N) then flatten; stacking then reshape keeps the
    # 8j+s row order
    stacked = jnp.stack(planes, axis=1)  # (k, 8, N)
    return stacked.reshape(8 * k, data.shape[1]).astype(jnp.int8)


def _pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(8r, N) int32 bit rows (already mod 2) -> (r, N) uint8."""
    r8, n = bits.shape
    r = r8 // 8
    b = bits.reshape(r, 8, n)
    shifts = jnp.arange(8, dtype=jnp.int32).reshape(1, 8, 1)
    return (b << shifts).sum(axis=1).astype(jnp.uint8)


def _gf_matmul_math(w: jnp.ndarray, data_u8: jnp.ndarray) -> jnp.ndarray:
    bits = _unpack_bits(data_u8.astype(jnp.uint8))
    acc = jax.lax.dot_general(
        w, bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return _pack_bits(acc & 1)


@functools.partial(jax.jit, static_argnames=())
def _gf_matmul_xla(w: jnp.ndarray, data_u8: jnp.ndarray) -> jnp.ndarray:
    return _gf_matmul_math(w, data_u8)


# ---------------------------------------------------------------------------
# Pallas fused kernel
# ---------------------------------------------------------------------------

def _pallas_kernel_body(r8: int, k: int, tile: int):
    def kernel(w_ref, data_ref, out_ref):
        # Mosaic has no i8 shrui; widen to i32 for the bit extraction
        data = data_ref[...].reshape(k, tile).astype(jnp.int32)
        planes = [((data >> s) & 1) for s in range(8)]
        stacked = jnp.stack(planes, axis=1).reshape(8 * k, tile).astype(jnp.int8)
        acc = jax.lax.dot_general(
            w_ref[:], stacked,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        ) & 1
        r = r8 // 8
        b = acc.reshape(r, 8, tile)
        shifts = jnp.arange(8, dtype=jnp.int32).reshape(1, 8, 1)
        out_ref[...] = ((b << shifts).sum(axis=1).astype(jnp.uint8)
                        .reshape(out_ref.shape))
    return kernel


def _make_pallas_fn(r8: int, k: int, n: int, tile: int,
                    interpret: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid = (n // tile,)
    fn = pl.pallas_call(
        _pallas_kernel_body(r8, k, tile),
        out_shape=jax.ShapeDtypeStruct((r8 // 8, n), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((r8, 8 * k), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, tile), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r8 // 8, tile), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )
    return jax.jit(fn)


def _make_pallas_batch_fn(r8: int, k: int, b: int, l: int, tile: int,
                          interpret: bool = False):
    """Batched stripes without the (B,k,L)->(k,B*L) transpose copy: the
    grid walks (stripe, tile) and each step reads a (1,k,tile) block.
    One dispatch, HBM traffic = bytes in + parity out."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid = (b, l // tile)
    fn = pl.pallas_call(
        _pallas_kernel_body(r8, k, tile),
        out_shape=jax.ShapeDtypeStruct((b, r8 // 8, l), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((r8, 8 * k), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k, tile), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, r8 // 8, tile), lambda i, j: (i, 0, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=512)
def _compiled(r8: int, k: int, n_padded: int, use_pallas: bool):
    if use_pallas:
        interpret = bool(os.environ.get("CEPH_TPU_PALLAS_INTERPRET"))
        return _make_pallas_fn(r8, k, n_padded, min(LANE_TILE, n_padded),
                               interpret=interpret)
    return _gf_matmul_xla


def clear_kernel_cache() -> None:
    _compiled.cache_clear()
    _compiled_batch.cache_clear()
    _bitmatrix_cached.cache_clear()
    _bitmatrix_device.cache_clear()


def _want_pallas() -> bool:
    if os.environ.get("CEPH_TPU_NO_PALLAS"):
        return False
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


def _pad_n(n: int) -> int:
    # bucket N so the jit cache stays small: pad to LANE_TILE multiples,
    # with a small-size bucket ladder below one tile
    if n >= LANE_TILE:
        return ((n + LANE_TILE - 1) // LANE_TILE) * LANE_TILE
    b = 512
    while b < n:
        b *= 2
    return b


def gf_matmul_device(matrix: np.ndarray, data, *, out_np: bool = True):
    """(r,k) GF(2^8) coeff matrix x (k,N) bytes -> (r,N) bytes, on device.

    ``data`` may be a numpy array or a device array; the result is returned
    as numpy when out_np (plugin path) or left on device (bench path).
    """
    w = bitmatrix_device(matrix)
    r8, k8 = w.shape
    k = k8 // 8
    n = data.shape[1]
    n_pad = _pad_n(n)
    use_pallas = _want_pallas() and n_pad % 128 == 0
    fn = _compiled(r8, k, n_pad, use_pallas)
    xd = jnp.asarray(data, dtype=jnp.uint8)
    if n_pad != n:
        xd = jnp.pad(xd, ((0, 0), (0, n_pad - n)))
    out = fn(w, xd)
    if n_pad != n:
        out = out[:, :n]
    return np.asarray(out) if out_np else out


@functools.lru_cache(maxsize=512)
def _compiled_batch(r8: int, k: int, b: int, l: int, use_pallas: bool):
    interpret = bool(os.environ.get("CEPH_TPU_PALLAS_INTERPRET"))
    if use_pallas:
        if l % LANE_TILE == 0:
            tile = LANE_TILE
        elif l <= LANE_TILE and l % 128 == 0:
            tile = l
        else:
            tile = 0
        if tile:
            return _make_pallas_batch_fn(r8, k, b, l, tile,
                                         interpret=interpret)

    def fn(w, xd):  # whole path under one jit: one dispatch per call
        flat = xd.transpose(1, 0, 2).reshape(k, b * l)
        out = _gf_matmul_math(w, flat)
        return out.reshape(r8 // 8, b, l).transpose(1, 0, 2)
    return jax.jit(fn)


def gf_matmul_batch_device(matrix: np.ndarray, data, *, out_np: bool = False):
    """Batched stripes: (B, k, L) -> (B, r, L), ONE device dispatch.

    Eager op-by-op dispatch is a tunnel round trip per op when the chip
    is remote; everything (including layout changes) lives under one jit.
    """
    b, k, l = data.shape
    w = bitmatrix_device(matrix)
    xd = jnp.asarray(data, dtype=jnp.uint8)
    fn = _compiled_batch(w.shape[0], k, b, l, _want_pallas())
    out = fn(w, xd)
    return np.asarray(out) if out_np else out
