"""Small AST helpers shared by the checkers.

Everything here works on structure, never on raw source text: a rule
that grepped for ``"CRUSH_ITEM_NONE"`` would fire on its own
implementation (and on docstrings), while an ``ast.Name`` test cannot.
"""

from __future__ import annotations

import ast
from typing import Iterator

PARENT_ATTR = "_lint_parent"


def attach_parents(tree: ast.AST) -> ast.AST:
    """Stamp every node with a ``_lint_parent`` backlink (idempotent)."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            setattr(child, PARENT_ATTR, node)
    return tree


def parent(node: ast.AST) -> ast.AST | None:
    return getattr(node, PARENT_ATTR, None)


def ancestors(node: ast.AST) -> Iterator[ast.AST]:
    cur = parent(node)
    while cur is not None:
        yield cur
        cur = parent(cur)


def enclosing_function(node: ast.AST) -> ast.AST | None:
    """Innermost (Async)FunctionDef containing `node`, if any."""
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def name_leaf(node: ast.AST) -> str | None:
    """The final identifier of a Name or Attribute (``a.b.c`` -> c)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def int_value(node: ast.AST) -> int | None:
    """Evaluate an int literal, including unary minus (``-1``)."""
    if isinstance(node, ast.Constant) and type(node.value) is int:
        return node.value
    if (isinstance(node, ast.UnaryOp)
            and isinstance(node.op, ast.USub)):
        inner = int_value(node.operand)
        return -inner if inner is not None else None
    return None


def names_in(node: ast.AST) -> set[str]:
    """All bare Name identifiers inside a subtree."""
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def references_name(tree: ast.AST, ident: str) -> bool:
    """True if `ident` appears as a Name or Attribute leaf (not as a
    string constant) anywhere in the subtree."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and node.id == ident:
            return True
        if isinstance(node, ast.Attribute) and node.attr == ident:
            return True
    return False


def imports_module(tree: ast.AST, *suffixes: str) -> bool:
    """True if the module imports any dotted path ending in one of
    `suffixes` (handles absolute and relative imports)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            names = [a.name for a in node.names]
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            names = [mod] + [f"{mod}.{a.name}" if mod else a.name
                             for a in node.names]
        else:
            continue
        for name in names:
            for suf in suffixes:
                if name == suf or name.endswith("." + suf):
                    return True
    return False


def decorator_names(fn: ast.AST) -> list[str]:
    """Dotted names of each decorator; for ``partial(f, ...)`` style
    decorators the *call target* name is returned (``partial``)."""
    out = []
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted(target)
        if name:
            out.append(name)
    return out
