"""Pluggable checker registry.

A rule is a ``Checker`` subclass registered with ``@register``; the
runner in ``core`` parses each file once and hands the shared
``Module`` objects to every registered checker -- ``check()`` per
module in scope, then ``finalize()`` once with the whole project for
cross-module passes (e.g. perf-counter coherence).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Iterable

if TYPE_CHECKING:                         # pragma: no cover
    from .callgraph import CallGraph
    from .core import Finding, Module, Project


class Checker:
    """Base class for one rule.

    ``name`` is the rule id used in findings, ``# lint: disable=`` and
    ``--rules``; ``description`` is one line for ``--list-rules``.
    """

    name: str = ""
    description: str = ""

    def scope(self, module: "Module") -> bool:
        """Whether `module` is subject to this rule (default: all)."""
        return True

    def check(self, module: "Module") -> Iterable["Finding"]:
        """Per-module pass over one parsed file."""
        return ()

    def finalize(self, project: "Project") -> Iterable["Finding"]:
        """Cross-module pass, called once after every check()."""
        return ()


class ProjectChecker(Checker):
    """Base class for whole-program rules.

    Besides the per-module hooks, a ProjectChecker receives the
    resolved ``CallGraph`` (symbol table, call/reference edges with
    fan-out, lock regions, reachability queries) once per run.  The
    graph is built lazily: it only costs anything when at least one
    registered ProjectChecker is selected.
    """

    def check_project(self,
                      graph: "CallGraph") -> Iterable["Finding"]:
        """Whole-program pass over the resolved call graph."""
        return ()


_REGISTRY: dict[str, Checker] = {}


def register(cls: type[Checker]) -> type[Checker]:
    """Class decorator: instantiate and register a checker by name."""
    if not cls.name:
        raise ValueError(f"checker {cls.__name__} has no name")
    if cls.name in _REGISTRY:
        raise ValueError(f"duplicate checker name {cls.name!r}")
    _REGISTRY[cls.name] = cls()
    return cls


def get_checkers(names: Iterable[str] | None = None) -> list[Checker]:
    """All registered checkers, or the named subset (order stable)."""
    if names is None:
        return [_REGISTRY[k] for k in sorted(_REGISTRY)]
    out = []
    for n in names:
        if n not in _REGISTRY:
            known = ", ".join(sorted(_REGISTRY))
            raise KeyError(f"unknown rule {n!r} (known: {known})")
        out.append(_REGISTRY[n])
    return out
