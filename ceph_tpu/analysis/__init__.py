"""Project-native static analysis.

Every major bug class fixed in PRs 1-3 was a mechanically detectable
invariant violation: raw ``CRUSH_ITEM_NONE`` leaking into ``o >= 0``
role checks, ``jax_enable_x64`` flipped at import time, host syncs and
recompiles inside jitted hot paths.  This package encodes those
invariants as AST checkers so tooling -- not reviewer vigilance --
enforces them, the way program-level checks underpin correctness in
optimized EC pipelines.

Layout:

* ``core``      -- file collection, single-parse module model, inline
                   ``# lint: disable=<rule>`` suppressions, baseline
                   files, and the run orchestration.
* ``registry``  -- the pluggable checker registry (``@register``).
* ``checkers``  -- the project rules; importing the subpackage
                   registers them.

CLI front end: ``tools/lint.py`` (see README "Static analysis").
"""

from .core import (          # noqa: F401
    Finding,
    Module,
    Project,
    baseline_key,
    changed_closure,
    collect_files,
    filter_suppressed,
    load_baseline,
    run,
    write_baseline,
)
from .registry import (      # noqa: F401
    Checker,
    ProjectChecker,
    get_checkers,
    register,
)

# Importing the subpackage registers every built-in rule.
from . import checkers       # noqa: F401,E402
