"""denc-symmetry: encoders and decoders must walk the same fields.

A denc envelope is only forward-compatible if the decoder consumes
exactly the byte sequence the encoder produced -- one transposed
``u32``/``u64`` or a forgotten ``optional`` and every later field
parses as garbage *silently* (fixed-width reads do not fail, they
misalign).  The committed corpus catches drift on types it covers;
this rule catches it at the source level for every pair, including
ones with no corpus entry yet.

For each encode/decode pair -- ``denc``/``dedenc`` methods of one
class, ``encode``/``decode`` methods of one class, or module-level
``_enc_X``/``_dec_X`` functions -- the rule extracts the *field
sequence*: the ordered denc primitive calls on the encoder/decoder
receiver, flattened across control flow (a version-gated field reads
in the same position it was written, so flat order is the invariant).
Structured ops normalize across the calling-convention asymmetry
(``enc.list(items, fn)`` vs ``dec.list(fn)``), element codecs recurse
through lambdas, ``Encoder.u32``-style method refs, and local helper
defs, and a call that passes the receiver onward (``sub.denc(enc)`` /
``Sub.dedenc(dec)``) counts as one nested-codec step.  Pairs where
either side delegates entirely (no receiver ops) are skipped -- there
is no sequence to compare.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .. import astutil
from ..callgraph import CallGraph, _call_base
from ..core import Finding
from ..registry import ProjectChecker, register

# simple ops: one primitive, same name both sides
_SIMPLE = {"u8", "u16", "u32", "u64", "i64", "f64", "boolean", "blob",
           "string", "value", "start", "finish"}
# structured ops: name -> number of element-codec args (taken from the
# END of the arg list -- the encoder passes the data first)
_STRUCTURED = {"optional": 1, "list": 1, "map": 2}

_WILD = ("?",)


def _leaf(node: ast.AST) -> str | None:
    return astutil.name_leaf(node)


def _const_keys_written(root: ast.AST) -> set[str]:
    """Constant string keys a frame packer produces: dict-literal
    keys plus constant subscript stores."""
    out: set[str] = set()
    for node in ast.walk(root):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                ks = astutil.const_str(k) if k is not None else None
                if ks is not None:
                    out.add(ks)
        elif (isinstance(node, ast.Subscript)
              and isinstance(node.ctx, ast.Store)):
            ks = astutil.const_str(node.slice)
            if ks is not None:
                out.add(ks)
    return out


def _const_keys_read(root: ast.AST) -> set[str]:
    """Constant string keys a frame unpacker consumes: constant
    subscript loads plus ``.get("k", ...)`` calls."""
    out: set[str] = set()
    for node in ast.walk(root):
        if (isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)):
            ks = astutil.const_str(node.slice)
            if ks is not None:
                out.add(ks)
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr == "get" and node.args):
            ks = astutil.const_str(node.args[0])
            if ks is not None:
                out.add(ks)
    return out


class _SeqExtractor:
    """Ordered denc-primitive sequence of one function body."""

    def __init__(self, local_defs: dict[str, ast.AST],
                 depth: int = 0) -> None:
        self.local_defs = local_defs
        self.depth = depth

    def extract(self, body, receiver: str) -> list[tuple]:
        out: list[tuple] = []
        for stmt in body:
            self._emit(stmt, receiver, out)
        return out

    def _emit(self, node, recv: str, out: list[tuple]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return          # element codecs are entered via fn args
        if isinstance(node, ast.Call):
            self._emit_call(node, recv, out)
            return
        for child in ast.iter_child_nodes(node):
            self._emit(child, recv, out)

    def _emit_call(self, node: ast.Call, recv, out) -> None:
        # inner receiver chains first: enc.u32(a).u64(b) emits u32
        # while walking the .u64 call's func
        self._emit(node.func, recv, out)
        fname = _leaf(node.func)
        on_recv = (isinstance(node.func, ast.Attribute)
                   and _call_base(node.func) == recv)
        if on_recv and fname in _STRUCTURED:
            n = _STRUCTURED[fname]
            data_args = node.args[:-n] if len(node.args) >= n else []
            fn_args = node.args[-n:] if len(node.args) >= n else []
            for a in data_args:
                self._emit(a, recv, out)
            sigs = tuple(self._fn_sig(a) for a in fn_args)
            out.append((fname,) + sigs)
            return
        for a in node.args:
            self._emit(a, recv, out)
        for kw in node.keywords:
            self._emit(kw.value, recv, out)
        if on_recv and fname in _SIMPLE:
            out.append((fname,))
        elif not on_recv and self._passes_receiver(node, recv):
            out.append(("sub",))

    @staticmethod
    def _passes_receiver(node: ast.Call, recv: str) -> bool:
        for a in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(a, ast.Name) and a.id == recv:
                return True
        return False

    def _fn_sig(self, fn: ast.AST):
        """Normalize an element-codec argument to its own sequence."""
        if self.depth > 6:
            return _WILD
        sub = _SeqExtractor(self.local_defs, self.depth + 1)
        if isinstance(fn, ast.Lambda):
            params = [a.arg for a in fn.args.args]
            if not params:
                return _WILD
            return tuple(sub.extract([fn.body], params[0])) or _WILD
        if isinstance(fn, ast.Attribute):        # Encoder.u32 ref
            return ((fn.attr,),) if fn.attr in _SIMPLE else _WILD
        if isinstance(fn, ast.Name):
            target = self.local_defs.get(fn.id)
            if isinstance(target, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                params = [a.arg for a in target.args.args
                          if a.arg not in ("self", "cls")]
                if not params:
                    return _WILD
                return tuple(sub.extract(target.body,
                                         params[0])) or _WILD
        return _WILD


def _ops_match(a: tuple, b: tuple) -> bool:
    if a == _WILD or b == _WILD:
        return True
    if a[0] != b[0] or len(a) != len(b):
        return False
    for sa, sb in zip(a[1:], b[1:]):
        if sa == _WILD or sb == _WILD:
            continue
        if len(sa) != len(sb):
            return False
        if not all(_ops_match(x, y) for x, y in zip(sa, sb)):
            return False
    return True


def _render(op: tuple) -> str:
    if len(op) == 1:
        return op[0]
    inner = ",".join("/".join(_render(x) for x in sig)
                     if sig != _WILD else "?" for sig in op[1:])
    return f"{op[0]}[{inner}]"


@register
class DencSymmetry(ProjectChecker):
    name = "denc-symmetry"
    description = ("encode/dump field sequence must match what the "
                   "paired decode consumes (denc/dedenc, "
                   "encode/decode, _enc_*/_dec_* pairs)")

    def check_project(self, graph: CallGraph) -> Iterable[Finding]:
        for path in sorted(graph.symbols):
            syms = graph.symbols[path]
            if astutil.imports_module(syms.module.tree, "denc"):
                yield from self._check_module(syms)
            # pack_X/unpack_X frame pairs and the WIRE_CODECS table
            # are wire vocabulary whether or not the module drives a
            # denc Encoder itself (messenger.py does not import denc)
            yield from self._check_pack_pairs(syms)
            yield from self._check_wire_codecs(syms)

    def _check_module(self, syms) -> Iterable[Finding]:
        pairs: list[tuple] = []
        for ci in syms.classes.values():
            for enc_name, dec_name in (("denc", "dedenc"),
                                       ("encode", "decode"),
                                       ("dump", "decode")):
                if enc_name in ci.methods and dec_name in ci.methods:
                    pairs.append((f"{ci.name}.{enc_name}",
                                  ci.methods[enc_name],
                                  f"{ci.name}.{dec_name}",
                                  ci.methods[dec_name]))
        for name, fi in syms.top_funcs.items():
            for pre, dpre in (("_enc_", "_dec_"), ("enc_", "dec_")):
                if name.startswith(pre):
                    dec = syms.top_funcs.get(dpre + name[len(pre):])
                    if dec is not None:
                        pairs.append((name, fi, dec.name, dec))
                    break
        for enc_label, enc_fi, dec_label, dec_fi in pairs:
            enc_seq = self._sequence(enc_fi)
            dec_seq = self._sequence(dec_fi)
            if not enc_seq or not dec_seq:
                continue        # full delegation: nothing to compare
            yield from self._compare(enc_label, enc_seq, dec_label,
                                     dec_fi, dec_seq)

    def _check_pack_pairs(self, syms) -> Iterable[Finding]:
        """``pack_X``/``unpack_X`` frame pairs (the SubOpPipe batch
        vocabulary): every constant dict key the unpacker reads must
        be one the packer writes.  Write-only keys are fine -- length
        metadata can serve other consumers -- but a read of a key the
        encoder never produces is a silent ``None``/KeyError on every
        frame."""
        for name, fi in syms.top_funcs.items():
            if not name.startswith("pack_"):
                continue
            unpack = syms.top_funcs.get("un" + name)
            if unpack is None:
                continue
            written = _const_keys_written(fi.node)
            written |= _const_keys_written(unpack.node)
            missing = sorted(_const_keys_read(unpack.node) - written)
            if missing:
                yield Finding(
                    unpack.path, unpack.lineno, self.name,
                    f"{unpack.name} reads key(s) "
                    f"{', '.join(repr(k) for k in missing)} that "
                    f"{name} never writes -- the frame vocabulary "
                    f"is asymmetric; every unpack of a real frame "
                    f"sees the key missing")

    def _check_wire_codecs(self, syms) -> Iterable[Finding]:
        """Each ``WIRE_CODECS`` entry must map a wire type to a
        conventionally-paired codec whose name matches the type --
        mapping ``"rep_op_reply"`` to ``_enc_rep_op`` by copy-paste
        would silently encode the wrong fixed layout."""
        for stmt in syms.module.tree.body:
            if not (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "WIRE_CODECS"
                    and isinstance(stmt.value, ast.Dict)):
                continue
            for k, v in zip(stmt.value.keys, stmt.value.values):
                ks = astutil.const_str(k) if k is not None else None
                if ks is None or not isinstance(v, ast.Tuple) \
                        or len(v.elts) != 2:
                    continue
                enc = _leaf(v.elts[0]) or ""
                dec = _leaf(v.elts[1]) or ""
                es = enc[len("_enc_"):] if enc.startswith("_enc_") \
                    else None
                ds = dec[len("_dec_"):] if dec.startswith("_dec_") \
                    else None
                if es is None or ds is None or es != ds:
                    yield Finding(
                        syms.module.path, k.lineno, self.name,
                        f"WIRE_CODECS['{ks}'] pairs '{enc}' with "
                        f"'{dec}' -- not a matched _enc_X/_dec_X "
                        f"pair; the decoder cannot be assumed to "
                        f"consume what the encoder wrote")
                elif es != ks:
                    yield Finding(
                        syms.module.path, k.lineno, self.name,
                        f"WIRE_CODECS['{ks}'] maps to the "
                        f"'{es}' codec pair -- a type borrowing "
                        f"another type's layout is a copy-paste "
                        f"hazard; give it its own pair or justify "
                        f"the shared layout")

    def _sequence(self, fi) -> list[tuple]:
        recv = self._receiver(fi)
        if recv is None:
            return []
        local_defs = {
            child.name: child
            for child in ast.walk(fi.node)
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef))
            and child is not fi.node}
        return _SeqExtractor(local_defs).extract(fi.node.body, recv)

    @staticmethod
    def _receiver(fi) -> str | None:
        """The Encoder/Decoder variable a pair member drives: the
        first non-self/cls parameter for denc-style signatures, else
        the single local assigned ``Encoder()``/``Decoder(...)``."""
        params = [a.arg for a in fi.node.args.args
                  if a.arg not in ("self", "cls")]
        if params and (fi.name in ("denc", "dedenc")
                       or fi.name.startswith(("_enc_", "_dec_",
                                              "enc_", "dec_"))):
            return params[0]
        assigned = []
        for node in ast.walk(fi.node):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and _leaf(node.value.func) in ("Encoder",
                                                   "Decoder")
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                assigned.append(node.targets[0].id)
        return assigned[0] if len(assigned) == 1 else None

    def _compare(self, enc_label, enc_seq, dec_label, dec_fi,
                 dec_seq) -> Iterable[Finding]:
        n = min(len(enc_seq), len(dec_seq))
        for i in range(n):
            if not _ops_match(enc_seq[i], dec_seq[i]):
                yield Finding(
                    dec_fi.path, dec_fi.lineno, self.name,
                    f"{dec_label} diverges from {enc_label} at field "
                    f"{i + 1}: encoder writes "
                    f"'{_render(enc_seq[i])}', decoder reads "
                    f"'{_render(dec_seq[i])}' -- every later field "
                    f"misparses silently")
                return
        if len(enc_seq) != len(dec_seq):
            yield Finding(
                dec_fi.path, dec_fi.lineno, self.name,
                f"{dec_label} consumes {len(dec_seq)} field(s) but "
                f"{enc_label} writes {len(enc_seq)} -- the tail "
                f"{'is never read' if len(enc_seq) > len(dec_seq) else 'reads past the encoded payload'}")
