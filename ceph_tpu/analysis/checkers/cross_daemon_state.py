"""cross-daemon-state: the process-seam census and boundary rule.

The multiprocess-swarm refactor (ROADMAP) moves each daemon onto its
own worker process with the messenger as the only seam.  Everything
that works today *because* one asyncio loop serializes one address
space breaks silently there, in two shapes this rule makes visible:

* **shared mutable module/class state** -- compile caches, perf
  singletons, tuned tables.  These are censused (``--seam-report``)
  and classified: a fork-safe *recomputable cache* (each process
  rebuilds its own copy at worst-case a recompile) vs a *per-process
  counter* (aggregation must move to the seam) vs *correctness state*
  (two processes diverge silently).  The census is an artifact, not a
  finding -- having a cache is fine; the swarm PR needs the worklist.

* **daemon-boundary reaches** -- code outside a daemon's own
  subsystem reading its private attributes, grabbing a live subsystem
  object (``osd.pgs``, ``mon.osdmap``, a store, a messenger), or
  mutating its attributes.  In-process these are harmless shortcuts;
  across processes they are dangling references.  These ARE findings:
  route them through the Messenger or a public accessor, or justify
  the in-process shortcut with a ``# lint: disable`` comment.

Receiver typing is by the repo's naming conventions (a variable named
``osd``/``mon``/``pg``, a ``.mon`` attribute chain, iteration over an
``.osds``/``.pgs`` container) -- the same best-effort contract as the
call graph.  A reach is *internal* (not a finding) when it happens in
a method of the daemon class itself or in the daemon's home subsystem
directory (``osd/`` for OSD and PG, ``mon/`` for Monitor): peering
code in ``osd/pg.py`` touching ``self.osd.osdmap`` rides the same
process as the OSD by construction, the chaos driver does not.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .. import astutil
from ..callgraph import CallGraph, own_nodes
from ..core import Finding
from ..registry import ProjectChecker, register

DAEMON_CLASSES = ("OSD", "Monitor", "PG")

# the daemon's home subsystem dir: reaches from inside it share the
# daemon's process by construction in the swarm plan (one worker owns
# the whole subsystem), reaches from anywhere else cross the seam
DAEMON_HOME = {"OSD": "osd/", "PG": "osd/", "Monitor": "mon/"}

# live subsystem objects: handing one across the seam hands out state
# that will be another process's memory in the swarm
SUBSYSTEM_ATTRS = {"osdmap", "msgr", "pgs", "store", "conns",
                   "subop_pipe", "pg_log"}

# conventionally daemon-typed receiver names / attribute leaves
NAME_TYPES = {"osd": "OSD", "victim": "OSD", "mon": "Monitor",
              "monitor": "Monitor", "pg": "PG"}
# containers whose elements are daemon-typed (iteration / subscript)
CONTAINER_ATTRS = {"osds": "OSD", "pgs": "PG", "mons": "Monitor"}

# census: value expressions that denote shared mutable state
_MUTABLE_LITERALS = (ast.Dict, ast.List, ast.Set, ast.DictComp,
                     ast.ListComp, ast.SetComp)
_MUTABLE_BUILTINS = {"dict", "list", "set", "bytearray", "deque",
                     "defaultdict", "OrderedDict", "Counter"}
_IMMUTABLE_CALLS = {"int", "float", "str", "bool", "bytes", "tuple",
                    "frozenset", "len", "calcsize", "namedtuple",
                    "TypeVar", "getenv", "compile", "frozen"}

_CACHE_HINTS = ("cache", "memo", "table", "compiled", "tuned", "plan",
                "sched", "shared", "registry", "plugin")
_COUNTER_HINTS = ("perf", "stats", "counter", "metric", "hist")
_PRIMITIVE_HINTS = ("lock", "sem", "cond", "event")

# container mutator methods: calling one on a module-global is the
# mutation evidence that separates shared state from a constant table
_MUTATORS = {"append", "add", "update", "setdefault", "pop",
             "popitem", "clear", "extend", "remove", "discard",
             "insert", "appendleft"}


def _mutated_names(graph: CallGraph) -> set[str]:
    """Leaf names with project-wide mutation evidence: a subscript
    store/delete, an augmented assignment, or a mutator-method call.
    A module-level dict nobody ever writes is a constant lookup
    table, not shared state."""
    out: set[str] = set()
    for syms in graph.symbols.values():
        for node in ast.walk(syms.module.tree):
            if (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, (ast.Store, ast.Del))):
                leaf = astutil.name_leaf(node.value)
                if leaf:
                    out.add(leaf)
            elif isinstance(node, ast.AugAssign):
                t = node.target
                if isinstance(t, ast.Subscript):
                    t = t.value
                leaf = astutil.name_leaf(t)
                if leaf:
                    out.add(leaf)
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr in _MUTATORS):
                leaf = astutil.name_leaf(node.func.value)
                if leaf:
                    out.add(leaf)
    return out


def _is_mutable_value(v: ast.AST) -> str | None:
    """Kind of shared mutable state a top-level value denotes, or
    None for plainly immutable initializers."""
    if isinstance(v, _MUTABLE_LITERALS):
        return "container"
    if isinstance(v, ast.Call):
        leaf = astutil.name_leaf(v.func)
        if leaf is None or leaf in _IMMUTABLE_CALLS:
            return None
        if leaf in _MUTABLE_BUILTINS:
            return "container"
        if leaf.lstrip("_")[:1].isupper():
            return "instance"
    return None


def classify_state(name: str, kind: str) -> str:
    """fork-safe recomputable cache vs per-process counter vs
    per-process primitive vs correctness state (the swarm-PR triage
    split; the default is the conservative one)."""
    n = name.lower()
    if any(h in n for h in _PRIMITIVE_HINTS):
        return "per-process-primitive"
    if any(h in n for h in _COUNTER_HINTS):
        return "per-process-counter"
    if any(h in n for h in _CACHE_HINTS):
        return "fork-safe-cache"
    return "correctness-state"


def shared_state_census(graph: CallGraph) -> list[dict]:
    """Every module-level mutable global and mutable class attribute
    in the project, classified.  Pure data for ``--seam-report``.
    Module-global containers need project-wide mutation evidence
    (``_mutated_names``); instances (singleton objects) and class
    attributes are censused unconditionally."""
    mutated = _mutated_names(graph)
    out: list[dict] = []
    for path in sorted(graph.symbols):
        tree = graph.symbols[path].module.tree
        for stmt in tree.body:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                if len(targets) != 1 or stmt.value is None:
                    continue
                t = targets[0]
                if not isinstance(t, ast.Name):
                    continue
                kind = _is_mutable_value(stmt.value)
                if kind is None:
                    continue
                if kind == "container" and t.id not in mutated:
                    continue          # constant lookup table
                out.append({
                    "path": path, "line": stmt.lineno, "name": t.id,
                    "kind": f"module-global-{kind}",
                    "classification": classify_state(t.id, kind)})
            elif isinstance(stmt, ast.ClassDef):
                for sub in stmt.body:
                    if not (isinstance(sub, ast.Assign)
                            and len(sub.targets) == 1
                            and isinstance(sub.targets[0], ast.Name)):
                        continue
                    kind = _is_mutable_value(sub.value)
                    if kind is None:
                        continue
                    if (kind == "container"
                            and sub.targets[0].id not in mutated):
                        continue      # constant lookup table
                    name = f"{stmt.name}.{sub.targets[0].id}"
                    out.append({
                        "path": path, "line": sub.lineno,
                        "name": name,
                        "kind": f"class-attr-{kind}",
                        "classification": classify_state(name, kind)})
    return out


def _receiver_daemon(v: ast.AST, varmap: dict[str, str]) -> str | None:
    """Daemon class a receiver expression denotes, by convention."""
    if isinstance(v, ast.Name):
        return varmap.get(v.id) or NAME_TYPES.get(v.id)
    if isinstance(v, ast.Attribute):
        # self.mon / cluster.mon / self.osd ... the leaf names the role
        return NAME_TYPES.get(v.attr)
    if isinstance(v, ast.Subscript):
        return CONTAINER_ATTRS.get(astutil.name_leaf(v.value))
    return None


def _daemon_vars(root: ast.AST) -> dict[str, str]:
    """Locals typed as daemons by how they were bound: iteration over
    (or subscript into) a conventional daemon container."""
    out: dict[str, str] = {}

    def _bind(target, it) -> None:
        if not isinstance(target, ast.Name):
            return
        if isinstance(it, ast.Call):      # .values() / sorted(...)
            base = it.func
            if (isinstance(base, ast.Attribute)
                    and base.attr in ("values", "list")):
                it = base.value
            else:
                return
        leaf = astutil.name_leaf(it)
        d = CONTAINER_ATTRS.get(leaf)
        if d:
            out[target.id] = d

    for node in own_nodes(root):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            _bind(node.target, node.iter)
        elif isinstance(node, ast.comprehension):
            _bind(node.target, node.iter)
        elif (isinstance(node, ast.Assign) and len(node.targets) == 1
              and isinstance(node.value, ast.Subscript)):
            leaf = astutil.name_leaf(node.value.value)
            d = CONTAINER_ATTRS.get(leaf)
            if d and isinstance(node.targets[0], ast.Name):
                out[node.targets[0].id] = d
    return out


def daemon_reaches(graph: CallGraph) -> list[dict]:
    """Every site where code outside a daemon's home touches its
    private/subsystem/mutated attributes.  Pure data; check_project
    turns these into findings."""
    out: list[dict] = []
    seen: set[tuple] = set()
    for path in sorted(graph.symbols):
        syms = graph.symbols[path]
        contexts = [(graph.module_root(path),
                     syms.module.tree, None)]
        contexts += [(fi.qualname, fi.node, fi.cls)
                     for fi in syms.functions]
        for qual, root, cls in contexts:
            varmap = _daemon_vars(root)
            for node in own_nodes(root):
                if not isinstance(node, ast.Attribute):
                    continue
                daemon = _receiver_daemon(node.value, varmap)
                if daemon is None:
                    continue
                if cls == daemon or DAEMON_HOME[daemon] in path:
                    continue               # the daemon's own process
                attr = node.attr
                write = isinstance(node.ctx, (ast.Store, ast.Del))
                private = (attr.startswith("_")
                           and not attr.startswith("__"))
                if not (write or private or attr in SUBSYSTEM_ATTRS):
                    continue
                key = (path, node.lineno, daemon, attr, write)
                if key in seen:
                    continue
                seen.add(key)
                out.append({
                    "path": path, "line": node.lineno,
                    "daemon": daemon, "attr": attr,
                    "access": "write" if write else "read",
                    "private": private, "context": qual})
    return out


def reach_origin_daemons(graph: CallGraph, qual: str,
                         max_fanout: int = 6) -> set[str]:
    """Daemon classes whose code can reach the function holding a
    boundary reach (reverse closure over call edges): a reach in a
    shared helper is charged to every daemon that can run it."""
    out: set[str] = set()
    for q in graph.callers([qual], max_fanout=max_fanout):
        fi = graph.functions.get(q)
        if fi is not None and fi.cls in DAEMON_CLASSES:
            out.add(fi.cls)
    return out


@register
class CrossDaemonState(ProjectChecker):
    name = "cross-daemon-state"
    description = ("direct reads/writes of another daemon's private "
                   "or live-subsystem attributes instead of crossing "
                   "the Messenger (dangling references in a "
                   "multiprocess fleet); censuses shared mutable "
                   "globals for --seam-report")

    def check_project(self, graph: CallGraph) -> Iterable[Finding]:
        for r in daemon_reaches(graph):
            attr, daemon = r["attr"], r["daemon"]
            if r["access"] == "write":
                what = (f"mutates {daemon}.{attr} from outside the "
                        f"daemon -- a cross-daemon write has no "
                        f"meaning once each daemon owns a process")
            elif r["private"]:
                what = (f"reaches into {daemon} private state "
                        f"'.{attr}' -- add a public accessor; "
                        f"another daemon's internals are another "
                        f"process's memory in the swarm")
            else:
                what = (f"grabs {daemon}'s live '{attr}' subsystem "
                        f"across the daemon boundary -- route "
                        f"through the Messenger or a public "
                        f"accessor")
            yield Finding(r["path"], r["line"], self.name, what)
