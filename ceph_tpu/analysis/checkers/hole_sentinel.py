"""hole-sentinel: raw CRUSH holes must be normalized before role math.

The PR 2 wedge: raw CRUSH output encodes holes as ``CRUSH_ITEM_NONE``
(2^31-1), which passes every ``o >= 0`` "is this a live osd" test and
left hole-led PGs primary-less.  The contract since then: holes are
normalized to ``-1`` at the map boundary (``pg_to_up_acting``), and
everything downstream uses ``o >= 0``.

This rule patrols the boundary.  In any module that can observe *raw*
CRUSH output (it imports the mapper / vectorized engine or handles
``CRUSH_ITEM_NONE`` itself -- excluding the ``crush/`` layer, which IS
the raw producer), an osd-id comparison against 0 or -1, or an osd-id
truthiness test, is flagged unless the enclosing function demonstrates
sentinel awareness by referencing ``CRUSH_ITEM_NONE`` somewhere in its
body (the guard-and-filter idiom the boundary uses).
"""

from __future__ import annotations

import ast
from typing import Iterable

from .. import astutil
from ..core import Finding, Module
from ..registry import Checker, register

SENTINEL = "CRUSH_ITEM_NONE"
_RAW_IMPORTS = ("crush.mapper", "crush.vectorized")
# identifiers treated as osd ids when compared (exact, or any name
# containing "osd"): the vocabulary the placement pipeline actually
# uses for device ids
_OSD_NAMES = {"o", "osd", "cand", "primary"}


def _is_osdish(node: ast.AST) -> bool:
    leaf = astutil.name_leaf(node)
    if leaf is None:
        return False
    low = leaf.lower()
    # plural identifiers (osds, new_up_osds, osd_ids) are collections
    # of ids, not ids: truthiness/compares on them are emptiness
    # tests, not the hole-sentinel bug class
    if low.endswith("s"):
        return False
    return leaf in _OSD_NAMES or "osd" in low


def _aware(fn: ast.AST | None, module: Module) -> bool:
    """Sentinel awareness: the innermost enclosing function (or the
    whole module, for top-level code) references CRUSH_ITEM_NONE."""
    scope = fn if fn is not None else module.tree
    return astutil.references_name(scope, SENTINEL)


@register
class HoleSentinel(Checker):
    name = "hole-sentinel"
    description = ("osd-id compares vs 0/-1 in raw-CRUSH-observing "
                   "modules must handle CRUSH_ITEM_NONE")

    def scope(self, module: Module) -> bool:
        if "crush/" in module.path or "/crush/" in module.path:
            return False           # the raw layer itself
        tree = module.tree
        return (astutil.references_name(tree, SENTINEL)
                or astutil.imports_module(tree, *_RAW_IMPORTS))

    def check(self, module: Module) -> Iterable[Finding]:
        astutil.attach_parents(module.tree)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Compare):
                yield from self._check_compare(node, module)
            elif isinstance(node, (ast.If, ast.IfExp, ast.While)):
                yield from self._check_truthiness(node, module)

    def _check_compare(self, node: ast.Compare,
                       module: Module) -> Iterable[Finding]:
        operands = [node.left] + list(node.comparators)
        ops = node.ops
        hit = None
        for i, op in enumerate(ops):
            left, right = operands[i], operands[i + 1]
            for name_side, const_side in ((left, right), (right, left)):
                if not _is_osdish(name_side):
                    continue
                val = astutil.int_value(const_side)
                if val == 0 and isinstance(op, (ast.GtE, ast.Lt,
                                                ast.LtE, ast.Gt)):
                    hit = f"{astutil.name_leaf(name_side)} vs 0"
                elif val == -1 and isinstance(op, (ast.Eq, ast.NotEq)):
                    hit = f"{astutil.name_leaf(name_side)} vs -1"
        if hit is None:
            return
        fn = astutil.enclosing_function(node)
        if _aware(fn, module):
            return
        yield Finding(
            module.path, node.lineno, self.name,
            f"osd-id comparison ({hit}) in a raw-CRUSH-observing "
            f"module without a {SENTINEL} guard in the enclosing "
            f"function; normalize holes to -1 first "
            f"(pg_to_up_acting boundary)")

    def _check_truthiness(self, node: ast.AST,
                          module: Module) -> Iterable[Finding]:
        test = node.test
        if not (isinstance(test, (ast.Name, ast.Attribute))
                and _is_osdish(test)):
            return
        fn = astutil.enclosing_function(node)
        if _aware(fn, module):
            return
        yield Finding(
            module.path, node.lineno, self.name,
            f"truthiness test on osd id "
            f"`{astutil.name_leaf(test)}` in a raw-CRUSH-observing "
            f"module: {SENTINEL} (2^31-1) and osd.0 both defeat it; "
            f"compare against the normalized -1 hole instead")
