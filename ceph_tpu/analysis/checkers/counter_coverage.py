"""counter-coverage: every perf-counter touch needs a live path.

``perf-coherence`` checks counter keys are *shaped* consistently;
this rule checks they can *fire*.  A counter incremented only inside
a function no entry point reaches is dead instrumentation: the
dashboard charts it as eternally zero and an operator debugging from
it chases a path that cannot execute.  It usually means one of two
bugs -- the instrumented helper lost its last caller in a refactor
(the counter should go), or the wiring that was supposed to call the
helper was never written (the counter is a lie).

Liveness is over-approximated on purpose: entry points are module
top-level code plus every public-shaped function (no leading
underscore, dunders, ``test_*``, ``main``), and the closure follows
call edges at any fan-out *and* reference edges (handler tables,
callbacks, decorators), so only a private function that nothing
reachable even *mentions* is dead.  Tests drive the tree through its
public API, so "reachable from a public function" is the static
stand-in for "some test or daemon path exercises it".
"""

from __future__ import annotations

import ast
from typing import Iterable

from .. import astutil
from ..callgraph import CallGraph, own_nodes
from ..core import Finding
from ..registry import ProjectChecker, register
from .perf_coherence import _perfish

_MUTATORS = {"inc", "tinc", "set_gauge", "hist_sample", "time"}


@register
class CounterCoverage(ProjectChecker):
    name = "counter-coverage"
    description = ("perf counters touched only in functions no "
                   "entry point (public API, test, module top "
                   "level) reaches -- dead instrumentation")

    def check_project(self, graph: CallGraph) -> Iterable[Finding]:
        live = graph.reachable(graph.entry_points(), refs=True)
        for qual in sorted(graph.functions):
            if qual in live:
                continue
            fi = graph.functions[qual]
            for node in own_nodes(fi.node):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _MUTATORS
                        and _perfish(node.func.value)):
                    continue
                key = (astutil.const_str(node.args[0])
                       if node.args else None)
                what = (f"counter '{key}'" if key
                        else f".{node.func.attr}(...)")
                yield Finding(
                    fi.path, node.lineno, self.name,
                    f"{what} is touched only in '{fi.local}', which "
                    f"no entry point reaches (not called or "
                    f"referenced from any public function, test, or "
                    f"module top level) -- dead instrumentation: "
                    f"wire the caller or drop the counter")
                break       # one finding per dead function is enough
