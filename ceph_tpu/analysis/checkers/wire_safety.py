"""wire-safety: everything that enters a Message must survive a wire.

Today the messenger loops frames back inside one process, so a
payload can smuggle anything -- a future, a bound method, a live jax
Array -- and the receiver gets the very same object.  Across the
multiprocess seam (shared-memory ring or socketpair) only *data*
crosses: the denc codecs serialize plain values, and anything tied to
the sending process's event loop, heap, or device client is garbage
on the other side.

The rule censuses every ``Message(type, {...})`` construction in the
tree (the construction site is where the payload's provenance is
visible; by the time ``Messenger.send``/``SubOpPipe.stage`` sees the
message it is an opaque dict) and flags payload fields whose value is
inferred to be non-wire-safe:

* an un-awaited coroutine (a call that resolves, at fan-out 1, to an
  ``async def``),
* an asyncio future/task (``ensure_future``/``create_task``/
  ``Future()``),
* a synchronization primitive (``Lock``/``Event``/``Semaphore``/
  ``Condition``),
* a live jax Array (a producer call resolving through the import
  table into ``jax``/``jax.numpy``),
* a bound method (``self.handler`` passed uncalled).

The census side (``--seam-report``) records every constructed wire
type with its codec verdict: ``typed`` (an explicit MOSDOp-style
layout in ``WIRE_CODECS``), ``control`` (``__``-prefixed messenger
internals), or ``generic`` (rides the tagged-value denc encoding),
plus which types the dispatch side consumes (``msg.type == "..."``
comparisons, ``_h_<type>`` handler methods, or a waiter queue keyed
by the request type a ``*_reply``/``*_ack`` name answers).  A
constructed type nobody consumes IS a finding: dead wire vocabulary,
or a sender whose reply silently hangs.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .. import astutil
from ..callgraph import CallGraph, _Resolver, own_nodes
from ..core import Finding
from ..registry import ProjectChecker, register

MAX_FANOUT = 1        # a coroutine verdict wants an unambiguous callee

_FUTURE_CALLS = {"ensure_future", "create_task"}
_SYNC_PRIMITIVES = {"Lock", "RLock", "Semaphore", "BoundedSemaphore",
                    "Event", "Condition", "Barrier", "Queue"}


def _module_str_consts(tree: ast.AST) -> dict[str, str]:
    """Top-level ``NAME = "literal"`` bindings (message-type
    constants like ACK_TYPE)."""
    out: dict[str, str] = {}
    for stmt in tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            s = astutil.const_str(stmt.value)
            if s is not None:
                out[stmt.targets[0].id] = s
    return out


def _local_values(root: ast.AST) -> dict[str, ast.AST]:
    """name -> value expression for single-assignment locals, so
    ``data = {...}; Message(t, data)`` (and an unsafe value bound to
    a name first) are as visible as the inline form."""
    out: dict[str, ast.AST] = {}
    dead: set[str] = set()
    for node in own_nodes(root):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            name = node.targets[0].id
            if name in out or name in dead:
                dead.add(name)       # reassigned: ambiguous
                out.pop(name, None)
            else:
                out[name] = node.value
    return out


class _ModuleScan:
    """Per-module context shared by the finding and census passes."""

    def __init__(self, graph: CallGraph, syms) -> None:
        self.graph = graph
        self.syms = syms
        self.consts = _module_str_consts(syms.module.tree)
        self.resolver = _Resolver(graph, syms)

    def type_of(self, node: ast.AST) -> str:
        s = astutil.const_str(node)
        if s is not None:
            return s
        if isinstance(node, ast.Name):
            if node.id in self.consts:
                return self.consts[node.id]
            # imported constant: alias -> defining module's table
            target = self.syms.aliases.get(node.id)
            if target:
                mod, _, leaf = target.rpartition(".")
                other = self.graph.module_by_dotted.get(mod)
                if other is not None:
                    consts = _module_str_consts(other.module.tree)
                    if leaf in consts:
                        return consts[leaf]
        return "<dynamic>"

    def unsafe_kind(self, v: ast.AST, cls: str | None,
                    local_values: dict) -> str | None:
        if isinstance(v, ast.Name) and v.id in local_values:
            v = local_values[v.id]
        if isinstance(v, ast.Await):
            return None                     # awaited: a plain result
        if isinstance(v, ast.Call):
            leaf = astutil.name_leaf(v.func)
            if leaf in _FUTURE_CALLS or leaf == "Future":
                return "an asyncio future/task"
            if leaf in _SYNC_PRIMITIVES:
                base = astutil.dotted(v.func) or ""
                head = self.syms.expand_alias(base.split(".", 1)[0])
                if head in ("asyncio", "threading", "") or "." not \
                        in base:
                    return "a synchronization primitive"
            d = astutil.dotted(v.func)
            if d and "." in d:
                head = self.syms.expand_alias(d.split(".", 1)[0])
                if head == "jax" or head.startswith("jax."):
                    return "a live jax Array"
            for dst, fo in self.resolver.resolve_call(v, cls, []):
                if fo <= MAX_FANOUT:
                    fi = self.graph.functions.get(dst)
                    if fi is not None and fi.is_async:
                        return "an un-awaited coroutine"
        if isinstance(v, ast.Attribute) and isinstance(v.ctx,
                                                       ast.Load):
            base = v.value
            if (isinstance(base, ast.Name) and base.id == "self"
                    and cls is not None):
                ci = self.syms.classes.get(cls)
                if ci is not None and v.attr in ci.methods:
                    return "a bound method"
        return None


def _payload_fields(call: ast.Call,
                    local_values: dict) -> list[tuple[str, ast.AST]]:
    data = None
    if len(call.args) >= 2:
        data = call.args[1]
    else:
        for kw in call.keywords:
            if kw.arg == "data":
                data = kw.value
    if isinstance(data, ast.Name):
        data = local_values.get(data.id)
    if not isinstance(data, ast.Dict):
        return []
    out = []
    for k, v in zip(data.keys, data.values):
        ks = astutil.const_str(k) if k is not None else None
        if ks is not None:
            out.append((ks, v))
    return out


def _message_sites(graph: CallGraph):
    """Yield one record per ``Message(...)`` construction in the
    project."""
    for path in sorted(graph.symbols):
        syms = graph.symbols[path]
        scan = _ModuleScan(graph, syms)
        contexts = [(graph.module_root(path),
                     syms.module.tree, None)]
        contexts += [(fi.qualname, fi.node, fi.cls)
                     for fi in syms.functions]
        for qual, root, cls in contexts:
            local_values = _local_values(root)
            for node in own_nodes(root):
                if not (isinstance(node, ast.Call)
                        and astutil.name_leaf(node.func) == "Message"
                        and node.args):
                    continue
                mtype = scan.type_of(node.args[0])
                fields = _payload_fields(node, local_values)
                yield (scan, path, qual, cls, node, mtype, fields,
                       local_values)


def wire_codec_table(graph: CallGraph) -> dict[str, tuple[str, str]]:
    """The ``WIRE_CODECS`` dict literal, parsed: type -> (enc, dec)
    function leaf names (empty when the module is out of scope)."""
    out: dict[str, tuple[str, str]] = {}
    for syms in graph.symbols.values():
        for stmt in syms.module.tree.body:
            if not (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "WIRE_CODECS"
                    and isinstance(stmt.value, ast.Dict)):
                continue
            for k, v in zip(stmt.value.keys, stmt.value.values):
                ks = astutil.const_str(k) if k is not None else None
                if ks is None or not isinstance(v, ast.Tuple) \
                        or len(v.elts) != 2:
                    continue
                enc = astutil.name_leaf(v.elts[0])
                dec = astutil.name_leaf(v.elts[1])
                if enc and dec:
                    out[ks] = (enc, dec)
    return out


def handled_types(graph: CallGraph) -> set[str]:
    """Message types some dispatcher consumes: a ``msg.type == "x"``
    / ``in ("x", ...)`` comparison, or a ``_h_<type>`` handler method
    (the ``getattr(self, f"_h_{msg.type}")`` dispatch idiom)."""
    out: set[str] = set()
    for fi in graph.functions.values():
        leaf = fi.local.rpartition(".")[2]
        if leaf.startswith("_h_"):
            out.add(leaf[len("_h_"):])
    for syms in graph.symbols.values():
        for node in ast.walk(syms.module.tree):
            if not isinstance(node, ast.Compare):
                continue
            left = node.left
            if not (isinstance(left, ast.Attribute)
                    and left.attr == "type"):
                continue
            for comp in node.comparators:
                s = astutil.const_str(comp)
                if s is not None:
                    out.add(s)
                elif isinstance(comp, (ast.Tuple, ast.Set,
                                       ast.List)):
                    for e in comp.elts:
                        es = astutil.const_str(e)
                        if es is not None:
                            out.add(es)
    return out


def _request_of(mtype: str) -> str | None:
    """The request type a conventional reply/ack name answers, or
    None when the name is not reply-shaped.  Replies are matched by
    per-request waiter queues (``msg.type == reply_type``), which no
    static dispatch table shows."""
    for suffix in ("_reply", "_ack"):
        if mtype.endswith(suffix):
            return mtype[:-len(suffix)]
    return None


def wire_census(graph: CallGraph) -> list[dict]:
    """One entry per constructed message type: codec verdict, the
    fields seen across all construction sites, whether the dispatch
    side handles it, and any unsafe fields."""
    codecs = wire_codec_table(graph)
    handled = handled_types(graph)
    types: dict[str, dict] = {}
    for scan, path, qual, cls, node, mtype, fields, local_values \
            in _message_sites(graph):
        entry = types.setdefault(mtype, {
            "type": mtype, "fields": set(), "sites": [],
            "unsafe_fields": []})
        entry["sites"].append(f"{path}:{node.lineno}")
        for k, v in fields:
            entry["fields"].add(k)
            kind = scan.unsafe_kind(v, cls, local_values)
            if kind is not None:
                entry["unsafe_fields"].append(
                    {"field": k, "carries": kind,
                     "site": f"{path}:{node.lineno}"})
    out = []
    for mtype in sorted(types):
        e = types[mtype]
        if mtype in codecs:
            codec = "typed"
        elif mtype.startswith("__"):
            codec = "control"
        elif mtype == "<dynamic>":
            codec = "dynamic"
        else:
            codec = "generic"
        verdict = ("unsafe" if e["unsafe_fields"] else "wire-safe")
        req = _request_of(mtype)
        consumed = (mtype in handled or codec in ("control", "dynamic")
                    or (req is not None
                        and (req in types or req in handled)))
        out.append({"type": mtype, "codec": codec,
                    "verdict": verdict,
                    "handled": consumed,
                    "fields": sorted(e["fields"]),
                    "sites": e["sites"],
                    "unsafe_fields": e["unsafe_fields"]})
    return out


@register
class WireSafety(ProjectChecker):
    name = "wire-safety"
    description = ("Message payload fields carrying futures, "
                   "coroutines, locks, live jax Arrays, or bound "
                   "methods -- objects that cannot cross a process "
                   "transport; censuses the wire-type vocabulary "
                   "for --seam-report")

    def check_project(self, graph: CallGraph) -> Iterable[Finding]:
        orphans = {e["type"] for e in wire_census(graph)
                   if not e["handled"]}
        for scan, path, qual, cls, node, mtype, fields, \
                local_values in _message_sites(graph):
            for k, v in fields:
                kind = scan.unsafe_kind(v, cls, local_values)
                if kind is None:
                    continue
                yield Finding(
                    path, node.lineno, self.name,
                    f"message '{mtype}' payload field '{k}' "
                    f"carries {kind} -- it cannot cross a process "
                    f"transport; ship plain data and rebuild the "
                    f"object on the receiving side")
            if mtype in orphans:
                yield Finding(
                    path, node.lineno, self.name,
                    f"message type '{mtype}' is constructed but no "
                    f"dispatcher consumes it (no == comparison, no "
                    f"_h_{mtype} handler, no request counterpart for "
                    f"a reply queue) -- dead wire vocabulary, or a "
                    f"sender whose reply silently hangs")
