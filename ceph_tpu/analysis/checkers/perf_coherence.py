"""perf-coherence: counter keys must be used consistently tree-wide.

``PerfCounters`` auto-vivifies plain counters, so the failure modes
are not missing registrations but *shape* mismatches that only bite
at scrape time -- this is the cross-module, two-pass rule:

* ``hist_sample(key)`` with no ``hist_register(key)`` anywhere in the
  tree is a guaranteed ``KeyError`` the first time the code path runs
  (the register lives in one module, the sample sites in others);
* ``hist_register(key)`` that nothing ever samples is a dead counter
  the dashboards will chart as eternally zero;
* one key used as two different kinds (``inc`` + ``set_gauge``,
  ``inc`` + ``tinc``, ...) collides in ``dump()``'s flat namespace --
  the gauge/avg silently overwrites the counter in the scraped JSON.

Pass 1 (``check``) collects constant-string keys invoked on
perf-shaped receivers (``perf``, ``pc``, ``*perf*``); pass 2
(``finalize``) reconciles them across every module.  Dynamic
(non-literal) keys are out of scope by design.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .. import astutil
from ..core import Finding, Module, Project
from ..registry import Checker, register

_METHOD_KIND = {
    "inc": "counter",
    "get": "counter",
    "set_gauge": "gauge",
    "tinc": "avg",
    "time": "avg",
    "hist_sample": "hist_sample",
    "hist_register": "hist_register",
}
# kinds that land in dump()'s flat key namespace and therefore collide
_VALUE_KINDS = ("counter", "gauge", "avg", "hist_register")


def _perfish(receiver: ast.AST) -> bool:
    leaf = astutil.name_leaf(receiver)
    if leaf is None:
        return False
    return leaf in ("pc",) or "perf" in leaf.lower()


@register
class PerfCoherence(Checker):
    name = "perf-coherence"
    description = ("perf counter keys sampled-but-unregistered, "
                   "registered-but-untouched, or kind-colliding "
                   "across modules")

    def __init__(self) -> None:
        # key -> kind -> first (path, line) observed
        self._sites: dict[str, dict[str, tuple[str, int]]] = {}

    def check(self, module: Module) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            kind = _METHOD_KIND.get(node.func.attr)
            if kind is None or not node.args:
                continue
            if not _perfish(node.func.value):
                continue
            key = astutil.const_str(node.args[0])
            if key is None:
                continue
            self._sites.setdefault(key, {}).setdefault(
                kind, (module.path, node.lineno))
        return ()

    def finalize(self, project: Project) -> Iterable[Finding]:
        sites, self._sites = self._sites, {}
        for key in sorted(sites):
            kinds = sites[key]
            if "hist_sample" in kinds and "hist_register" not in kinds:
                path, line = kinds["hist_sample"]
                yield Finding(
                    path, line, self.name,
                    f"histogram key '{key}' is sampled but never "
                    f"hist_register()ed anywhere in the tree: "
                    f"KeyError on first sample")
            if "hist_register" in kinds and "hist_sample" not in kinds:
                path, line = kinds["hist_register"]
                yield Finding(
                    path, line, self.name,
                    f"histogram key '{key}' is registered but never "
                    f"sampled anywhere in the tree: dead counter")
            value_kinds = [k for k in _VALUE_KINDS if k in kinds]
            if len(value_kinds) > 1:
                path, line = kinds[value_kinds[1]]
                yield Finding(
                    path, line, self.name,
                    f"key '{key}' is used as {value_kinds[0]} and as "
                    f"{value_kinds[1]}: the kinds share dump()'s "
                    f"flat namespace, one silently overwrites the "
                    f"other in the scraped JSON")
