"""hot-path-config-read: no config lookups on the launch-loop paths.

PR 8 established the config-snapshot discipline: every knob the codec
batcher / mesh / EC read path consumes is read ONCE at construction
(``CodecBatcher.from_config``, the ECBackend ``osd_ec_read_*``
snapshot) and the hot loops never touch the config dict.  A
``conf.get`` that creeps back onto those paths re-adds a dict probe
chain per launch/read -- and worse, makes behavior racy against
runtime ``config set`` (half a batch under the old value, half under
the new).  This rule is the static closure of that discipline: from
the launch-loop entry points the dynamic no-lookup micro-assertions
watch, every function reachable through call edges of fan-out <= 4 is
"on the hot path", and a config read there is a finding.

The read heuristic matches the ``config-schema`` rule: a ``.get`` or
``[]`` whose receiver's leaf name is ``conf``/``config``/``cfg`` and
whose key is a snake_case option name.  The fix is always the same --
snapshot at construction and close over the value.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from .. import astutil
from ..callgraph import CallGraph, own_nodes
from ..core import Finding
from ..registry import ProjectChecker, register

# the launch-loop entry points the config-snapshot discipline covers:
# the batcher submit/launch spine, the mesh launches, the batched
# StripeInfo drivers, the EC read path (runs per degraded read), the
# shard-cache hot entry points, the bulk CRUSH mapper and the CRC
# engines -- the same spine the dynamic micro-assertions watch
ROOTS = (
    "CodecBatcher.encode",
    "CodecBatcher.decode",
    "CodecBatcher.rmw",
    "CodecBatcher._submit",
    "CodecBatcher._run_batch",
    "MeshCodec.encode",
    "MeshCodec.decode",
    "MeshCodec.rmw",
    "StripeInfo.encode_async",
    "StripeInfo.decode_async",
    "StripeInfo.reconstruct_logical_async",
    "ECBackend._fetch_shards",
    "ECBackend._gather_shards",
    "ECBackend.collect_shard_states",
    # the recovery repair path (runs per rebuilt shard: fragment
    # pulls + full gathers) and the flat codec launch entry points --
    # the osd_ec_repair_fragments_enabled gate is snapshot at
    # construction, never read per repair
    "ECBackend.read_recovery_payload",
    "ECBackend._fragment_recover",
    "LinearSubchunkCodec.encode_batch",
    "LinearSubchunkCodec.decode_batch",
    "HedgedGather.gather_shards",
    "HedgedGather.first_reply",
    "DeviceShardCache.get",
    "DeviceShardCache.put",
    "VectorCrush.map_pgs",
    "crc32c_batch",
    "crc32c_rows",
)

MAX_FANOUT = 4

_RECEIVERS = {"conf", "config", "cfg"}
_KEY_RE = re.compile(r"^[a-z][a-z0-9]*(?:_[a-z0-9]+)+$")


def _config_read(node: ast.AST) -> str | None:
    """The option key this node reads from a config receiver, if any."""
    if isinstance(node, ast.Call) \
            and isinstance(node.func, ast.Attribute) \
            and node.func.attr == "get" and node.args \
            and astutil.name_leaf(node.func.value) in _RECEIVERS:
        key = astutil.const_str(node.args[0])
    elif isinstance(node, ast.Subscript) \
            and isinstance(node.ctx, ast.Load) \
            and astutil.name_leaf(node.value) in _RECEIVERS:
        key = astutil.const_str(node.slice)
    else:
        return None
    if key is not None and _KEY_RE.match(key):
        return key
    return None


@register
class HotPathConfigRead(ProjectChecker):
    name = "hot-path-config-read"
    description = ("conf/config/cfg reads reachable from the launch-"
                   "loop entry points the config-snapshot discipline "
                   "covers (snapshot at construction instead)")

    def check_project(self, graph: CallGraph) -> Iterable[Finding]:
        roots: list[str] = []
        root_of: dict[str, str] = {}
        for spec in ROOTS:
            for qual in graph.lookup(spec):
                roots.append(qual)
                root_of[qual] = spec
        if not roots:
            return
        seen: dict[str, str] = {}
        stack = [(q, root_of[q]) for q in roots]
        while stack:
            cur, origin = stack.pop()
            if cur in seen:
                continue
            seen[cur] = origin
            for dst, fo in graph.calls.get(cur, {}).items():
                if fo <= MAX_FANOUT and dst not in seen \
                        and dst in graph.functions:
                    stack.append((dst, origin))
        for qual, origin in sorted(seen.items()):
            fi = graph.functions.get(qual)
            if fi is None:
                continue
            for node in own_nodes(fi.node):
                key = _config_read(node)
                if key is not None:
                    yield Finding(
                        fi.path, node.lineno, self.name,
                        f"config key '{key}' read on the launch-loop "
                        f"hot path (reachable from {origin}): a dict "
                        f"probe per launch, racy against runtime "
                        f"config set -- snapshot the value at "
                        f"construction (from_config / __init__) and "
                        f"close over it")
