"""donated-buffer-aliasing: no reads of a buffer a launch consumed.

The sharded data plane (parallel/mesh_codec.py) passes stripe buffers
to ``jax.jit(..., donate_argnums=...)``-compiled launches: the launch
OWNS the donated device buffer -- XLA may alias it into the output (the
RMW in-place update) or free it mid-execution.  Reading the Python
name again after the call returns garbage-or-crash depending on
backend and phase of the moon, which is exactly the class of bug a
test on one backend does not catch.  ROADMAP queued this rule the day
the data plane adopted donation: *a donated array read after the
launch that consumed it is a use-after-donate*.

Detection is best-effort by construction, like the rest of the call
graph layer:

* a *donating callable* is a name bound to ``jax.jit``/``pjit`` (or a
  function decorated with either) carrying a literal
  ``donate_argnums``;
* donation PROPAGATES interprocedurally: a function that forwards its
  own parameter into a donated position is itself donating that
  parameter (fixpoint over the project), so a caller module away from
  the jit still gets flagged;
* at every call site of a donating callable, an argument spelled as a
  plain name that is READ again after the call -- before any
  re-binding of the name -- is a finding.

Scoped to jax-importing modules: donation is a jax contract; nothing
else produces these buffers.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .device_path import _imports_top
from .. import astutil
from ..callgraph import CallGraph, own_nodes
from ..core import Finding
from ..registry import ProjectChecker, register

_JIT_LEAVES = {"jit", "pjit"}

# Launch wrappers whose donation the AST cannot see (the jit carrying
# donate_argnums comes out of a cached compile factory, so no literal
# reaches the call site): seeded into the donor fixpoint by name, the
# way device_path.ROOTS anchors reachability.  Positions are call-arg
# indices after self.  The scheduled-kernel mesh launches
# (parallel/mesh_codec.py) consume their donated device buffers
# through exactly these entry points.
ROOTS = (
    ("MeshCodec._sched_launch", (1,)),
    ("MeshCodec._sched_rmw_launch", (1, 2)),
)


def _donated_positions(call: ast.Call) -> tuple[int, ...] | None:
    """Literal donate_argnums of a jit/pjit call, or None."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for el in v.elts:
                if not (isinstance(el, ast.Constant)
                        and isinstance(el.value, int)):
                    return None
                out.append(el.value)
            return tuple(out)
        return None
    return None


def _is_jit(call: ast.Call, syms) -> bool:
    leaf = astutil.name_leaf(call.func)
    if leaf not in _JIT_LEAVES:
        return False
    dotted = astutil.dotted(call.func)
    if dotted is None or "." not in dotted:
        # bare `jit(...)`: accept when imported from jax
        return syms.expand_alias(leaf).startswith("jax")
    head = dotted.split(".", 1)[0]
    return syms.expand_alias(head).startswith("jax")


def _params(node) -> list[str]:
    a = node.args
    return [p.arg for p in (a.posonlyargs + a.args)]


@register
class DonatedBufferAliasing(ProjectChecker):
    name = "donated-buffer-aliasing"
    description = ("a buffer read after being passed into a donated "
                   "(donate_argnums) launch position -- the launch "
                   "owns it; reading it back is use-after-donate")

    def check_project(self, graph: CallGraph) -> Iterable[Finding]:
        in_scope = {
            path for path, syms in graph.symbols.items()
            if _imports_top(syms.module.tree, "jax")}
        if not in_scope:
            return
        # donors: callee key -> donated CALL-ARG positions.  Keys:
        # ("mod", path, name) for module-level jit bindings,
        # ("fn", qualname) for functions (decorated or propagated).
        donors: dict[tuple, tuple[int, ...]] = {}
        for path in in_scope:
            syms = graph.symbols[path]
            for node in ast.walk(syms.module.tree):
                if (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)
                        and _is_jit(node.value, syms)):
                    pos = _donated_positions(node.value)
                    if pos:
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                donors[("mod", path, tgt.id)] = pos
            for fi in syms.functions:
                if fi.path != path:
                    continue
                for dec in fi.node.decorator_list:
                    if isinstance(dec, ast.Call):
                        pos = (_donated_positions(dec)
                               if self._jitlike_decorator(dec, syms)
                               else None)
                        if pos:
                            # param index -> call-arg index (methods
                            # drop the explicit self at the call site)
                            off = 1 if fi.cls else 0
                            donors[("fn", fi.qualname)] = tuple(
                                p - off for p in pos if p - off >= 0)
        # declared donor ROOTS: launch wrappers around factory-made
        # donating executables
        for spec, pos in ROOTS:
            for qual in graph.lookup(spec):
                fi = graph.functions.get(qual)
                if fi is not None and fi.path in in_scope:
                    merged = set(donors.get(("fn", qual), ())) | set(pos)
                    donors[("fn", qual)] = tuple(sorted(merged))

        # interprocedural fixpoint: forwarding a parameter into a
        # donated position makes the forwarder a donor of that param
        for _ in range(6):
            grew = False
            for path in in_scope:
                syms = graph.symbols[path]
                for fi in syms.functions:
                    params = _params(fi.node)
                    off = 1 if fi.cls and params[:1] == ["self"] else 0
                    mine: set[int] = set(
                        donors.get(("fn", fi.qualname), ()))
                    before = len(mine)
                    for call, pos in self._donating_calls(
                            fi, syms, graph, donors):
                        for p in pos:
                            if p >= len(call.args):
                                continue
                            arg = call.args[p]
                            if isinstance(arg, ast.Name) \
                                    and arg.id in params:
                                cp = params.index(arg.id) - off
                                if cp >= 0:
                                    mine.add(cp)
                    if len(mine) > before:
                        donors[("fn", fi.qualname)] = tuple(
                            sorted(mine))
                        grew = True
            if not grew:
                break

        for path in sorted(in_scope):
            syms = graph.symbols[path]
            for fi in syms.functions:
                yield from self._check_function(fi, syms, graph,
                                                donors)

    @staticmethod
    def _jitlike_decorator(dec: ast.Call, syms) -> bool:
        """``@jax.jit(...)`` / ``@partial(jax.jit, ...)`` forms."""
        if _is_jit(dec, syms):
            return True
        leaf = astutil.name_leaf(dec.func)
        if leaf != "partial" or not dec.args:
            return False
        inner = dec.args[0]
        leaf0 = astutil.name_leaf(inner)
        if leaf0 not in _JIT_LEAVES:
            return False
        head = (astutil.dotted(inner) or leaf0).split(".", 1)[0]
        return syms.expand_alias(head).startswith("jax")

    def _donating_calls(self, fi, syms, graph: CallGraph,
                        donors: dict):
        """(call node, donated call-arg positions) sites in ``fi``,
        including calls through local jit bindings made inside it."""
        local: dict[str, tuple[int, ...]] = {}
        for node in own_nodes(fi.node):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and _is_jit(node.value, syms)):
                pos = _donated_positions(node.value)
                if pos:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            local[tgt.id] = pos
        for node in own_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            pos = self._resolve_donor(node, fi, syms, graph, donors,
                                      local)
            if pos:
                yield node, pos

    @staticmethod
    def _resolve_donor(call: ast.Call, fi, syms, graph: CallGraph,
                       donors: dict, local: dict):
        func = call.func
        if isinstance(func, ast.Name):
            name = func.id
            if name in local:
                return local[name]
            hit = donors.get(("mod", fi.path, name))
            if hit:
                return hit
            tf = syms.top_funcs.get(name)
            if tf is not None:
                return donors.get(("fn", tf.qualname))
            target = syms.aliases.get(name)
            if target and "." in target:
                mod, _, leaf = target.rpartition(".")
                msyms = graph.module_by_dotted.get(mod)
                if msyms is not None:
                    hit = donors.get(("mod", msyms.module.path, leaf))
                    if hit:
                        return hit
                    tf = msyms.top_funcs.get(leaf)
                    if tf is not None:
                        return donors.get(("fn", tf.qualname))
            return None
        if isinstance(func, ast.Attribute) and fi.cls \
                and isinstance(func.value, ast.Name) \
                and func.value.id == "self":
            ci = syms.classes.get(fi.cls)
            if ci is not None:
                meth = ci.methods.get(func.attr)
                if meth is not None:
                    return donors.get(("fn", meth.qualname))
        return None

    def _check_function(self, fi, syms, graph: CallGraph,
                        donors: dict) -> Iterable[Finding]:
        sites = list(self._donating_calls(fi, syms, graph, donors))
        if not sites:
            return
        # name -> [(load lineno, node)], [store linenos]
        loads: dict[str, list[int]] = {}
        stores: dict[str, list[int]] = {}
        for node in own_nodes(fi.node):
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    loads.setdefault(node.id, []).append(node.lineno)
                else:
                    stores.setdefault(node.id, []).append(node.lineno)
        for call, pos in sites:
            end = getattr(call, "end_lineno", call.lineno)
            for p in pos:
                if p >= len(call.args):
                    continue
                arg = call.args[p]
                if not isinstance(arg, ast.Name):
                    continue
                name = arg.id
                rebinds = [ln for ln in stores.get(name, ())
                           if ln >= call.lineno]
                horizon = min(rebinds) if rebinds else 10 ** 9
                bad = [ln for ln in loads.get(name, ())
                       if end < ln < horizon]
                if bad:
                    yield Finding(
                        fi.path, min(bad), self.name,
                        f"`{name}` read after the launch at line "
                        f"{call.lineno} consumed it (donated arg "
                        f"position {p}): the launch owns a donated "
                        f"buffer -- read before the launch, re-bind "
                        f"the name, or copy first")
