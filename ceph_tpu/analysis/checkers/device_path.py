"""device-path-host-sync: no host syncs reachable from batch launches.

The batched data plane only pays off if a launch stays on device from
submission to fan-out: one stray ``np.asarray`` / ``.item()`` /
``.block_until_ready()`` / ``bytes()`` inside the launch closure
re-serializes the whole batch through the host and silently turns the
amortized round trip back into a per-op one.  PR 5's
``scalar_calls_on_batched_paths=0`` perf-counter gate proves this
dynamically -- but only on the paths the bench happens to drive.  This
rule is the static closure of the same invariant: starting from the
launch entry points (the submit API of ``CodecBatcher``, the batched
``StripeInfo`` drivers riding it, the bulk ``VectorCrush`` mapper, and
the ``crc32c_batch`` engines), every function reachable through call
edges of fan-out <= 4 is "on the batched device path", and host-sync
operations there are findings.

Two precision fences keep the closure on the data plane it guards:

* the traversal never leaves *device-plane modules* (modules that
  import numpy or jax at the top level) -- a call that escapes into
  the transaction/messaging layers has already crossed the one
  intended host boundary, and everything past it is host code by
  construction;
* ``bytes()`` only counts in jax-importing modules -- it forces a
  transfer only when its argument can be a device array, and device
  arrays do not flow through modules that never touch jax.

Deliberate host hops (the single post-launch materialization, the
host fallback for non-batch codecs, the host CRC engine) carry a
``# lint: disable=device-path-host-sync`` with a one-line
justification -- the suppression is the documentation that the hop
was a decision, not an accident.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .. import astutil
from ..callgraph import CallGraph, own_nodes
from ..core import Finding
from ..registry import ProjectChecker, register

# the launch entry points of the batched data plane, by Class.method
# (or bare function) name; the dynamic scalar_calls_on_batched_paths
# gate exercises exactly these (bench.py --integrity / --osd-path)
ROOTS = (
    "CodecBatcher.encode",
    "CodecBatcher.decode",
    "MeshCodec.encode",
    "MeshCodec.decode",
    "MeshCodec.rmw",
    "StripeInfo.encode_async",
    "StripeInfo.decode_async",
    "StripeInfo.reconstruct_logical_async",
    "VectorCrush.map_pgs",
    "crc32c_batch",
    "crc32c_rows",
    "crc32c_device_chunks",
    "ErasureCodeTpu.encode_batch_crc",
    "JaxBackend.matmul_batch_crc",
    # the XOR-schedule compiler's launch entry points
    # (ops/xor_schedule.py): the batched scheduled kernel family and
    # the host scheduled executor the BitMatrixCodec data path rides
    "sched_matmul_batch_device",
    "scheduled_xor_matmul",
    "MeshCodec._apply_sched",
    "MeshCodec._rmw_sched",
    # the hedged gather spine (osd/hedged_gather.py): reply buffers
    # flow straight into decode launches, so a stray host sync in the
    # engine re-serializes every gather.  (The ECBackend fetch shims
    # around it are NOT rooted: they call into minimum_to_decode
    # PLANNING code, whose host-side GF algebra is legitimate.)
    "HedgedGather.gather_shards",
    "HedgedGather.first_reply",
    # the pipelined launch spine (PR 12): the staged launch driver
    # owns the dispatch/materialize split -- a stray host sync inside
    # it would close the overlap window the double-buffering opens
    "CodecBatcher._drive",
    "CodecBatcher._dispatch",
    "CodecBatcher._complete",
    # the flat linear codec family (ec/linear_codec.py): lrc/pmsr
    # encode/decode ride the batched scheduled/dense kernels through
    # these, and the mesh flat-dialect RMW reshape wraps the same
    # launches -- a host hop inside any of them re-serializes every
    # layered/regenerating launch
    "LinearSubchunkCodec.encode_batch",
    "LinearSubchunkCodec.decode_batch",
    "LinearSubchunkCodec._batch_matmul",
    "MeshCodec._rmw_flat",
)

# ambiguity budget: a fuzzy call edge that could hit more than this
# many same-named functions is noise, not the device path
MAX_FANOUT = 4

_NUMPY_SYNCS = {"asarray", "array", "copyto"}


def _imports_top(tree: ast.AST, *tops: str) -> bool:
    """True if the module imports any of the given top-level packages
    (``import jax.numpy`` and ``from jax import numpy`` both count as
    ``jax``)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            heads = [a.name.split(".", 1)[0] for a in node.names]
        elif isinstance(node, ast.ImportFrom) and not node.level:
            heads = [(node.module or "").split(".", 1)[0]]
        else:
            continue
        if any(h in tops for h in heads):
            return True
    return False


@register
class DevicePathHostSync(ProjectChecker):
    name = "device-path-host-sync"
    description = ("np.asarray/.item()/.block_until_ready()/bytes() "
                   "transitively reachable from batched launch entry "
                   "points (static form of the "
                   "scalar_calls_on_batched_paths=0 gate)")

    def check_project(self, graph: CallGraph) -> Iterable[Finding]:
        # device plane: where arrays flow
        in_scope = {
            path for path, syms in graph.symbols.items()
            if _imports_top(syms.module.tree, "numpy", "jax")}
        jax_scope = {
            path for path in in_scope
            if _imports_top(graph.symbols[path].module.tree, "jax")}
        roots: list[str] = []
        root_of: dict[str, str] = {}
        for spec in ROOTS:
            for qual in graph.lookup(spec):
                if graph.functions[qual].path in in_scope:
                    roots.append(qual)
                    root_of[qual] = spec
        if not roots:
            return
        # BFS with origin tracking so the finding can say WHICH entry
        # point makes the sync reachable
        seen: dict[str, str] = {}
        stack = [(q, root_of[q]) for q in roots]
        while stack:
            cur, origin = stack.pop()
            if cur in seen:
                continue
            seen[cur] = origin
            for dst, fo in graph.calls.get(cur, {}).items():
                fi = graph.functions.get(dst)
                if (fo <= MAX_FANOUT and dst not in seen
                        and fi is not None and fi.path in in_scope):
                    stack.append((dst, origin))
        for qual, origin in sorted(seen.items()):
            fi = graph.functions.get(qual)
            if fi is None:
                continue
            syms = graph.symbols[fi.path]
            allow_bytes = fi.path in jax_scope
            for node in own_nodes(fi.node):
                if isinstance(node, ast.Call):
                    msg = self._sync_kind(node, syms, allow_bytes)
                    if msg:
                        yield Finding(
                            fi.path, node.lineno, self.name,
                            f"{msg} on the batched device path "
                            f"(reachable from {origin}): forces a "
                            f"device->host sync per call -- keep the "
                            f"batch on device, hoist the hop to the "
                            f"single post-launch materialization, or "
                            f"justify with a disable comment")

    @staticmethod
    def _sync_kind(node: ast.Call, syms,
                   allow_bytes: bool) -> str | None:
        func = node.func
        if isinstance(func, ast.Attribute):
            attr = func.attr
            if attr == "block_until_ready":
                return ".block_until_ready()"
            if attr == "item" and not node.args:
                return ".item()"
            if attr in _NUMPY_SYNCS:
                base = astutil.dotted(func.value)
                if base and syms.expand_alias(
                        base.split(".", 1)[0]) == "numpy":
                    return f"np.{attr}"
            return None
        if isinstance(func, ast.Name):
            if (allow_bytes and func.id == "bytes"
                    and len(node.args) == 1
                    and not isinstance(node.args[0], ast.Constant)):
                return "bytes()"
            if (func.id in _NUMPY_SYNCS
                    and syms.expand_alias(func.id).startswith(
                        "numpy.")):
                return f"np.{func.id}"
        return None
