"""x64-scope: jax_enable_x64 is thread-local-context only.

The PR 1 hazard: flipping ``jax_enable_x64`` globally (at import time
or anywhere else) changes dtype semantics for *every* computation in
the process -- the CRUSH straw2 64-bit hash needs x64, but the EC
GF(2) kernels and everything jitted elsewhere must keep the default.
The sanctioned mechanism is the scoped context manager
(``jax.experimental.enable_x64``), exactly how
``crush/vectorized.py`` wraps its mapper entry points.

Flagged everywhere, with no sanctioned call sites:

* ``<anything>.config.update("jax_enable_x64", ...)`` (covers
  ``jax.config.update`` and ``from jax import config`` forms);
* attribute assignment to ``jax_enable_x64`` (the
  ``jax.config.jax_enable_x64 = True`` back door).
"""

from __future__ import annotations

import ast
from typing import Iterable

from .. import astutil
from ..core import Finding, Module
from ..registry import Checker, register

_FLAG = "jax_enable_x64"


@register
class X64Scope(Checker):
    name = "x64-scope"
    description = ("jax_enable_x64 mutated outside the enable_x64 "
                   "context manager")

    def check(self, module: Module) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                yield from self._check_call(node, module)
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    yield from self._check_target(tgt, node, module)
            elif isinstance(node, ast.AugAssign):
                yield from self._check_target(node.target, node,
                                              module)

    def _check_call(self, node: ast.Call,
                    module: Module) -> Iterable[Finding]:
        name = astutil.dotted(node.func) or ""
        if not (name == "update" or name.endswith("config.update")
                or name.endswith(".update")):
            return
        if not node.args:
            return
        if astutil.const_str(node.args[0]) != _FLAG:
            return
        yield Finding(
            module.path, node.lineno, self.name,
            f"global {_FLAG} flip via {name}(); use the scoped "
            f"jax.experimental.enable_x64 context manager instead")

    def _check_target(self, tgt: ast.AST, node: ast.AST,
                      module: Module) -> Iterable[Finding]:
        if isinstance(tgt, ast.Attribute) and tgt.attr == _FLAG:
            yield Finding(
                module.path, node.lineno, self.name,
                f"direct assignment to {astutil.dotted(tgt)}; use "
                f"the scoped jax.experimental.enable_x64 context "
                f"manager instead")
