"""blocking-under-lock: no synchronous stalls inside lock regions.

The OSD, messenger, and fault injector all run on one asyncio loop;
a ``time.sleep``, raw socket op, or ``Future.result()`` inside a
``with <lock>`` / ``async with <lock>`` region doesn't just stall the
holder -- it wedges every task queued on that lock *and* (being a
blocking call on the loop thread) the whole reactor, which is how a
slow peer turns into a cluster-wide heartbeat storm.

Scoped to ``osd/``, ``msg/`` and ``common/faults.py``.  A context
manager expression whose final identifier contains ``lock`` is
treated as a lock; nested ``def``s inside the region are skipped
(they execute later, not under the lock).
"""

from __future__ import annotations

import ast
from typing import Iterable

from .. import astutil
from ..core import Finding, Module
from ..registry import Checker, register

_SOCKET_METHODS = {"accept", "connect", "connect_ex", "recv",
                   "recvfrom", "recv_into", "listen", "sendall"}
_SOCKET_BASES = {"socket"}


def _is_lock_expr(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Call):
        expr = expr.func            # e.g. `with self._lock_for(pg):`
    leaf = astutil.name_leaf(expr)
    return leaf is not None and "lock" in leaf.lower()


@register
class BlockingUnderLock(Checker):
    name = "blocking-under-lock"
    description = ("time.sleep / socket ops / Future.result() inside "
                   "a lock region in osd/, msg/, common/faults.py")

    def scope(self, module: Module) -> bool:
        p = module.path
        return ("osd/" in p or "msg/" in p
                or p.endswith("common/faults.py"))

    def check(self, module: Module) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            if not any(_is_lock_expr(item.context_expr)
                       for item in node.items):
                continue
            yield from self._scan_region(node, module)

    def _scan_region(self, region: ast.AST,
                     module: Module) -> Iterable[Finding]:
        stack: list[ast.AST] = list(region.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                continue            # runs later, not under the lock
            if isinstance(node, ast.Call):
                yield from self._check_call(node, module)
            stack.extend(ast.iter_child_nodes(node))

    def _check_call(self, node: ast.Call,
                    module: Module) -> Iterable[Finding]:
        name = astutil.dotted(node.func) or ""
        if name == "time.sleep" or name == "sleep":
            yield Finding(
                module.path, node.lineno, self.name,
                "time.sleep() while holding a lock stalls every "
                "waiter and blocks the event loop; sleep outside "
                "the region (or await asyncio.sleep outside it)")
            return
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            base = astutil.dotted(node.func.value) or ""
            if attr == "result" and not node.args:
                yield Finding(
                    module.path, node.lineno, self.name,
                    "Future.result() under a lock blocks the loop "
                    "thread until the future resolves -- and the "
                    "resolver may need this very lock (deadlock); "
                    "await it outside the region")
            elif (attr in _SOCKET_METHODS
                  and (base in _SOCKET_BASES
                       or "sock" in base.lower().rsplit(".", 1)[-1])):
                yield Finding(
                    module.path, node.lineno, self.name,
                    f"socket .{attr}() under a lock: network "
                    f"latency becomes lock hold time for every "
                    f"waiter; do the I/O outside the region")
            elif (attr in ("socket", "create_connection")
                  and base in _SOCKET_BASES):
                yield Finding(
                    module.path, node.lineno, self.name,
                    f"socket.{attr}() under a lock: connection "
                    f"setup blocks all waiters; do it outside the "
                    f"region")
