"""await-under-lock: no peer round trips while holding an asyncio lock.

``blocking-under-lock`` catches synchronous stalls (time.sleep, raw
sockets) inside a lock region; the failure mode it cannot see is the
*asynchronous* one: an ``await`` under an ``async with <lock>`` whose
call chain reaches a peer RPC suspends the holder for a full network
round trip, and every task queued on that lock inherits the wait.
That is exactly how a pipelined op path silently re-serializes -- the
device and the messenger may both be asynchronous, but if the commit
fan-out is awaited under the PG lock, the PG still processes one write
per round trip (the PR-12 write-spine refactor exists because this
rule fired on pg.do_op).

Mechanics: every *async* lock region the call-graph engine collected
(``CallGraph.lock_regions``) is projected through call edges of
fan-out <= 4 with ``spawn=False`` (a task the region only scheduled
does not hold its locks).  If the closure reaches one of the known
round-trip sinks (the OSD fan-out/request APIs, the mon RPC, the
hedged-gather engine), the region is a finding -- one per (region,
sink), anchored at the ``async with`` line.

Scoped to ``osd/``, ``mon/``, ``msg/``.  Deliberate holds (recovery
blocking client ops per round is a correctness choice, not an
accident) carry a ``# lint: disable=await-under-lock -- why`` on the
region line; the suppression is the documentation.
"""

from __future__ import annotations

from typing import Iterable

from ..callgraph import CallGraph
from ..core import Finding
from ..registry import ProjectChecker, register

MAX_FANOUT = 4
_SCOPE = ("osd/", "mon/", "msg/")

# the peer-round-trip sinks: awaiting any of these suspends the caller
# until a remote daemon answers (or a timeout fires).  Named the same
# way device_path.ROOTS names launch entry points -- ``Class.method``
# or a bare function name, resolved against the live symbol table.
SINKS = (
    "OSD.fanout_and_wait",
    "OSD.fanout_staged",
    "OSD._mon_request",
    "OSD._mon_send_failover",
    "HedgedGather.gather_shards",
    "HedgedGather.first_reply",
    "Messenger.send",
    "Connection.send",
)


def _in_scope(path: str) -> bool:
    return any(s in path for s in _SCOPE)


@register
class AwaitUnderLock(ProjectChecker):
    name = "await-under-lock"
    description = ("awaits inside async lock regions in osd/, mon/, "
                   "msg/ that can suspend the holder across a peer "
                   "round trip (interprocedural hold-time rule)")

    def check_project(self, graph: CallGraph) -> Iterable[Finding]:
        sink_of: dict[str, str] = {}
        for spec in SINKS:
            for qual in graph.lookup(spec):
                sink_of[qual] = spec
        if not sink_of:
            return
        # reachability is shared across regions: memoize per callee
        reach_cache: dict[str, set[str]] = {}

        def sinks_from(dst: str) -> set[str]:
            if dst not in reach_cache:
                closure = graph.reachable([dst], max_fanout=MAX_FANOUT,
                                          spawn=False)
                reach_cache[dst] = {sink_of[q] for q in closure
                                    if q in sink_of}
            return reach_cache[dst]

        for region in graph.lock_regions:
            if not region.is_async or not _in_scope(region.path):
                continue
            hit: dict[str, str] = {}       # sink spec -> via callee
            for dst, fo in region.callees:
                if fo > MAX_FANOUT:
                    continue
                fi = graph.functions.get(dst)
                if fi is None or not fi.is_async:
                    # a sync callee cannot await; it can only *create*
                    # a coroutine, and creating is not suspending
                    continue
                for spec in sinks_from(dst):
                    hit.setdefault(spec, dst)
            for spec in sorted(hit):
                via = graph.functions[hit[spec]].local
                yield Finding(
                    region.path, region.line, self.name,
                    f"'{region.locks[0]}' is held across a peer "
                    f"round trip: the region awaits {via}(), which "
                    f"reaches {spec} -- every task queued on the "
                    f"lock inherits the RTT and the op path "
                    f"re-serializes; move the wait outside the "
                    f"region or justify with a disable comment")
