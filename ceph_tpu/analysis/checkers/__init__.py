"""Built-in project rules; importing this package registers them."""

from . import (        # noqa: F401
    blocking_under_lock,
    config_schema,
    dropped_task,
    hole_sentinel,
    jit_stability,
    perf_coherence,
    tracer_safety,
    x64_scope,
)
