"""Built-in project rules; importing this package registers them."""

from . import (        # noqa: F401
    await_snapshot,
    await_under_lock,
    blocking_under_lock,
    config_schema,
    cross_daemon_state,
    counter_coverage,
    denc_symmetry,
    device_path,
    donated_aliasing,
    dropped_task,
    hole_sentinel,
    hot_config,
    jit_stability,
    lock_order,
    perf_coherence,
    tracer_safety,
    wire_safety,
    x64_scope,
)
