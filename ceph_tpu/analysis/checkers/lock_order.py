"""lock-order: one global acquisition order across call chains.

``blocking-under-lock`` sees a single function; the deadlock class it
cannot see is *ordering*: coroutine 1 holds lock A and awaits a call
chain that takes lock B, while coroutine 2 holds B and reaches for A
-- the acquisitions live in different functions, often different
modules, and each region looks innocent in isolation.  This rule
projects every lock region through the call graph: while region R
holds lock L, the locks acquired by R's nested ``with`` blocks plus
every lock region owned by a function reachable from R's call sites
(fan-out <= 4) form "L is held while X is taken" edges.  A cycle in
that edge graph is a lock-order inversion.

Lock identity is best-effort by name: ``self._foo_lock`` in class C
is ``C._foo_lock`` everywhere, so different *instances* of one class
collapse into one lock -- which is exactly the granularity a global
order is defined over.  Self-edges (L held while L is taken) are
skipped: across two instances of a class that is legal, and the
name-based identity cannot tell instances apart.

Scoped to ``osd/``, ``mon/``, ``msg/`` -- the daemons that share the
event loop and take each other's locks across message handlers.
"""

from __future__ import annotations

from typing import Iterable

from ..callgraph import CallGraph
from ..core import Finding
from ..registry import ProjectChecker, register

MAX_FANOUT = 4
_SCOPE = ("osd/", "mon/", "msg/")


def _in_scope(path: str) -> bool:
    return any(s in path for s in _SCOPE)


@register
class LockOrder(ProjectChecker):
    name = "lock-order"
    description = ("conflicting lock-acquisition orders across call "
                   "chains in osd/, mon/, msg/ (interprocedural "
                   "deadlock ordering)")

    def check_project(self, graph: CallGraph) -> Iterable[Finding]:
        regions = [r for r in graph.lock_regions
                   if _in_scope(r.path)]
        if not regions:
            return
        # locks acquired anywhere inside a function (for closures)
        owner_locks: dict[str, list[str]] = {}
        for r in graph.lock_regions:
            owner_locks.setdefault(r.owner, []).extend(r.locks)
        # held-while-acquiring edges with a witness site each
        edges: dict[tuple[str, str], tuple[str, int]] = {}

        def add(a: str, b: str, path: str, line: int) -> None:
            if a != b:
                edges.setdefault((a, b), (path, line))

        for r in regions:
            inner = list(r.inner_locks)
            callee_set = set()
            for dst, fo in r.callees:
                if fo <= MAX_FANOUT:
                    callee_set.add(dst)
            if callee_set:
                # spawn=False: a lock taken on a task the region only
                # *scheduled* is not taken while this lock is held
                for qual in graph.reachable(callee_set,
                                            max_fanout=MAX_FANOUT,
                                            spawn=False):
                    inner.extend(owner_locks.get(qual, ()))
            # multi-item `with a, b:` acquires in item order
            for i, a in enumerate(r.locks):
                for b in r.locks[i + 1:]:
                    add(a, b, r.path, r.line)
            for held in r.locks:
                for taken in inner:
                    add(held, taken, r.path, r.line)

        adj: dict[str, set[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)
        for a, b in sorted(edges):
            if a < b and (b, a) in edges:
                pa, la = edges[(a, b)]
                pb, lb = edges[(b, a)]
                yield Finding(
                    pa, la, self.name,
                    f"lock-order inversion: '{a}' is held while "
                    f"'{b}' is taken here, but '{b}' is held while "
                    f"'{a}' is taken at {pb}:{lb} -- two coroutines "
                    f"interleaving these chains deadlock; pick one "
                    f"global order")
        # longer cycles with no pairwise inversion (A->B->C->A)
        for cycle in _simple_cycles(adj):
            if len(cycle) < 3:
                continue
            a, b = cycle[0], cycle[1]
            path, line = edges[(a, b)]
            chain = " -> ".join(cycle + [cycle[0]])
            yield Finding(
                path, line, self.name,
                f"lock-order cycle: {chain} -- the acquisitions live "
                f"in different functions but close a ring; pick one "
                f"global order")


def _simple_cycles(adj: dict[str, set[str]]) -> list[list[str]]:
    """Minimal deterministic cycle enumeration: one canonical cycle
    per strongly connected component of size >= 3."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in sorted(adj.get(v, ())):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) >= 3:
                sccs.append(sorted(comp))
    for v in sorted(adj):
        if v not in index:
            strongconnect(v)
    out = []
    for comp in sccs:
        members = set(comp)
        # walk a cycle within the component, greedily smallest-first
        cycle = [comp[0]]
        seen = {comp[0]}
        cur = comp[0]
        while True:
            nxts = sorted(n for n in adj.get(cur, ())
                          if n in members)
            if not nxts:
                break
            nxt = next((n for n in nxts if n not in seen), nxts[0])
            if nxt in seen:
                if nxt == cycle[0]:
                    out.append(cycle)
                break
            cycle.append(nxt)
            seen.add(nxt)
            cur = nxt
    return out
