"""await-invalidates-snapshot: check-then-act across a suspension.

The single-asyncio-loop invariant every daemon leans on: between two
awaits, nobody else runs, so a local snapshot of shared mutable state
(``pg = self.pgs.get(pgid)``, ``conn = self.conns[addr]``) stays
coherent for straight-line code.  Every ``await`` is the hole in that
argument -- the loop runs peering, a kill, a revive, an epoch bump --
and in the multiprocess swarm the hole widens to "always".  The race
shape is bind -> await -> use:

    osd = self.osds[index]
    await something()          # the loop may remove/replace the osd
    osd.apply(...)             # acts on a snapshot of the past

Mechanics, per async function in ``osd/``, ``mon/``, ``loadgen/``:

* a *snapshot binding* is ``x = <root>[k]`` / ``x = <root>.get(k)``
  where the root is ``self``-rooted shared state or a module-level
  mutable global (the shared-state census's own definition);
* an ``await`` between the binding and a later use *suspends* when
  its operand is not a call, does not resolve in the project, or its
  fan-out <= 4 call-graph closure (``spawn=False``, the
  await-under-lock projection) contains a function that itself awaits
  outside the project -- sleep, a stream read, a future.  A call
  whose whole closure is project-local synchronous code provably
  cannot yield the loop and is exempt;
* re-binding the name after the await clears it (that IS the fix:
  re-read), and a lock region spanning both the binding and the use
  exempts the window (the mutators that matter serialize on the
  guarding lock).

Line-ordered, single-function approximation: a loop that carries a
snapshot across its back edge into the next iteration's await is not
modeled, and neither is a snapshot handed to a callee.  Both
directions are conservative-quiet, never noisy.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .. import astutil
from ..callgraph import (CallGraph, _Resolver, is_lock_name,
                         own_nodes)
from ..core import Finding
from ..registry import ProjectChecker, register

MAX_FANOUT = 4
_SCOPE = ("osd/", "mon/", "loadgen/")


def _in_scope(path: str) -> bool:
    return any(s in path for s in _SCOPE)


def _module_globals(tree: ast.AST) -> set[str]:
    """Names of module-level mutable containers (dict/list/set
    literals or mutable-builtin calls) -- snapshot roots."""
    out: set[str] = set()
    for stmt in tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            v = stmt.value
            if isinstance(v, (ast.Dict, ast.List, ast.Set)):
                out.add(stmt.targets[0].id)
            elif isinstance(v, ast.Call):
                leaf = astutil.name_leaf(v.func)
                if leaf in ("dict", "list", "set", "defaultdict",
                            "OrderedDict"):
                    out.add(stmt.targets[0].id)
    return out


def _snapshot_source(value: ast.AST,
                     mod_globals: set[str]) -> str | None:
    """Dotted render of the shared container a binding snapshots
    from, or None when the binding is not a snapshot."""
    if isinstance(value, ast.Subscript):
        base = value.value
    elif (isinstance(value, ast.Call)
          and isinstance(value.func, ast.Attribute)
          and value.func.attr == "get" and value.args):
        base = value.func.value
    else:
        return None
    d = astutil.dotted(base)
    if d is None:
        return None
    head = d.split(".", 1)[0]
    if head == "self" and "." in d:
        return d
    if head in mod_globals and head == d:
        return d
    return None


class _SuspensionOracle:
    """Does awaiting this expression actually yield the event loop?"""

    def __init__(self, graph: CallGraph) -> None:
        self.graph = graph
        self._primitive: dict[str, bool] = {}
        self._closure: dict[tuple, bool] = {}

    def _has_primitive_await(self, qual: str) -> bool:
        """The function awaits something the project cannot resolve
        (sleep, a stream, a bare future) -- a true suspension point."""
        if qual in self._primitive:
            return self._primitive[qual]
        fi = self.graph.functions.get(qual)
        hit = False
        if fi is not None:
            syms = self.graph.symbols.get(fi.path)
            resolver = _Resolver(self.graph, syms) if syms else None
            for node in own_nodes(fi.node):
                if not isinstance(node, ast.Await):
                    continue
                v = node.value
                if not isinstance(v, ast.Call) or resolver is None:
                    hit = True
                    break
                targets = [d for d, fo in resolver.resolve_call(
                    v, fi.cls, []) if fo <= MAX_FANOUT]
                if not targets:
                    hit = True
                    break
        self._primitive[qual] = hit
        return hit

    def suspends(self, await_node: ast.Await, cls: str | None,
                 resolver: _Resolver) -> bool:
        v = await_node.value
        if not isinstance(v, ast.Call):
            return True                      # await fut / await x
        targets = tuple(sorted(
            d for d, fo in resolver.resolve_call(v, cls, [])
            if fo <= MAX_FANOUT))
        if not targets:
            return True                      # unknown callee
        if targets not in self._closure:
            closure = self.graph.reachable(
                list(targets), max_fanout=MAX_FANOUT, spawn=False)
            self._closure[targets] = any(
                self._has_primitive_await(q) for q in closure)
        return self._closure[targets]


def _bind_lines(root: ast.AST, name: str) -> list[int]:
    """Every line that (re)binds `name` in this function."""
    out = []
    for node in own_nodes(root):
        tgts = []
        if isinstance(node, (ast.Assign,)):
            tgts = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign,
                               ast.NamedExpr)):
            tgts = [node.target]
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            tgts = [node.target]
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            tgts = [i.optional_vars for i in node.items
                    if i.optional_vars is not None]
        for t in tgts:
            for sub in ast.walk(t):
                if isinstance(sub, ast.Name) and sub.id == name:
                    out.append(node.lineno)
    return sorted(set(out))


def _lock_spans(root: ast.AST) -> list[tuple[int, int]]:
    out = []
    for node in own_nodes(root):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        for item in node.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):
                expr = expr.func
            if is_lock_name(astutil.name_leaf(expr)):
                out.append((node.lineno,
                            node.end_lineno or node.lineno))
                break
    return out


def snapshot_races(graph: CallGraph) -> list[dict]:
    """Every bind -> suspending-await -> use window in scope.  Pure
    data; check_project turns these into findings."""
    oracle = _SuspensionOracle(graph)
    out: list[dict] = []
    for path in sorted(graph.symbols):
        if not _in_scope(path):
            continue
        syms = graph.symbols[path]
        resolver = _Resolver(graph, syms)
        mod_globals = _module_globals(syms.module.tree)
        for fi in syms.functions:
            if not fi.is_async:
                continue
            root = fi.node
            awaits = [n for n in own_nodes(root)
                      if isinstance(n, ast.Await)]
            if not awaits:
                continue
            # (lineno, end_lineno) spans: a "use" inside the await
            # expression's own argument list evaluates BEFORE the
            # suspension, so the hazard needs span_end < use
            susp_spans = sorted(
                (n.lineno, n.end_lineno or n.lineno) for n in awaits
                if oracle.suspends(n, fi.cls, resolver))
            if not susp_spans:
                continue
            locks = _lock_spans(root)
            bindings = []
            for node in own_nodes(root):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    continue
                src = _snapshot_source(node.value, mod_globals)
                if src is not None:
                    bindings.append((node.targets[0].id,
                                     node.lineno, src))
            for name, bline, src in bindings:
                rebinds = [ln for ln in _bind_lines(root, name)
                           if ln != bline]
                uses = sorted(
                    n.lineno for n in own_nodes(root)
                    if isinstance(n, ast.Name) and n.id == name
                    and isinstance(n.ctx, ast.Load)
                    and n.lineno > bline)
                race = None
                for use in uses:
                    last_bind = max([bline] + [ln for ln in rebinds
                                               if ln <= use])
                    if last_bind != bline:
                        break      # re-read: later uses are fresh
                    aw = next((lo for lo, hi in susp_spans
                               if last_bind < lo and hi < use), None)
                    if aw is None:
                        continue
                    if any(lo <= last_bind and use <= hi
                           for lo, hi in locks):
                        continue   # the guarding lock spans the window
                    race = {"path": path, "line": use,
                            "function": fi.local, "local": name,
                            "source": src, "bind_line": bline,
                            "await_line": aw, "use_line": use}
                    break
                if race is not None:
                    out.append(race)
    return out


@register
class AwaitInvalidatesSnapshot(ProjectChecker):
    name = "await-invalidates-snapshot"
    description = ("a local snapshot of shared mutable state used "
                   "after an await that can yield the event loop, "
                   "without a re-read or a spanning lock (check-"
                   "then-act race in osd/, mon/, loadgen/)")

    def check_project(self, graph: CallGraph) -> Iterable[Finding]:
        for r in snapshot_races(graph):
            yield Finding(
                r["path"], r["line"], self.name,
                f"'{r['local']}' snapshots {r['source']} at line "
                f"{r['bind_line']} but is used after the await at "
                f"line {r['await_line']} -- the event loop may have "
                f"mutated the source in between (await span "
                f"{r['bind_line']}->{r['use_line']}); re-read it, "
                f"hold the guarding lock across the window, or "
                f"justify the stale use")
