"""tracer-safety: no host syncs inside jitted/pallas code.

Inside a function being traced by ``jax.jit`` or ``pallas_call``,
``np.asarray(...)``, ``.item()``, ``float()/int()`` on traced values,
and Python ``if`` on tracer data either fail at trace time or -- worse
-- silently force a device->host sync per call, which is exactly the
per-op stall the PR 3 placement cache exists to avoid.

Scoped to the accelerator hot paths (``ops/`` and
``crush/vectorized.py``).  Traced scopes are found three ways:

* functions decorated ``@jax.jit`` / ``@partial(jax.jit, ...)``
  (static_argnames/static_argnums are honored: branching on a static
  arg is Python-level and fine, the vectorized mapper's
  ``if self.leaf`` idiom);
* local functions passed by name to ``jax.jit(f)`` / ``pallas_call``;
* kernel *builders* whose call result feeds ``pallas_call(...)`` --
  their nested ``def kernel(...)`` bodies are the traced code.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .. import astutil
from ..core import Finding, Module
from ..registry import Checker, register

_JIT_NAMES = {"jax.jit", "jit"}
_PALLAS_NAMES = {"pallas_call", "pl.pallas_call",
                 "pltpu.pallas_call"}
_PARTIAL_NAMES = {"partial", "functools.partial"}
_HOST_CALLS = {"np.asarray", "np.array", "numpy.asarray",
               "numpy.array", "onp.asarray", "onp.array",
               "jax.device_get", "device_get"}
_HOST_METHODS = {"item", "tolist"}
_HOST_BUILTINS = {"float", "int", "bool"}


def _jit_static_names(call: ast.Call,
                      params: list[str]) -> set[str]:
    """Parameter names made static by a jit(...) call's kwargs."""
    static: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for el in ast.walk(kw.value):
                s = astutil.const_str(el)
                if s is not None:
                    static.add(s)
        elif kw.arg == "static_argnums":
            for el in ast.walk(kw.value):
                v = astutil.int_value(el)
                if v is not None and 0 <= v < len(params):
                    static.add(params[v])
    return static


def _params(fn: ast.AST) -> list[str]:
    a = fn.args
    return ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
            + [p.arg for p in a.kwonlyargs])


def _is_jit_target(call_func: ast.AST) -> bool:
    return (astutil.dotted(call_func) or "") in _JIT_NAMES


@register
class TracerSafety(Checker):
    name = "tracer-safety"
    description = ("host-sync calls or if-on-tracer inside jitted / "
                   "pallas code in the accelerator hot paths")

    def scope(self, module: Module) -> bool:
        p = module.path
        return ("ops/" in p or p.endswith("crush/vectorized.py")
                or "ops\\" in p)

    def check(self, module: Module) -> Iterable[Finding]:
        astutil.attach_parents(module.tree)
        defs: dict[str, list[ast.AST]] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)

        # (fn, tracer_params, include_own_body)
        traced: list[tuple[ast.AST, set[str], bool]] = []

        for fns in defs.values():
            for fn in fns:
                static = self._decorator_static(fn)
                if static is not None:
                    tracers = set(_params(fn)) - static
                    traced.append((fn, tracers, True))

        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.dotted(node.func) or ""
            if not (name in _JIT_NAMES or name in _PALLAS_NAMES):
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Name) and arg.id in defs:
                for fn in defs[arg.id]:
                    static = _jit_static_names(node, _params(fn))
                    traced.append((fn, set(_params(fn)) - static,
                                   True))
            elif (isinstance(arg, ast.Call)
                  and isinstance(arg.func, ast.Name)
                  and arg.func.id in defs):
                # builder pattern: pallas_call(make_kernel(...)) --
                # the builder's params are config, its nested defs
                # are the traced kernels
                for fn in defs[arg.func.id]:
                    traced.append((fn, set(), False))

        seen: set[int] = set()
        for fn, tracers, own_body in traced:
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            yield from self._scan(fn, tracers, own_body, module)

    def _decorator_static(self, fn: ast.AST) -> set[str] | None:
        """If `fn` is jit-decorated, its static param names; else
        None."""
        for dec in fn.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = astutil.dotted(target) or ""
            if name in _JIT_NAMES:
                if isinstance(dec, ast.Call):
                    return _jit_static_names(dec, _params(fn))
                return set()
            if (isinstance(dec, ast.Call) and name in _PARTIAL_NAMES
                    and dec.args
                    and (astutil.dotted(dec.args[0]) or "")
                    in _JIT_NAMES):
                return _jit_static_names(dec, _params(fn))
        return None

    def _scan(self, fn: ast.AST, tracers: set[str], own_body: bool,
              module: Module) -> Iterable[Finding]:
        stack: list[tuple[ast.AST, set[str]]] = []
        if own_body:
            stack.append((fn, set(tracers)))
        else:
            for node in ast.walk(fn):
                if (node is not fn
                        and isinstance(node, (ast.FunctionDef,
                                              ast.AsyncFunctionDef))):
                    stack.append((node, set(_params(node))))
        emitted: set[tuple[int, str]] = set()
        for scope_fn, scope_tracers in stack:
            # nested defs (while_loop bodies etc.) run traced too;
            # their params are tracers
            all_tracers = set(scope_tracers)
            for node in ast.walk(scope_fn):
                if (node is not scope_fn
                        and isinstance(node, (ast.FunctionDef,
                                              ast.AsyncFunctionDef))):
                    all_tracers |= set(_params(node))
            for node in ast.walk(scope_fn):
                for f in self._scan_node(node, all_tracers, module):
                    key = (f.line, f.message)
                    if key not in emitted:
                        emitted.add(key)
                        yield f

    def _scan_node(self, node: ast.AST, tracers: set[str],
                   module: Module) -> Iterable[Finding]:
        if isinstance(node, ast.Call):
            name = astutil.dotted(node.func) or ""
            if name in _HOST_CALLS:
                yield Finding(
                    module.path, node.lineno, self.name,
                    f"host-sync call {name}() inside traced code; "
                    f"it blocks on device->host transfer every "
                    f"invocation (move it outside the jitted scope)")
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr in _HOST_METHODS
                  and not node.args):
                yield Finding(
                    module.path, node.lineno, self.name,
                    f".{node.func.attr}() inside traced code forces "
                    f"a host sync; keep values on device")
            elif (isinstance(node.func, ast.Name)
                  and node.func.id in _HOST_BUILTINS
                  and len(node.args) == 1
                  and not isinstance(node.args[0], ast.Constant)):
                yield Finding(
                    module.path, node.lineno, self.name,
                    f"{node.func.id}() on a traced value concretizes "
                    f"it (ConcretizationTypeError or a silent host "
                    f"sync); use jnp dtype casts instead")
        elif isinstance(node, (ast.If, ast.While, ast.IfExp)):
            test = node.test
            if self._is_none_test(test):
                return
            names = astutil.names_in(test)
            if names and names <= tracers:
                yield Finding(
                    module.path, node.lineno, self.name,
                    f"Python branch on traced value(s) "
                    f"{', '.join(sorted(names))}; use jnp.where / "
                    f"lax.cond, or mark the argument static")

    @staticmethod
    def _is_none_test(test: ast.AST) -> bool:
        """`x is None` / `x is not None` branches are Python-level
        optionality, not tracer data flow."""
        return (isinstance(test, ast.Compare)
                and len(test.ops) == 1
                and isinstance(test.ops[0], (ast.Is, ast.IsNot)))
