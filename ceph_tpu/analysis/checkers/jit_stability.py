"""jit-stability: jitted callables must be built once and reused.

``jax.jit`` keys its compile cache on the *callable object* plus the
static argument values.  Two project-shaped ways to defeat it:

* constructing the jit inside a loop (a fresh callable every
  iteration -> recompile every iteration -- the per-epoch recompile
  hazard the PR 3 placement cache exists to amortize);
* jitting a method without marking ``self`` static: each tracer-typed
  ``self`` either fails (unhashable) or retraces per instance.  The
  vectorized mapper's ``@partial(jax.jit,
  static_argnames=("self", ...))`` is the sanctioned shape.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .. import astutil
from ..core import Finding, Module
from ..registry import Checker, register

_JIT_NAMES = {"jax.jit", "jit"}
_PARTIAL_NAMES = {"partial", "functools.partial"}


def _is_jit_call(node: ast.Call) -> bool:
    name = astutil.dotted(node.func) or ""
    if name in _JIT_NAMES:
        return True
    # partial(jax.jit, ...) used as a value (not a decorator)
    return (name in _PARTIAL_NAMES and node.args
            and (astutil.dotted(node.args[0]) or "") in _JIT_NAMES)


def _static_names(call: ast.Call) -> set[str]:
    out: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for el in ast.walk(kw.value):
                s = astutil.const_str(el)
                if s is not None:
                    out.add(s)
        elif kw.arg == "static_argnums":
            for el in ast.walk(kw.value):
                v = astutil.int_value(el)
                if v is not None:
                    out.add(str(v))
    return out


@register
class JitStability(Checker):
    name = "jit-stability"
    description = ("jax.jit built inside a loop, or a method jitted "
                   "without static self (recompile hazards)")

    def check(self, module: Module) -> Iterable[Finding]:
        astutil.attach_parents(module.tree)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call) and _is_jit_call(node):
                yield from self._check_loop(node, module)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                yield from self._check_method(node, module)

    def _check_loop(self, node: ast.Call,
                    module: Module) -> Iterable[Finding]:
        fn = astutil.enclosing_function(node)
        for anc in astutil.ancestors(node):
            if anc is fn:
                break
            if isinstance(anc, (ast.For, ast.AsyncFor, ast.While)):
                yield Finding(
                    module.path, node.lineno, self.name,
                    "jax.jit constructed inside a loop: a fresh "
                    "callable per iteration misses the compile "
                    "cache and recompiles every time; hoist the "
                    "jitted function out of the loop")
                return

    def _check_method(self, fn: ast.AST,
                      module: Module) -> Iterable[Finding]:
        params = [a.arg for a in fn.args.args]
        if not params or params[0] != "self":
            return
        for dec in fn.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = astutil.dotted(target) or ""
            static: set[str] | None = None
            if name in _JIT_NAMES:
                static = (_static_names(dec)
                          if isinstance(dec, ast.Call) else set())
            elif (isinstance(dec, ast.Call)
                  and name in _PARTIAL_NAMES and dec.args
                  and (astutil.dotted(dec.args[0]) or "")
                  in _JIT_NAMES):
                static = _static_names(dec)
            if static is None:
                continue
            if "self" not in static and "0" not in static:
                yield Finding(
                    module.path, fn.lineno, self.name,
                    f"method {fn.name}() jitted without "
                    f"static_argnames=('self', ...): self is traced "
                    f"(unhashable / retrace per call); mark it "
                    f"static as the vectorized mapper does")
