"""dropped-task: fire-and-forget asyncio tasks must not die silently.

A bare ``asyncio.create_task(...)`` / ``ensure_future(...)`` statement
drops the Task object on the floor: its exception is never retrieved
(the failure surfaces, at best, as a "Task exception was never
retrieved" stderr line long after the fact) and CPython keeps only a
weak reference to running tasks, so the garbage collector may cancel
it mid-flight.  Every daemon loop here learned this the hard way --
the OSD/mgr/monitor all route spawns through ``make_task_tracker`` or
keep the handle on ``self``.

Compliant forms: assign the result (to a name, attribute, or through
a tracker like ``self._track(...)``), await it, or chain an immediate
``.add_done_callback(...)``.

Beyond the raw asyncio spawners, TASK_ROOTS names project APIs that
RETURN a live task the caller must own -- ``OSD.start_request`` hands
back ``(tid, task)`` and the HedgedGather engine is the one place
that cancels AND reaps those sub-reads; a bare ``start_request(...)``
statement is a sub-read nobody will ever cancel, whose late reply
nobody will ever drain.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .. import astutil
from ..core import Finding, Module
from ..registry import Checker, register

_SPAWNERS = {"create_task", "ensure_future"}

# task-returning project APIs: the result carries a live task (or
# reply waiters) the caller owns.  start_request: dropping the tuple
# orphans the sub-read task (HedgedGather is the intended owner).
# fanout_staged: the returned (tid, future) waiters ARE the commit
# acks of the pipelined write spine -- a bare call stages sends whose
# replies nobody ever drains (wedged waiters).  arm_flush_window: the
# sub-op pipe's flush-window coroutine; unowned, the staged flush
# never ships.
TASK_ROOTS = {"start_request", "fanout_staged", "arm_flush_window"}


@register
class DroppedTask(Checker):
    name = "dropped-task"
    description = ("asyncio create_task/ensure_future result dropped "
                   "without a done-callback (silent task death)")

    def check(self, module: Module) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Call)):
                continue
            leaf = astutil.name_leaf(node.value.func)
            if leaf in _SPAWNERS:
                yield Finding(
                    module.path, node.lineno, self.name,
                    f"{leaf}() result dropped: the task's exception is "
                    f"never retrieved and the GC may cancel it "
                    f"mid-flight; keep a reference (tracker/attribute) "
                    f"or attach a done-callback")
            elif leaf in TASK_ROOTS:
                yield Finding(
                    module.path, node.lineno, self.name,
                    f"{leaf}() result dropped: it returns a live "
                    f"sub-read task the caller owns -- unowned, it is "
                    f"never cancelled or reaped and its late reply is "
                    f"never drained (the HedgedGather engine is the "
                    f"intended owner on the read spine)")
