"""dropped-task: fire-and-forget asyncio tasks must not die silently.

A bare ``asyncio.create_task(...)`` / ``ensure_future(...)`` statement
drops the Task object on the floor: its exception is never retrieved
(the failure surfaces, at best, as a "Task exception was never
retrieved" stderr line long after the fact) and CPython keeps only a
weak reference to running tasks, so the garbage collector may cancel
it mid-flight.  Every daemon loop here learned this the hard way --
the OSD/mgr/monitor all route spawns through ``make_task_tracker`` or
keep the handle on ``self``.

Compliant forms: assign the result (to a name, attribute, or through
a tracker like ``self._track(...)``), await it, or chain an immediate
``.add_done_callback(...)``.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .. import astutil
from ..core import Finding, Module
from ..registry import Checker, register

_SPAWNERS = {"create_task", "ensure_future"}


@register
class DroppedTask(Checker):
    name = "dropped-task"
    description = ("asyncio create_task/ensure_future result dropped "
                   "without a done-callback (silent task death)")

    def check(self, module: Module) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Call)):
                continue
            leaf = astutil.name_leaf(node.value.func)
            if leaf in _SPAWNERS:
                yield Finding(
                    module.path, node.lineno, self.name,
                    f"{leaf}() result dropped: the task's exception is "
                    f"never retrieved and the GC may cancel it "
                    f"mid-flight; keep a reference (tracker/attribute) "
                    f"or attach a done-callback")
