"""config-schema: every config key read must be declared somewhere.

``ConfigProxy`` raises on unknown options, but the daemons' hot paths
read plain dicts (``self.config.get("osd_...", default)``) which
auto-absolve typos: a misspelled key silently returns the inline
default forever, and a knob added in one module but never declared in
``DEFAULT_SCHEMA`` (or a daemon's defaults dict) can never be set via
``config set`` / central config push -- it looks tunable and is not.

Declarations, collected tree-wide (two-pass, like perf-coherence):

* ``Option("name", ...)`` constructor calls (the typed schema);
* string keys of dict literals assigned to a ``config``-named target
  (the per-daemon defaults tables: ``self.config = {...}``);
* the live ``ceph_tpu.common.config.DEFAULT_SCHEMA``, when importable,
  so partial-tree runs (``lint --changed`` on one dirty file) don't
  false-positive on keys declared in an un-linted module.

Reads: ``X.get("some_key")`` / ``X["some_key"]`` (Load context) where
the receiver's leaf name is ``conf``/``config``/``cfg`` and the key
looks like an option name (snake_case with at least one underscore --
single words like ``events`` on unrelated dicts that happen to be
called ``config`` are out of scope by design).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from .. import astutil
from ..core import Finding, Module, Project
from ..registry import Checker, register

_RECEIVERS = {"conf", "config", "cfg"}
_KEY_RE = re.compile(r"^[a-z][a-z0-9]*(?:_[a-z0-9]+)+$")


def _schema_keys() -> set[str]:
    """Names declared by the live typed schema, best-effort."""
    try:
        from ...common.config import DEFAULT_SCHEMA
    except Exception:
        return set()
    return {o.name for o in DEFAULT_SCHEMA}


def _config_target(targets: list[ast.AST]) -> bool:
    """Is any assignment target a config defaults table by name?"""
    for t in targets:
        leaf = astutil.name_leaf(t)
        if leaf is not None and "config" in leaf.lower():
            return True
    return False


@register
class ConfigSchema(Checker):
    name = "config-schema"
    description = ("config keys read via conf/config get()/[] that "
                   "no Option() schema or defaults table declares")

    def __init__(self) -> None:
        self._declared: set[str] = set()
        # key -> list of (path, line) read sites
        self._reads: dict[str, list[tuple[str, int]]] = {}

    def check(self, module: Module) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            # declarations: Option("name", ...)
            if isinstance(node, ast.Call) \
                    and astutil.name_leaf(node.func) == "Option" \
                    and node.args:
                key = astutil.const_str(node.args[0])
                if key is not None:
                    self._declared.add(key)
            # declarations: <...config...> = {"key": default, ...}
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Dict) \
                    and _config_target(node.targets):
                for k in node.value.keys:
                    if k is None:          # **spread entry
                        continue
                    key = astutil.const_str(k)
                    if key is not None:
                        self._declared.add(key)
            # reads: conf.get("key"[, default])
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "get" \
                    and node.args:
                if astutil.name_leaf(node.func.value) in _RECEIVERS:
                    self._note_read(module, node, node.args[0])
            # reads: conf["key"] in Load context
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Load) \
                    and astutil.name_leaf(node.value) in _RECEIVERS:
                self._note_read(module, node, node.slice)
        return ()

    def _note_read(self, module: Module, node: ast.AST,
                   key_node: ast.AST) -> None:
        key = astutil.const_str(key_node)
        if key is not None and _KEY_RE.match(key):
            self._reads.setdefault(key, []).append(
                (module.path, node.lineno))

    def finalize(self, project: Project) -> Iterable[Finding]:
        declared, self._declared = self._declared, set()
        reads, self._reads = self._reads, {}
        declared |= _schema_keys()
        for key in sorted(reads):
            if key in declared:
                continue
            for path, line in reads[key]:
                yield Finding(
                    path, line, self.name,
                    f"config key '{key}' is read here but no "
                    f"Option() schema entry or config defaults "
                    f"table declares it: typos read as the inline "
                    f"default forever and the knob cannot be set at "
                    f"runtime")
