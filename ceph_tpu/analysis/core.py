"""Parse-once module model, suppressions, baseline, and the runner.

One ``ast.parse`` per file feeds every rule (the tree is shared via
``Module``); findings are plain ``path:line rule message`` records.

Suppression layers, innermost first:

* inline ``# lint: disable=<rule>[,<rule>...]`` (or bare ``disable``
  for all rules) on the flagged line, or on a standalone comment line
  immediately above it;
* a checked-in baseline file (``tools/lint_baseline.txt``) keyed by
  ``path::rule::message`` -- line-number free, so findings survive
  unrelated edits but a *new* instance of an old finding still fires.
"""

from __future__ import annotations

import ast
import io
import os
import time
import tokenize
from dataclasses import dataclass, field
from typing import Iterable

from .registry import ProjectChecker, get_checkers

_SUPPRESS_PREFIX = "lint:"
_SKIP_DIRS = {"__pycache__", ".git", ".hg", "node_modules"}


@dataclass(frozen=True, order=True)
class Finding:
    path: str          # display path (posix, relative to the root)
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"


@dataclass
class Module:
    """One parsed source file shared by all checkers."""

    path: str                     # display path (posix)
    abspath: str
    source: str
    tree: ast.AST
    # line -> set of suppressed rule names; "*" suppresses all rules
    suppressions: dict[int, set[str]] = field(default_factory=dict)

    @classmethod
    def parse(cls, abspath: str, display: str) -> "Module":
        with open(abspath, encoding="utf-8", errors="replace") as f:
            source = f.read()
        tree = ast.parse(source, filename=display)
        mod = cls(path=display, abspath=abspath, source=source,
                  tree=tree)
        mod.suppressions = _scan_suppressions(source)
        return mod

    def suppressed(self, finding: Finding) -> bool:
        rules = self.suppressions.get(finding.line)
        return bool(rules) and ("*" in rules
                                or finding.rule in rules)


@dataclass
class Project:
    modules: list[Module]
    # resolved CallGraph, set by run() when a ProjectChecker ran (or
    # on demand via graph()); callers may also build it themselves
    callgraph: object | None = None

    def by_path(self) -> dict[str, Module]:
        return {m.path: m for m in self.modules}

    def graph(self):
        """The interprocedural CallGraph, built on first use."""
        if self.callgraph is None:
            from .callgraph import CallGraph
            self.callgraph = CallGraph.build(self)
        return self.callgraph


def _scan_suppressions(source: str) -> dict[int, set[str]]:
    """Comment tokens of the form ``# lint: disable[=r1,r2]``.

    A trailing comment suppresses its own line; a standalone comment
    line suppresses itself and the next line (so the directive can sit
    above long expressions).
    """
    out: dict[int, set[str]] = {}
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return out
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        text = tok.string.lstrip("#").strip()
        if not text.startswith(_SUPPRESS_PREFIX):
            continue
        directive = text[len(_SUPPRESS_PREFIX):].strip()
        if not directive.startswith("disable"):
            continue
        # "disable=<rules> -- why": the justification rides the
        # directive so the hop and its reason live on one line
        rest = directive[len("disable"):].split("--", 1)[0].strip()
        if rest.startswith("="):
            rules = {r.strip() for r in rest[1:].split(",")
                     if r.strip()}
        elif rest:
            continue               # e.g. "disablefoo": not a directive
        else:
            rules = {"*"}
        line = tok.start[0]
        out.setdefault(line, set()).update(rules)
        if tok.line.strip().startswith("#"):     # standalone comment
            out.setdefault(line + 1, set()).update(rules)
    return out


# -- file collection --------------------------------------------------------

def collect_files(paths: Iterable[str],
                  root: str | None = None) -> list[tuple[str, str]]:
    """Expand path arguments into ``(abspath, display)`` pairs.

    Directories are walked recursively for ``*.py``; display paths are
    posix-style and relative to `root` (default: cwd) so findings and
    baseline entries are machine independent.
    """
    root = os.path.abspath(root or os.getcwd())
    seen: set[str] = set()
    out: list[tuple[str, str]] = []

    def add(abspath: str) -> None:
        abspath = os.path.abspath(abspath)
        if abspath in seen:
            return
        seen.add(abspath)
        rel = os.path.relpath(abspath, root).replace(os.sep, "/")
        out.append((abspath, rel))

    for p in paths:
        p = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in _SKIP_DIRS
                                     and not d.startswith("."))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        add(os.path.join(dirpath, fn))
        elif p.endswith(".py") and os.path.isfile(p):
            add(p)
    return sorted(out, key=lambda t: t[1])


# -- baseline ---------------------------------------------------------------

def baseline_key(f: Finding) -> str:
    return f"{f.path}::{f.rule}::{f.message}"


def load_baseline(path: str) -> set[str]:
    if not os.path.isfile(path):
        return set()
    out: set[str] = set()
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line and not line.startswith("#"):
                out.add(line)
    return out


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    keys = sorted({baseline_key(f) for f in findings})
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("# lint baseline: one `path::rule::message` per "
                 "line; see README 'Static analysis'.\n")
        for k in keys:
            fh.write(k + "\n")


# -- runner -----------------------------------------------------------------

def run(paths: Iterable[str], root: str | None = None,
        rules: Iterable[str] | None = None,
        profile: dict[str, float] | None = None,
        ) -> tuple[list[Finding], Project]:
    """Parse every file once, run the checkers, return raw findings
    (suppressions and baseline NOT yet applied) plus the project.

    Per-module rules see each ``Module``; ``ProjectChecker`` rules
    additionally get the resolved ``CallGraph`` (built once, only
    when such a rule is selected).  Pass a dict as ``profile`` to get
    per-rule wall seconds (plus ``[parse]`` / ``[callgraph]``).
    """
    findings: list[Finding] = []
    modules: list[Module] = []
    t0 = time.perf_counter()
    for abspath, display in collect_files(paths, root):
        try:
            modules.append(Module.parse(abspath, display))
        except SyntaxError as e:
            findings.append(Finding(display, e.lineno or 1, "parse",
                                    f"syntax error: {e.msg}"))
    project = Project(modules)
    if profile is not None:
        profile["[parse]"] = time.perf_counter() - t0
    checkers = get_checkers(rules)
    if any(isinstance(c, ProjectChecker) for c in checkers):
        t0 = time.perf_counter()
        project.graph()
        if profile is not None:
            profile["[callgraph]"] = time.perf_counter() - t0
    for checker in checkers:
        t0 = time.perf_counter()
        for mod in project.modules:
            if checker.scope(mod):
                findings.extend(checker.check(mod))
        findings.extend(checker.finalize(project))
        if isinstance(checker, ProjectChecker):
            findings.extend(checker.check_project(project.graph()))
        if profile is not None:
            profile[checker.name] = (profile.get(checker.name, 0.0)
                                     + time.perf_counter() - t0)
    return sorted(findings), project


def changed_closure(project: Project, dirty: Iterable[str],
                    max_fanout: int = 8) -> set[str]:
    """Expand a set of dirty file paths with every module holding a
    (transitive) caller of anything the dirty modules define -- the
    re-analysis set for ``lint.py --changed``: an edit to a callee can
    surface interprocedural findings in callers that did not change.
    """
    graph = project.graph()
    dirty = set(dirty)
    targets = [q for q, fi in graph.functions.items()
               if fi.path in dirty]
    targets += [graph.module_root(p) for p in dirty
                if p in graph.symbols]
    out = set(dirty)
    for qual in graph.callers(targets, max_fanout=max_fanout):
        fi = graph.functions.get(qual)
        if fi is not None:
            out.add(fi.path)
    return out


def filter_suppressed(findings: Iterable[Finding], project: Project,
                      baseline: set[str] | None = None,
                      ) -> tuple[list[Finding], int, int]:
    """Apply inline suppressions then the baseline.

    Returns (kept, n_inline_suppressed, n_baselined).
    """
    baseline = baseline or set()
    by_path = project.by_path()
    kept: list[Finding] = []
    n_inline = n_base = 0
    for f in findings:
        mod = by_path.get(f.path)
        if mod is not None and mod.suppressed(f):
            n_inline += 1
        elif baseline_key(f) in baseline:
            n_base += 1
        else:
            kept.append(f)
    return kept, n_inline, n_base
