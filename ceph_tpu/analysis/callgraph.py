"""Best-effort interprocedural call graph over a lint Project.

Edges come in two strengths, and every edge carries its *fan-out*:

* precise (fan-out 1): local/nested defs, module top-level functions,
  imported names, ``self.method`` resolved in the enclosing class (or
  a statically resolvable base class), ``alias.func`` through the
  import table, ``Class.method`` on a known class;
* fuzzy (fan-out N): an attribute call whose receiver cannot be
  typed resolves to every project function with that name -- N says
  how ambiguous the guess was.

Rules pick their own precision/recall point via ``max_fanout`` when
they traverse: a reachability rule guarding a hot path wants tight
edges (a call named ``encode`` that could be any of nine functions is
probably not the one you meant), while a liveness rule wants every
edge it can get (an over-approximated "reachable" is the safe
direction for dead-code detection).  Reference edges (a function name
mentioned without a call -- callbacks, handler tables, decorators)
are kept separately for the liveness side.

The graph also tags what the whole-program rules need beyond edges:
per-function async-ness (``FunctionInfo.is_async``) and lock regions
(every ``with``/``async with`` on a lock-like context manager, with
the calls made and locks taken while it is held).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from . import astutil
from .core import Project
from .project import (FunctionInfo, ModuleSymbols, collect_symbols)

# names so common that a fuzzy match is noise, not signal
_FUZZY_SKIP = {"get", "items", "keys", "values", "update", "close",
               "pop", "add", "append", "run", "start", "stop", "send",
               "put", "read", "write", "copy", "next", "clear", "set"}
_FUZZY_CAP = 24          # store at most this many targets per site

# callables that SCHEDULE their argument on another task instead of
# running it in the caller's activation: the inner call still becomes
# an edge (the code does run -- liveness must see it) but a *deferred*
# one, because the caller's locks are not held when it executes
_SPAWN_WRAPPERS = {"ensure_future", "create_task", "call_soon",
                   "call_later", "call_soon_threadsafe"}


def _call_base(func: ast.AST) -> str | None:
    """Base identifier of a (possibly chained) method call:
    ``enc.u32(x).u64`` -> ``enc``."""
    node = func
    while True:
        if isinstance(node, ast.Attribute):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        else:
            break
    return node.id if isinstance(node, ast.Name) else None


def is_lock_name(leaf: str | None) -> bool:
    return leaf is not None and "lock" in leaf.lower()


@dataclass
class LockRegion:
    """One ``with <lock>:`` region and what happens while it is held."""

    locks: list[str]                 # ids of the locks this region takes
    owner: str                       # qualname of the holding function
    path: str
    line: int
    is_async: bool
    callees: list[tuple[str, int]] = field(default_factory=list)
    inner_locks: list[str] = field(default_factory=list)


class CallGraph:
    """The project call graph plus the symbol table it was built from."""

    def __init__(self, project: Project) -> None:
        self.project = project
        self.symbols: dict[str, ModuleSymbols] = collect_symbols(project)
        self.functions: dict[str, FunctionInfo] = {}
        self.by_name: dict[str, list[str]] = {}
        self.module_by_dotted: dict[str, ModuleSymbols] = {}
        # src qualname -> {dst qualname: fanout of the resolving site}
        self.calls: dict[str, dict[str, int]] = {}
        # edges that ONLY occur through a spawn wrapper (ensure_future
        # / create_task): real for liveness, not for lock analysis
        self.spawn_only: dict[str, set[str]] = {}
        self.refs: dict[str, set[str]] = {}
        self.lock_regions: list[LockRegion] = []
        self._rcalls: dict[str, dict[str, int]] | None = None

    # -- construction --------------------------------------------------------
    @classmethod
    def build(cls, project: Project) -> "CallGraph":
        g = cls(project)
        for syms in g.symbols.values():
            g.module_by_dotted[syms.dotted] = syms
            for fi in syms.functions:
                g.functions[fi.qualname] = fi
                g.by_name.setdefault(fi.name, []).append(fi.qualname)
        for syms in g.symbols.values():
            _Resolver(g, syms).resolve()
        return g

    def module_root(self, path: str) -> str:
        """Pseudo-function id for a module's top-level code."""
        return f"{path}::<module>"

    def _edge(self, src: str, dst: str, fanout: int,
              spawned: bool = False) -> None:
        cur = self.calls.setdefault(src, {})
        prev = cur.get(dst)
        if prev is None:
            if spawned:
                self.spawn_only.setdefault(src, set()).add(dst)
        elif not spawned:
            self.spawn_only.get(src, set()).discard(dst)
        if prev is None or fanout < prev:
            cur[dst] = fanout
            self._rcalls = None

    def _ref(self, src: str, dst: str) -> None:
        self.refs.setdefault(src, set()).add(dst)

    # -- queries -------------------------------------------------------------
    def lookup(self, spec: str) -> list[str]:
        """Qualnames matching ``Class.method`` or ``func`` anywhere in
        the project (how rules name their entry points)."""
        out = []
        for qual, fi in self.functions.items():
            if fi.local == spec or (fi.cls and
                                    f"{fi.cls}.{fi.name}" == spec):
                out.append(qual)
            elif "." not in spec and fi.cls is None \
                    and fi.local == spec:
                out.append(qual)
        return sorted(set(out))

    def reachable(self, roots, *, max_fanout: int = 10**6,
                  refs: bool = False, spawn: bool = True) -> set[str]:
        """Forward transitive closure over call edges (and optionally
        reference edges) whose fan-out is within ``max_fanout``.
        ``spawn=False`` skips edges that only exist through a task
        spawn (ensure_future/create_task) -- the traversal then means
        "runs in the caller's activation", which is what lock-holding
        analysis needs."""
        seen = set()
        stack = [r for r in roots]
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            spawned = self.spawn_only.get(cur, ())
            for dst, fo in self.calls.get(cur, {}).items():
                if not spawn and dst in spawned:
                    continue
                if fo <= max_fanout and dst not in seen:
                    stack.append(dst)
            if refs:
                for dst in self.refs.get(cur, ()):
                    if dst not in seen:
                        stack.append(dst)
        return seen

    def callers(self, targets, *, max_fanout: int = 10**6) -> set[str]:
        """Reverse transitive closure: every function from which some
        target is reachable (targets themselves included)."""
        if self._rcalls is None:
            rc: dict[str, dict[str, int]] = {}
            for src, dsts in self.calls.items():
                for dst, fo in dsts.items():
                    cur = rc.setdefault(dst, {})
                    if fo < cur.get(src, 10**9):
                        cur[src] = fo
            self._rcalls = rc
        seen = set()
        stack = list(targets)
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            for src, fo in self._rcalls.get(cur, {}).items():
                if fo <= max_fanout and src not in seen:
                    stack.append(src)
        return seen

    def entry_points(self) -> set[str]:
        """Liveness roots: module top-level code plus every function
        whose name is public API shaped (no leading underscore, or a
        dunder), a test, or a main."""
        roots: set[str] = set()
        for path in self.symbols:
            roots.add(self.module_root(path))
        for qual, fi in self.functions.items():
            n = fi.name
            if (not n.startswith("_")
                    or (n.startswith("__") and n.endswith("__"))
                    or n.startswith("test_") or n == "main"):
                roots.add(qual)
        return roots


def own_nodes(root: ast.AST):
    """Walk a function (or module) body without descending into nested
    function definitions -- their statements run on a different
    activation, so they belong to their own FunctionInfo."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # decorators/defaults evaluate in the enclosing scope
            stack.extend(node.decorator_list)
            stack.extend(d for d in node.args.defaults if d)
            continue
        stack.extend(ast.iter_child_nodes(node))


def _literal_prefix(node: ast.AST) -> str | None:
    """Leading constant of a dynamic attribute name: the f-string
    ``f"_h_{t}"`` and the concat ``"_h_" + t`` both yield ``"_h_"``."""
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if (isinstance(first, ast.Constant)
                and isinstance(first.value, str)
                and len(node.values) > 1):
            return first.value
    if (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add)
            and isinstance(node.left, ast.Constant)
            and isinstance(node.left.value, str)):
        return node.left.value
    return None


def _spawn_wrapped_ids(root: ast.AST) -> set[str]:
    """ids of every Call node inside the arguments of a spawn wrapper
    (``ensure_future(self._loop(c))``: the inner call creates the
    coroutine, the wrapper schedules it on another task)."""
    out: set[int] = set()
    for node in own_nodes(root):
        if not (isinstance(node, ast.Call)
                and astutil.name_leaf(node.func) in _SPAWN_WRAPPERS):
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Call):
                    out.add(id(sub))
    return out


class _Resolver:
    """Second pass: turn one module's call sites into graph edges."""

    def __init__(self, graph: CallGraph, syms: ModuleSymbols) -> None:
        self.g = graph
        self.syms = syms
        self.path = syms.module.path

    def resolve(self) -> None:
        mod_qual = self.g.module_root(self.path)
        self._resolve_body(self.syms.module.tree, mod_qual,
                           cls=None, locals_chain=[])
        for fi in self.syms.functions:
            self._resolve_body(fi.node, fi.qualname, cls=fi.cls,
                               locals_chain=self._local_defs(fi))
            self._collect_lock_regions(fi)
            # a def is an edge: the nested function can only run if
            # its enclosing function ran (conservative for liveness)
            for child in ast.walk(fi.node):
                if child is fi.node:
                    continue
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    nested = f"{self.path}::" + self._nested_local(
                        fi, child)
                    if nested in self.g.functions:
                        self.g._edge(fi.qualname, nested, 1)

    def _nested_local(self, outer: FunctionInfo,
                      node: ast.AST) -> str:
        # nested defs were registered as "<outer>.<locals>.<name>";
        # deeper nesting chains the same suffix
        for cand in self.g.by_name.get(node.name, ()):
            fi = self.g.functions[cand]
            if fi.node is node:
                return fi.local
        return f"{outer.local}.<locals>.{node.name}"

    def _local_defs(self, fi: FunctionInfo) -> list[dict[str, str]]:
        out: dict[str, str] = {}
        for child in ast.iter_child_nodes(fi.node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                for cand in self.g.by_name.get(child.name, ()):
                    if self.g.functions[cand].node is child:
                        out[child.name] = cand
                        break
        return [out] if out else []

    # -- body walk -----------------------------------------------------------
    def _resolve_body(self, root, src: str, cls, locals_chain) -> None:
        spawned_ids = _spawn_wrapped_ids(root)
        call_funcs = set()
        for node in own_nodes(root):
            if isinstance(node, ast.Call):
                call_funcs.add(id(node.func))
                self._dynamic_dispatch(node, src)
                for dst, fo in self.resolve_call(node, cls,
                                                locals_chain):
                    self.g._edge(src, dst, fo,
                                 spawned=id(node) in spawned_ids)
        # reference edges: function names mentioned outside call
        # position (handler tables, callbacks, decorators)
        for node in own_nodes(root):
            if id(node) in call_funcs:
                continue
            leaf = astutil.name_leaf(node)
            if leaf and leaf in self.g.by_name:
                for dst in self.g.by_name[leaf][:_FUZZY_CAP]:
                    self.g._ref(src, dst)

    def _dynamic_dispatch(self, node: ast.Call, src: str) -> None:
        """``getattr(x, f"_h_{t}")`` / ``getattr(x, "_h_" + t)``: a
        dispatch-by-name-prefix convention.  Every function whose name
        starts with the literal prefix gets a reference edge -- the
        handlers ARE live, the table is just spelled dynamically."""
        if not (isinstance(node.func, ast.Name)
                and node.func.id == "getattr" and len(node.args) >= 2):
            return
        prefix = _literal_prefix(node.args[1])
        if prefix is None or len(prefix) < 2:
            return
        for name, quals in self.g.by_name.items():
            if name.startswith(prefix):
                for dst in quals[:_FUZZY_CAP]:
                    self.g._ref(src, dst)

    # -- call resolution -----------------------------------------------------
    def resolve_call(self, node: ast.Call, cls,
                     locals_chain) -> list[tuple[str, int]]:
        func = node.func
        dotted = astutil.dotted(func)
        if dotted is None:
            if isinstance(func, ast.Attribute):
                return self._fuzzy(func.attr)
            return []
        if "." not in dotted:
            return self._resolve_bare(dotted, locals_chain)
        head, _, rest = dotted.partition(".")
        leaf = dotted.rsplit(".", 1)[1]
        if head in ("self", "cls") and cls and "." not in rest:
            hit = self._resolve_method(cls, rest, set())
            if hit:
                return [(hit, 1)]
            return self._fuzzy(rest, methods_only=True)
        # alias/module-qualified: np.asarray, mod.func, Class.method
        expanded = self.syms.expand_alias(head)
        target = (expanded + "." + rest) if rest else expanded
        prefix, _, tleaf = target.rpartition(".")
        hit = self._resolve_dotted(prefix, tleaf)
        if hit:
            return [(hit, 1)]
        return self._fuzzy(leaf)

    def _resolve_bare(self, name, locals_chain) -> list[tuple[str, int]]:
        for frame in locals_chain:
            if name in frame:
                return [(frame[name], 1)]
        fi = self.syms.top_funcs.get(name)
        if fi:
            return [(fi.qualname, 1)]
        ci = self.syms.classes.get(name)
        if ci:
            init = ci.methods.get("__init__")
            return [(init.qualname, 1)] if init else []
        target = self.syms.aliases.get(name)
        if target:
            prefix, _, leaf = target.rpartition(".")
            hit = self._resolve_dotted(prefix, leaf)
            if hit:
                return [(hit, 1)]
        return []

    def _resolve_dotted(self, prefix: str, leaf: str) -> str | None:
        """``prefix.leaf`` as module.func, module.Class (-> __init__),
        package.module.Class.method, or local Class.method."""
        syms = self.g.module_by_dotted.get(prefix)
        if syms is not None:
            fi = syms.top_funcs.get(leaf)
            if fi:
                return fi.qualname
            ci = syms.classes.get(leaf)
            if ci:
                init = ci.methods.get("__init__")
                return init.qualname if init else None
            return None
        # prefix may itself be a class: "…mod.Class" + method leaf
        mod_prefix, _, cls_name = prefix.rpartition(".")
        csyms = (self.g.module_by_dotted.get(mod_prefix)
                 if mod_prefix else self.syms)
        if cls_name and csyms is not None:
            ci = csyms.classes.get(cls_name)
            if ci and leaf in ci.methods:
                return ci.methods[leaf].qualname
        # bare "Class.method" in this module
        ci = self.syms.classes.get(prefix)
        if ci and leaf in ci.methods:
            return ci.methods[leaf].qualname
        return None

    def _resolve_method(self, cls_name: str, meth: str,
                        seen: set) -> str | None:
        """Walk the statically visible inheritance chain."""
        if cls_name in seen:
            return None
        seen.add(cls_name)
        ci = self.syms.classes.get(cls_name)
        if ci is None:
            return None
        if meth in ci.methods:
            return ci.methods[meth].qualname
        for base in ci.bases:
            head, _, rest = base.partition(".")
            expanded = self.syms.expand_alias(head)
            target = (expanded + "." + rest) if rest else expanded
            mod, _, bcls = target.rpartition(".")
            bsyms = self.g.module_by_dotted.get(mod)
            if bsyms is not None:
                bci = bsyms.classes.get(bcls)
                if bci and meth in bci.methods:
                    return bci.methods[meth].qualname
            elif base in self.syms.classes:
                hit = self._resolve_method(base, meth, seen)
                if hit:
                    return hit
        return None

    def _fuzzy(self, leaf: str,
               methods_only: bool = False) -> list[tuple[str, int]]:
        if leaf in _FUZZY_SKIP:
            return []
        cands = self.g.by_name.get(leaf, [])
        if methods_only:
            cands = [q for q in cands
                     if self.g.functions[q].cls is not None]
        if not cands:
            return []
        fo = len(cands)
        return [(q, fo) for q in cands[:_FUZZY_CAP]]

    # -- lock regions --------------------------------------------------------
    def _collect_lock_regions(self, fi: FunctionInfo) -> None:
        for node in own_nodes(fi.node):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            locks = []
            for item in node.items:
                lid = self._lock_id(item.context_expr, fi)
                if lid:
                    locks.append(lid)
            if not locks:
                continue
            region = LockRegion(
                locks=locks, owner=fi.qualname, path=self.path,
                line=node.lineno,
                is_async=isinstance(node, ast.AsyncWith))
            locals_chain = self._local_defs(fi)
            spawned_ids = _spawn_wrapped_ids(node)
            for inner in own_nodes(node):
                if isinstance(inner, ast.Call):
                    # a call handed to ensure_future/create_task runs
                    # on its own task -- this region's locks are not
                    # held across it
                    if id(inner) in spawned_ids:
                        continue
                    region.callees.extend(
                        self.resolve_call(inner, fi.cls, locals_chain))
                elif isinstance(inner, (ast.With, ast.AsyncWith)):
                    for item in inner.items:
                        lid = self._lock_id(item.context_expr, fi)
                        if lid:
                            region.inner_locks.append(lid)
            self.g.lock_regions.append(region)

    def _lock_id(self, expr: ast.AST, fi: FunctionInfo) -> str | None:
        if isinstance(expr, ast.Call):
            expr = expr.func
        leaf = astutil.name_leaf(expr)
        if not is_lock_name(leaf):
            return None
        base = _call_base(expr) if isinstance(expr, ast.Attribute) \
            else None
        if base in ("self", "cls") and fi.cls:
            return f"{fi.cls}.{leaf}"
        if isinstance(expr, ast.Name):
            return f"{self.path}:{leaf}"
        dotted = astutil.dotted(expr)
        return dotted or f"{self.path}:{leaf}"
