"""Whole-program symbol model: every module's functions, classes, and
import aliases, resolved once and shared by the interprocedural rules.

The per-module layer (``core.Module``) deliberately sees one
``ast.Module`` at a time; this layer stitches those trees into a
project-wide symbol table that ``callgraph.CallGraph`` turns into call
edges.  Resolution is *best effort by construction*: Python has no
sound static call graph, so the contract here is the one the checkers
need -- precise edges where the syntax supports them (local defs,
``self.method`` in a known class, imported names, dotted module
calls), name-based fuzzy edges everywhere else, each tagged with its
fan-out so a rule can choose how much ambiguity to traverse.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .core import Module, Project


@dataclass
class FunctionInfo:
    """One function/method/nested def anywhere in the project."""

    qualname: str            # "<display path>::<local dotted name>"
    name: str                # bare name
    local: str               # "Class.method", "func", "f.<locals>.g"
    path: str                # module display path
    node: ast.AST            # the (Async)FunctionDef
    cls: str | None          # enclosing class name, if a method
    is_async: bool
    lineno: int


@dataclass
class ClassInfo:
    name: str
    bases: list[str]                       # dotted base-class names
    methods: dict[str, FunctionInfo] = field(default_factory=dict)


@dataclass
class ModuleSymbols:
    """Symbols and import aliases of one parsed module."""

    module: Module
    dotted: str                            # "ceph_tpu.osd.backend"
    package: str                           # "ceph_tpu.osd"
    functions: list[FunctionInfo] = field(default_factory=list)
    top_funcs: dict[str, FunctionInfo] = field(default_factory=dict)
    classes: dict[str, ClassInfo] = field(default_factory=dict)
    # local alias -> dotted target ("np" -> "numpy",
    # "CodecBatcher" -> "ceph_tpu.osd.codec_batcher.CodecBatcher")
    aliases: dict[str, str] = field(default_factory=dict)

    @classmethod
    def collect(cls, module: Module) -> "ModuleSymbols":
        dotted = path_to_dotted(module.path)
        package = dotted.rsplit(".", 1)[0] if "." in dotted else ""
        if module.path.endswith("/__init__.py"):
            package = dotted
        syms = cls(module=module, dotted=dotted, package=package)
        _Collector(syms).visit(module.tree)
        return syms

    def expand_alias(self, name: str) -> str:
        """Map a local head identifier through the import table
        (``np`` -> ``numpy``); unknown names map to themselves."""
        return self.aliases.get(name, name)


def path_to_dotted(display: str) -> str:
    p = display[:-3] if display.endswith(".py") else display
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


class _Collector(ast.NodeVisitor):
    """One pass over a module tree building its ModuleSymbols."""

    def __init__(self, syms: ModuleSymbols) -> None:
        self.syms = syms
        self._class_stack: list[str] = []
        self._func_stack: list[str] = []

    # -- imports -------------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            if a.asname:
                self.syms.aliases[a.asname] = a.name
            else:
                # `import a.b.c` binds `a`; dotted call resolution
                # re-joins the tail, so aliasing the head is enough
                head = a.name.split(".", 1)[0]
                self.syms.aliases.setdefault(head, head)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        base = self._resolve_from(node)
        for a in node.names:
            if a.name == "*":
                continue
            target = f"{base}.{a.name}" if base else a.name
            self.syms.aliases[a.asname or a.name] = target

    def _resolve_from(self, node: ast.ImportFrom) -> str:
        if not node.level:
            return node.module or ""
        # relative import: anchor at this module's package
        parts = self.syms.package.split(".") if self.syms.package else []
        if node.level > 1:
            parts = parts[: len(parts) - (node.level - 1)]
        if node.module:
            parts = parts + node.module.split(".")
        return ".".join(parts)

    # -- defs ----------------------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        bases = []
        for b in node.bases:
            d = _dotted(b)
            if d:
                bases.append(d)
        if not self._class_stack and not self._func_stack:
            self.syms.classes[node.name] = ClassInfo(node.name, bases)
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._add_function(node, is_async=False)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._add_function(node, is_async=True)

    def _add_function(self, node, is_async: bool) -> None:
        if self._func_stack:
            local = (".".join(self._func_stack)
                     + f".<locals>.{node.name}")
            cls = None
        elif self._class_stack:
            local = ".".join(self._class_stack) + f".{node.name}"
            cls = self._class_stack[-1]
        else:
            local = node.name
            cls = None
        info = FunctionInfo(
            qualname=f"{self.syms.module.path}::{local}",
            name=node.name, local=local, path=self.syms.module.path,
            node=node, cls=cls, is_async=is_async, lineno=node.lineno)
        self.syms.functions.append(info)
        if cls is not None:
            ci = self.syms.classes.get(cls)
            if ci is not None:
                ci.methods[node.name] = info
        elif not self._func_stack and not self._class_stack:
            self.syms.top_funcs[node.name] = info
        self._func_stack.append(node.name)
        # class scope does not leak into nested defs
        saved, self._class_stack = self._class_stack, []
        self.generic_visit(node)
        self._class_stack = saved
        self._func_stack.pop()


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def collect_symbols(project: Project) -> dict[str, ModuleSymbols]:
    """Symbol tables for every module, keyed by display path."""
    return {m.path: ModuleSymbols.collect(m) for m in project.modules}
