"""Machine-readable process-seam audit (``SEAM_AUDIT.json``).

The swarm PR will replace SimCluster's in-process daemons with real
processes; every assumption that only holds because the daemons share
one interpreter becomes a silent corruption the day they stop.  This
module composes the pure-data census passes the three seam rules
already run -- shared mutable state (cross-daemon-state), the wire
vocabulary (wire-safety), and await-window snapshot races
(await-invalidates-snapshot) -- into one artifact that the swarm PR
can diff against:

* ``shared_state``   -- every module-level mutable global and mutable
  class attribute, classified fork-safe recomputable cache vs
  per-process counter/primitive vs correctness state;
* ``daemon_reaches`` -- every site where one daemon touches another's
  private or subsystem attributes instead of crossing the Messenger,
  with the inline justification when one is carried;
* ``wire_types``     -- every message type with its codec class,
  payload-safety verdict, and whether any dispatcher consumes it;
* ``snapshot_races`` -- every bind -> await -> use window, with its
  justification when suppressed.

Entries whose flagged line carries a ``# lint: disable=<rule> -- why``
directive are marked ``justified`` and the ``why`` text is lifted into
the report, so the artifact names each sharp edge *and* the reason it
is allowed to stay.  CLI front end: ``tools/lint.py --seam-report``.
"""

from __future__ import annotations

from .core import Module, Project
from .checkers.await_snapshot import snapshot_races
from .checkers.cross_daemon_state import daemon_reaches, shared_state_census
from .checkers.wire_safety import wire_census

SCHEMA = "ceph-tpu-seam-audit-v1"

# the analyzer's own tables (rule registries, hint tuples) are not
# cluster state; keeping them out leaves the audit about the daemons
_SELF_PATHS = ("analysis/",)


def _justification(mod: Module | None, line: int,
                   rule: str) -> str | None:
    """The ``-- why`` text of the disable directive covering ``line``
    (the directive's own line or the standalone comment line above),
    or None when the site is not suppressed for ``rule``."""
    if mod is None:
        return None
    rules = mod.suppressions.get(line, set())
    if "*" not in rules and rule not in rules:
        return None
    lines = mod.source.splitlines()
    for ln in (line, line - 1):
        if not 1 <= ln <= len(lines):
            continue
        text = lines[ln - 1]
        if "lint:" in text and "disable" in text and "--" in text:
            return text.split("--", 1)[1].strip()
    return ""


def build_report(project: Project) -> dict:
    """The full seam audit as a JSON-serializable dict."""
    graph = project.graph()
    mods = project.by_path()

    shared = [e for e in shared_state_census(graph)
              if not any(p in e["path"] for p in _SELF_PATHS)]

    reaches = []
    for r in daemon_reaches(graph):
        why = _justification(mods.get(r["path"]), r["line"],
                             "cross-daemon-state")
        reaches.append({**r, "justified": why is not None,
                        "justification": why})

    wire = []
    for e in wire_census(graph):
        why = None
        for site in e["sites"]:
            path, _, line = site.rpartition(":")
            why = _justification(mods.get(path), int(line),
                                 "wire-safety")
            if why is not None:
                break
        wire.append({**e, "justified": why is not None,
                     "justification": why})

    races = []
    for r in snapshot_races(graph):
        why = _justification(mods.get(r["path"]), r["line"],
                             "await-invalidates-snapshot")
        races.append({**r, "justified": why is not None,
                      "justification": why})

    by_class: dict[str, int] = {}
    for e in shared:
        c = e["classification"]
        by_class[c] = by_class.get(c, 0) + 1

    summary = {
        "shared_state_sites": len(shared),
        "shared_state_by_classification": dict(sorted(
            by_class.items())),
        "daemon_reaches": len(reaches),
        "unjustified_daemon_reaches": sum(
            1 for r in reaches if not r["justified"]),
        "wire_types": len(wire),
        "unsafe_wire_types": sorted(
            e["type"] for e in wire if e["verdict"] != "wire-safe"),
        "unhandled_wire_types": sorted(
            e["type"] for e in wire
            if not e["handled"] and not e["justified"]),
        "snapshot_races": len(races),
        "unjustified_snapshot_races": sum(
            1 for r in races if not r["justified"]),
    }
    return {
        "version": 1,
        "schema": SCHEMA,
        "shared_state": shared,
        "daemon_reaches": reaches,
        "wire_types": wire,
        "snapshot_races": races,
        "summary": summary,
    }
