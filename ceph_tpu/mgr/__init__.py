"""Manager-plane modules (the mgr module host analog,
src/pybind/mgr): cluster-wide optimization passes that consume the
OSDMap and emit map mutations.  The balancer is the flagship customer
of the vectorized CRUSH op -- full-cluster placement recompute in one
launch."""

from .mgr import Mgr, MgrModule  # noqa: F401,E402
