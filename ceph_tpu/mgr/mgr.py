"""Manager daemon: module host + daemon metrics aggregation.

The src/mgr stack in miniature: the mgr beacons to the monitor (which
publishes the active mgr's address to its subscribers, the MgrMap
analog), receives periodic perf-counter reports from daemons
(DaemonServer / MgrClient report protocol), and hosts python modules
with a serve-loop + command surface (the ActivePyModules / MgrModule
shape).  Built-in modules:

  * balancer     -- periodic upmap optimization (mgr balancer upmap
                    mode); active when `balancer_active` config is on
  * pg_autoscaler-- recommends pg_num per pool from utilization
                    heuristics (report-only: pg splitting/merging is
                    not implemented)
  * status       -- cluster + daemon-report summary

Modules answer `mgr_command` messages ({"prefix": "<module> <cmd>"}),
the `ceph tell mgr` analog.
"""

from __future__ import annotations

import asyncio
import json
import time

from ..common import make_task_tracker
from ..msg import Message, Messenger
from ..mon.osdmap import OSDMap, Incremental


class MgrModule:
    """Module SPI (mgr_module.py analog): override serve/handle."""

    name = "module"

    def __init__(self, mgr: "Mgr") -> None:
        self.mgr = mgr

    async def serve(self) -> None:
        """Background loop; cancelled at shutdown."""

    async def handle_command(self, cmd: str, args: dict):
        raise ValueError(f"unknown command {cmd!r}")


class BalancerModule(MgrModule):
    name = "balancer"

    async def serve(self) -> None:
        while True:
            await asyncio.sleep(self.mgr.config["balancer_interval"])
            if not self.mgr.config["balancer_active"]:
                continue
            try:
                res = await self.mgr.mon_command(
                    "osd balancer run",
                    {"max": self.mgr.config["balancer_max_moves"]})
                if res.get("moved"):
                    self.mgr.log.append(
                        f"balancer: moved {res['moved']} pgs "
                        f"(stddev {res['before']['stddev']} -> "
                        f"{res['after']['stddev']})")
            except Exception as e:
                self.mgr.log.append(f"balancer: {e}")

    async def handle_command(self, cmd: str, args: dict):
        if cmd == "status":
            from .balancer import pg_distribution
            return {"active": self.mgr.config["balancer_active"],
                    "distribution": pg_distribution(self.mgr.osdmap)}
        if cmd == "on":
            self.mgr.config["balancer_active"] = True
            return "active"
        if cmd == "off":
            self.mgr.config["balancer_active"] = False
            return "inactive"
        if cmd == "execute":
            return await self.mgr.mon_command(
                "osd balancer run",
                {"max": args.get("max",
                                 self.mgr.config["balancer_max_moves"])})
        raise ValueError(f"unknown balancer command {cmd!r}")


class PgAutoscalerModule(MgrModule):
    name = "pg_autoscaler"

    async def handle_command(self, cmd: str, args: dict):
        if cmd != "status":
            raise ValueError(f"unknown pg_autoscaler command {cmd!r}")
        # the reference targets ~100 PGs/OSD scaled by pool bias;
        # recommendation only (pg splitting is future work)
        n_osd = sum(1 for i in self.mgr.osdmap.osds.values()
                    if i.up and i.in_cluster)
        out = []
        for pool in self.mgr.osdmap.pools.values():
            target = max(1, (100 * max(n_osd, 1)) // max(
                1, len(self.mgr.osdmap.pools)) // max(1, pool.size))
            # round to the next power of two, the pg_num discipline
            rec = 1 << max(0, (target - 1).bit_length())
            out.append({"pool": pool.name, "pg_num": pool.pg_num,
                        "recommended": rec,
                        "would_adjust": rec != pool.pg_num})
        return out


class PrometheusModule(MgrModule):
    """GET /metrics exposition (src/pybind/mgr/prometheus analog):
    cluster state from the osdmap + per-daemon perf counters from the
    DaemonServer reports."""

    name = "prometheus"

    def __init__(self, mgr: "Mgr") -> None:
        super().__init__(mgr)
        self.server = None
        self.addr: tuple[str, int] | None = None

    async def serve(self) -> None:
        from .prometheus import MetricsHttpServer
        self.server = MetricsHttpServer(self.render)
        self.addr = await self.server.start(
            port=self.mgr.config.get("prometheus_port", 0))
        try:
            await asyncio.Event().wait()      # serve until cancelled
        except asyncio.CancelledError:
            await self.server.stop()
            raise

    async def render(self) -> str:
        from .prometheus import (
            families_from_perf, merge_families, render_metrics,
        )
        m = self.mgr
        osd_up = {"help": "OSD up state", "type": "gauge",
                  "samples": [({"ceph_daemon": f"osd.{o}"},
                               1 if i.up else 0)
                              for o, i in m.osdmap.osds.items()]}
        osd_in = {"help": "OSD in state", "type": "gauge",
                  "samples": [({"ceph_daemon": f"osd.{o}"},
                               1 if i.in_cluster else 0)
                              for o, i in m.osdmap.osds.items()]}
        pools = {"help": "pool pg_num", "type": "gauge",
                 "samples": [({"pool": p.name}, p.pg_num)
                             for p in m.osdmap.pools.values()]}
        epoch = {"help": "osdmap epoch", "type": "counter",
                 "samples": [({}, m.osdmap.epoch)]}
        perf = [families_from_perf(name, rep.get("summary", {}),
                                   prefix="ceph_daemon")
                for name, rep in m.daemon_reports.items()]
        pg_states = {"help": "PG count by state per daemon",
                     "type": "gauge", "samples": []}
        for name, rep in m.daemon_reports.items():
            for state, n in rep.get("summary", {}).get(
                    "pg_states", {}).items():
                pg_states["samples"].append(
                    ({"ceph_daemon": name, "state": state}, n))
        return render_metrics(merge_families(
            {"ceph_osd_up": osd_up, "ceph_osd_in": osd_in,
             "ceph_pool_pg_num": pools, "ceph_osdmap_epoch": epoch,
             "ceph_pg_states": pg_states},
            *perf))

    async def handle_command(self, cmd: str, args: dict):
        if cmd == "status":
            return {"addr": list(self.addr) if self.addr else None}
        raise ValueError(f"unknown prometheus command {cmd!r}")


class ProgressModule(MgrModule):
    """Recovery/backfill progress events (src/pybind/mgr/progress):
    watches the missing-object counts daemons report; an event opens
    when recovery work appears, tracks the high-water mark, and
    completes when the count drains to zero."""

    name = "progress"

    def __init__(self, mgr: "Mgr") -> None:
        super().__init__(mgr)
        self.events: dict[str, dict] = {}
        self._serial = 0

    STALE_REPORT_S = 30.0

    def _total_missing(self) -> int:
        # a daemon that died mid-recovery leaves its last report behind
        # forever (nothing prunes daemon_reports); counting it would pin
        # an event open and block all future ones
        now = time.monotonic()
        return sum(rep.get("summary", {}).get("missing_objects", 0)
                   for rep in self.mgr.daemon_reports.values()
                   if now - rep.get("stamp", 0) < self.STALE_REPORT_S)

    async def serve(self) -> None:
        while True:
            await asyncio.sleep(1.0)
            try:
                self._tick()
            except Exception as e:
                self.mgr.log.append(f"progress: {type(e).__name__}: {e}")

    def _tick(self) -> None:
        missing = self._total_missing()
        open_ev = next((e for e in self.events.values()
                        if not e["done"]), None)
        if missing > 0 and open_ev is None:
            self._serial += 1
            self.events[f"ev{self._serial}"] = {
                "message": "Recovering degraded objects",
                "started": time.monotonic(), "peak": missing,
                "remaining": missing, "progress": 0.0, "done": False}
        elif open_ev is not None:
            open_ev["peak"] = max(open_ev["peak"], missing)
            open_ev["remaining"] = missing
            open_ev["progress"] = round(
                1.0 - missing / max(open_ev["peak"], 1), 3)
            if missing == 0:
                open_ev["done"] = True
                open_ev["progress"] = 1.0
                open_ev["finished"] = time.monotonic()
        # completed events linger for 5 minutes AFTER completion (aging
        # by start time would delete a long recovery's event instantly)
        now = time.monotonic()
        for key in [k for k, e in self.events.items()
                    if e["done"] and now - e.get("finished", now) > 300]:
            del self.events[key]

    async def handle_command(self, cmd: str, args: dict):
        if cmd == "show":
            return {k: {kk: vv for kk, vv in e.items()
                        if kk not in ("started", "finished")}
                    for k, e in self.events.items()}
        if cmd == "clear":
            self.events.clear()
            return ""
        raise ValueError(f"unknown progress command {cmd!r}")


class StatusModule(MgrModule):
    name = "status"

    async def handle_command(self, cmd: str, args: dict):
        if cmd != "show":
            raise ValueError(f"unknown status command {cmd!r}")
        now = time.monotonic()
        return {
            "epoch": self.mgr.osdmap.epoch,
            "daemons": {name: {"age": round(now - rep["stamp"], 1),
                               "counters": rep.get("summary", {})}
                        for name, rep in self.mgr.daemon_reports.items()},
            "log_tail": self.mgr.log[-10:],
        }


class DashboardModule(MgrModule):
    """Read-only web dashboard (src/pybind/mgr/dashboard, compressed
    to the observability core): an HTTP endpoint on the active mgr
    serving cluster health, OSD/pool/daemon state as JSON plus a
    minimal HTML overview -- riding the same hardened HTTP loop as
    the prometheus exporter."""

    name = "dashboard"

    def __init__(self, mgr: "Mgr") -> None:
        super().__init__(mgr)
        self._server = None
        self.addr: tuple[str, int] | None = None

    async def serve(self) -> None:
        if not self.mgr.config.get("dashboard_enabled", True):
            return
        from .prometheus import MetricsHttpServer
        self._server = MetricsHttpServer(self._route, router=True)
        try:
            self.addr = await self._server.start(
                port=int(self.mgr.config.get("dashboard_port", 0)))
        except OSError as e:
            # an operator must see WHY the dashboard is absent
            self.mgr.log.append(f"dashboard: bind failed: {e}")
            return
        try:
            await asyncio.Event().wait()       # serve until cancelled
        except asyncio.CancelledError:
            await self._server.stop()

    def _payload(self, path: str):
        m = self.mgr.osdmap
        if path == "/api/osds":
            return [{"id": o, "up": i.up, "in": i.in_cluster,
                     "host": i.host,
                     "weight": i.weight / 0x10000}
                    for o, i in sorted(m.osds.items())]
        if path == "/api/pools":
            return [{"id": pid, "name": p.name, "type": p.type,
                     "size": p.size, "pg_num": p.pg_num}
                    for pid, p in sorted(m.pools.items())]
        if path == "/api/daemons":
            return self.mgr.daemon_reports
        if path in ("/", "/api/summary"):
            osds = list(m.osds.values())
            return {"epoch": m.epoch,
                    "osds": {"total": len(osds),
                             "up": sum(1 for o in osds if o.up),
                             "in": sum(1 for o in osds
                                       if o.in_cluster)},
                    "pools": len(m.pools),
                    "daemons": sorted(self.mgr.daemon_reports)}
        return None

    async def _route(self, path: str):
        payload = self._payload(path)
        if payload is None:
            return "404 Not Found", "text/plain", b"not found"
        if path == "/":
            s = payload
            body = (
                "<html><head><title>ceph_tpu</title></head><body>"
                f"<h1>cluster @ epoch {s['epoch']}</h1>"
                f"<p>OSDs: {s['osds']['up']}/{s['osds']['total']}"
                f" up, {s['osds']['in']} in</p>"
                f"<p>pools: {s['pools']}</p>"
                f"<p>daemons: {', '.join(s['daemons']) or '-'}"
                "</p><p>JSON: <a href='/api/summary'>summary</a> "
                "<a href='/api/osds'>osds</a> "
                "<a href='/api/pools'>pools</a> "
                "<a href='/api/daemons'>daemons</a></p>"
                "</body></html>").encode()
            return "200 OK", "text/html", body
        return ("200 OK", "application/json",
                json.dumps(payload).encode())

    async def handle_command(self, cmd: str, args: dict):
        if cmd == "status":
            return {"addr": list(self.addr) if self.addr else None}
        raise ValueError(f"unknown dashboard command {cmd!r}")


class TelemetryModule(MgrModule):
    """Anonymized cluster report (src/pybind/mgr/telemetry): opt-in,
    aggregates non-identifying facts -- daemon counts, pool shapes,
    usage -- into the report a phone-home channel would send (no
    egress in this environment; the report is inspectable instead)."""

    name = "telemetry"

    def __init__(self, mgr: "Mgr") -> None:
        super().__init__(mgr)
        self.enabled = bool(mgr.config.get("telemetry_on", False))

    def report(self) -> dict:
        m = self.mgr.osdmap
        osds = list(m.osds.values())
        return {
            "report_version": 1,
            "osd": {"count": len(osds),
                    "up": sum(1 for o in osds if o.up),
                    "in": sum(1 for o in osds if o.in_cluster)},
            "pools": [{"type": p.type, "size": p.size,
                       "pg_num": p.pg_num,
                       "erasure_code_profile":
                           bool(p.erasure_code_profile)}
                      for p in m.pools.values()],
            "daemons": sorted(self.mgr.daemon_reports),
            "crush": {"buckets": len(m.crush.buckets),
                      "rules": len(m.crush.rules)},
        }

    async def handle_command(self, cmd: str, args: dict):
        if cmd == "status":
            return {"enabled": self.enabled}
        if cmd == "on":
            self.enabled = True
            return ""
        if cmd == "off":
            self.enabled = False
            return ""
        if cmd == "show":
            return self.report()
        raise ValueError(f"unknown telemetry command {cmd!r}")


class Mgr:
    def __init__(self, name: str = "x",
                 config: dict | None = None,
                 secret: bytes | None = None,
                 msgr_opts: dict | None = None) -> None:
        self.name = name
        self.msgr = Messenger(f"mgr.{name}", secret=secret,
                              **(msgr_opts or {}))
        self.osdmap = OSDMap()
        self.mon_addr: tuple[str, int] | None = None
        self.config = {
            "balancer_active": False,
            "balancer_interval": 5.0,
            "balancer_max_moves": 10,
            "beacon_interval": 2.0,
            **(config or {}),
        }
        # daemon name -> last report (DaemonStateIndex analog)
        self.daemon_reports: dict[str, dict] = {}
        self.log: list[str] = []
        self.modules: dict[str, MgrModule] = {}
        for cls in (BalancerModule, PgAutoscalerModule, StatusModule,
                    PrometheusModule, ProgressModule,
                    TelemetryModule, DashboardModule):
            mod = cls(self)
            self.modules[mod.name] = mod
        self._tasks: list[asyncio.Task] = []
        self._track = make_task_tracker(self._tasks)
        self._cmd_waiters: dict[int, asyncio.Future] = {}
        self._tid = 0
        self.msgr.add_dispatcher(self._dispatch)

    async def start(self, mon_addr: tuple[str, int],
                    host: str = "127.0.0.1", port: int = 0):
        self.mon_addr = tuple(mon_addr)
        addr = await self.msgr.bind(host, port)
        await self._beacon()
        await self._refresh_map()
        self._tasks += [asyncio.ensure_future(self._beacon_loop())]
        self._tasks += [asyncio.ensure_future(m.serve())
                        for m in self.modules.values()]
        return addr

    async def stop(self) -> None:
        pending = list(self._tasks)
        for t in pending:
            t.cancel()
        # let cancellations land before the messenger goes away, or a
        # module mid-send races the teardown
        await asyncio.gather(*pending, return_exceptions=True)
        await self.msgr.shutdown()

    # -- mon session --------------------------------------------------------
    async def _beacon(self) -> None:
        try:
            await self.msgr.send(self.mon_addr, "mon.0", Message(
                "mgr_beacon", {"name": self.name,
                               "addr": list(self.msgr.addr)}))
        except (ConnectionError, OSError):
            pass

    async def _beacon_loop(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.config["beacon_interval"])
                await self._beacon()
        except asyncio.CancelledError:
            pass

    async def _refresh_map(self) -> None:
        q: asyncio.Queue = asyncio.Queue()

        async def d(conn, msg):
            if msg.type == "osdmap_full":
                await q.put(msg.data["map"])

        self.msgr.add_dispatcher(d)
        try:
            await self.msgr.send(self.mon_addr, "mon.0",
                                 Message("sub_osdmap", {}))
            self.osdmap = OSDMap.from_dict(
                await asyncio.wait_for(q.get(), 10))
        finally:
            self.msgr.dispatchers.remove(d)

    async def mon_command(self, cmd: str, args: dict | None = None):
        self._tid += 1
        tid = self._tid
        fut = asyncio.get_event_loop().create_future()
        self._cmd_waiters[tid] = fut
        try:
            await self.msgr.send(self.mon_addr, "mon.0", Message(
                "mon_command", {"cmd": cmd, "args": args or {},
                                "tid": tid}))
            data = await asyncio.wait_for(fut, 15)
        finally:
            self._cmd_waiters.pop(tid, None)
        if not data.get("ok"):
            raise RuntimeError(data.get("error"))
        return data["result"]

    # -- dispatch -----------------------------------------------------------
    async def _dispatch(self, conn, msg: Message) -> None:
        if msg.type == "osdmap_inc":
            inc = Incremental.from_dict(msg.data["inc"])
            if inc.epoch == self.osdmap.epoch + 1:
                self.osdmap.apply_incremental(inc)
            elif inc.epoch > self.osdmap.epoch:
                self._track(asyncio.ensure_future(self._refresh_map()))
        elif msg.type == "mon_command_reply":
            fut = self._cmd_waiters.get(msg.data.get("tid"))
            if fut is not None and not fut.done():
                fut.set_result(msg.data)
        elif msg.type == "mgr_report":
            # DaemonServer: daemons push perf summaries
            self.daemon_reports[msg.data["daemon"]] = {
                "stamp": time.monotonic(),
                "summary": msg.data.get("summary", {}),
            }
            await conn.send(Message("mgr_report_ack", {}))
        elif msg.type == "mgr_command":
            await self._handle_mgr_command(conn, msg)

    async def _handle_mgr_command(self, conn, msg: Message) -> None:
        prefix = msg.data.get("prefix", "")
        args = msg.data.get("args", {})
        parts = prefix.split(None, 1)
        try:
            mod = self.modules.get(parts[0]) if parts else None
            if mod is None:
                raise ValueError(f"no mgr module {parts[:1]}")
            result = await mod.handle_command(
                parts[1] if len(parts) > 1 else "", args)
            await conn.send(Message("mgr_command_reply",
                                    {"ok": True, "result": result,
                                     "tid": msg.data.get("tid")}))
        except Exception as e:
            await conn.send(Message("mgr_command_reply",
                                    {"ok": False, "error": str(e),
                                     "tid": msg.data.get("tid")}))
