"""Prometheus exposition: shared renderer + the mgr module.

The mgr module serves GET /metrics the way the reference's
src/pybind/mgr/prometheus module does (cluster state from the maps +
per-daemon perf counters from the DaemonServer reports); the same
renderer backs the standalone exporter (src/exporter analog,
tools/exporter.py) which scrapes admin sockets instead.
"""

from __future__ import annotations

import asyncio


def _esc(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def render_metrics(families: dict[str, dict]) -> str:
    """{metric: {"help": str, "type": str,
                 "samples": [(labels_dict, value)]}} -> text format."""
    out: list[str] = []
    for name in sorted(families):
        fam = families[name]
        out.append(f"# HELP {name} {fam.get('help', '')}")
        out.append(f"# TYPE {name} {fam.get('type', 'gauge')}")
        for labels, value in fam["samples"]:
            if labels:
                lbl = ",".join(f'{k}="{_esc(v)}"'
                               for k, v in sorted(labels.items()))
                out.append(f"{name}{{{lbl}}} {value}")
            else:
                out.append(f"{name} {value}")
    return "\n".join(out) + "\n"


def families_from_perf(daemon: str, counters: dict,
                       prefix: str = "ceph") -> dict:
    """Flatten a perf-counter summary into labeled samples."""
    fams: dict[str, dict] = {}
    for key, val in counters.items():
        if isinstance(val, dict):
            val = val.get("value")     # non-counter dicts are skipped,
        if not isinstance(val, (int, float)) \
                or isinstance(val, bool):   # not coerced to a bogus 0
            continue
        name = f"{prefix}_{key}"
        fams.setdefault(name, {"help": f"perf counter {key}",
                               "type": "counter", "samples": []})
        fams[name]["samples"].append(({"ceph_daemon": daemon}, val))
    return fams


def merge_families(*many: dict) -> dict:
    out: dict[str, dict] = {}
    for fams in many:
        for name, fam in fams.items():
            if name in out:
                out[name]["samples"].extend(fam["samples"])
            else:
                out[name] = {"help": fam.get("help", ""),
                             "type": fam.get("type", "gauge"),
                             "samples": list(fam["samples"])}
    return out


class MetricsHttpServer:
    """Tiny hardened GET-only HTTP server (overall request deadline,
    header-count cap).  ``render`` is either the legacy /metrics
    coroutine or, with ``router=True``, a ``(path) -> (status,
    content_type, bytes)`` coroutine -- the dashboard rides the same
    hardened loop instead of hand-rolling a second one."""

    def __init__(self, render, router: bool = False) -> None:
        self._render = render
        self._router = router
        self._server: asyncio.AbstractServer | None = None
        self.addr: tuple[str, int] | None = None

    async def start(self, host: str = "127.0.0.1",
                    port: int = 0) -> tuple[str, int]:
        self._server = await asyncio.start_server(self._conn, host, port)
        self.addr = self._server.sockets[0].getsockname()[:2]
        return self.addr

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _conn(self, reader, writer) -> None:
        try:
            # one overall deadline for the whole request: a per-line
            # timeout lets a byte-dripping client hold the task forever
            loop = asyncio.get_event_loop()
            deadline = loop.time() + 15.0
            line = await asyncio.wait_for(
                reader.readline(), deadline - loop.time())
            for _ in range(200):           # header-count cap
                remaining = deadline - loop.time()
                if remaining <= 0:         # HARD deadline: a dripper
                    return                 # cannot stretch it per-line
                h = await asyncio.wait_for(reader.readline(), remaining)
                if h in (b"\r\n", b"\n", b""):
                    break
            path = line.split()[1].decode() if len(line.split()) > 1 \
                else "/"
            if self._router:
                status, ctype, body = await self._render(
                    path.split("?")[0])
            elif path.rstrip("/") in ("", "/metrics".rstrip("/")):
                body = (await self._render()).encode()
                status = "200 OK"
                ctype = "text/plain; version=0.0.4"
            else:
                body = b"try /metrics\n"
                status = "404 Not Found"
                ctype = "text/plain"
            writer.write((f"HTTP/1.1 {status}\r\n"
                          f"content-type: {ctype}\r\n"
                          f"content-length: {len(body)}\r\n"
                          f"connection: close\r\n\r\n").encode())
            writer.write(body)
            await writer.drain()
        except (ConnectionError, asyncio.TimeoutError,
                asyncio.IncompleteReadError, IndexError, ValueError):
            # ValueError covers LimitOverrunError (oversized lines)
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass
