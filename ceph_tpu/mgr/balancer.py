"""Upmap balancer: even out PGs/OSD by emitting pg_upmap_items.

The mgr balancer module's upmap mode (src/pybind/mgr/balancer,
OSDMap::calc_pg_upmaps): compute the full cluster's PG->OSD mapping,
find the most over/under-full devices, and emit (from, to) upmap items
that move single replicas while respecting the failure domain (no two
replicas of a pg on one host).  The full-cluster mapping recompute is
the `OSDMapMapping`/ParallelPGMapper job (src/osd/OSDMapMapping.h:175)
-- served here by the shared epoch-memoized placement cache
(ceph_tpu/mon/pg_mapping.py): one vectorized CRUSH launch over every
(pool, ps) when the map fits the fused path, a batched scalar sweep
otherwise, identical to what clients are routed by.
"""

from __future__ import annotations

from collections import defaultdict

import numpy as np

from ..crush.types import CRUSH_ITEM_NONE


def _osd_hosts(osdmap) -> dict[int, int]:
    """osd -> host bucket id, from the crush hierarchy."""
    hosts: dict[int, int] = {}
    for b in osdmap.crush.buckets.values():
        for item in b.items:
            if item >= 0:
                hosts[item] = b.id
    return hosts


def full_mapping(osdmap) -> dict[str, list[int]]:
    """pgid -> UP set for every pg of every pool, straight from the
    epoch-memoized placement cache (mon/pg_mapping.py).

    This used to run its own CRUSH sweep WITHOUT the upmap/down-osd
    filtering clients apply, so the balancer scored a mapping nobody
    was actually served from.  Now it reads the exact table
    Objecter.calc_target reads (holes are -1 after normalization)."""
    return {f"{pool_id}.{pg:x}": list(up)
            for pool_id, pg, up, _acting
            in osdmap.placement_cache().iter_all()}


def _counts_of(mapping, eligible) -> dict[int, int]:
    counts: dict[int, int] = defaultdict(int)
    for osds in mapping.values():
        for o in osds:
            if 0 <= o != CRUSH_ITEM_NONE:
                counts[o] += 1
    for o in eligible:
        counts.setdefault(o, 0)
    return counts


def _summary(counts) -> dict:
    vals = list(counts.values()) or [0]
    return {"per_osd": dict(sorted(counts.items())),
            "max": max(vals), "min": min(vals),
            "stddev": round(float(np.std(vals)), 3)}


def _eligible(osdmap) -> list[int]:
    """Balance candidates: up, in, and CRUSH-weighted (a reweight-0
    OSD is being drained -- it must never become a move target)."""
    return [o for o, i in osdmap.osds.items()
            if i.up and i.in_cluster and i.weight > 0]


def pg_distribution(osdmap) -> dict:
    """PGs-per-OSD histogram summary (for before/after comparison)."""
    return _summary(_counts_of(full_mapping(osdmap),
                               _eligible(osdmap)))


def balance(osdmap, max_moves: int = 10) -> dict:
    """One balancer pass: greedy upmap moves from the fullest OSD to
    the emptiest eligible one until balanced or out of moves.

    Eligible target: up+in+weighted, not already in the pg, and on a
    host no other member of the pg occupies (the failure-domain part of
    OSDMap::try_pg_upmap's re-validation; device-class/root constraints
    are not modelled).  Returns {"plans", "before", "after"} from ONE
    full-cluster mapping compute.
    """
    mapping = full_mapping(osdmap)
    hosts = _osd_hosts(osdmap)
    candidates = _eligible(osdmap)
    counts = _counts_of(mapping, candidates)
    before = _summary(counts)
    plans: dict[str, list] = {}
    for _ in range(max_moves):
        order = sorted(candidates, key=lambda o: counts[o])
        low, high = order[0], order[-1]
        if counts[high] - counts[low] <= 1:
            break                     # balanced
        moved = False
        for pgid, osds in mapping.items():
            if high not in osds or low in osds or pgid in plans:
                continue
            others = [o for o in osds
                      if o >= 0 and o not in (high, CRUSH_ITEM_NONE)]
            if hosts.get(low) in {hosts.get(o) for o in others}:
                continue              # would stack replicas on a host
            plans[pgid] = [(high, low)]
            mapping[pgid] = [low if o == high else o for o in osds]
            counts[high] -= 1
            counts[low] += 1
            moved = True
            break
        if not moved:
            break                     # no legal move left
    return {"plans": plans, "before": before,
            "after": _summary(counts)}


def compute_upmaps(osdmap, max_moves: int = 10) -> dict[str, list]:
    return balance(osdmap, max_moves)["plans"]


def compact_items(existing: list, new: list) -> list:
    """Fold new upmap items into an existing chain: (a,b)+(b,c)->(a,c),
    identities drop (OSDMap::calc_pg_upmaps resolves chains the same
    way so per-pg item lists do not grow without bound)."""
    items = [tuple(i) for i in existing]
    for frm, to in (tuple(i) for i in new):
        for idx, (x, y) in enumerate(items):
            if y == frm:
                frm = x
                items.pop(idx)
                break
        if frm != to:
            items.append((frm, to))
    return [list(i) for i in items]
