"""Autotune the MXU-packed GF(2) kernel family on real hardware.

Sweeps the (unpack, mm dtype, pack, tile, group) space of
ceph_tpu/ops/gf2kernels._make_pallas_batch_fn_gN on a device-resident
stripe batch, parity-gates every candidate against the host oracle,
and writes the winner per k to ceph_tpu/ops/gf2_tuned.json -- the
config gf_matmul_batch_device serves by default from then on.

The reference tunes its SIMD technique per-CPU at plugin load
(src/erasure-code/isa/ErasureCodeIsa.cc picks AVX2/AVX512 paths); this
is the TPU equivalent, run once per hardware generation:

    python -m ceph_tpu.tools.ec_autotune --k 8 --m 3 --write
"""

from __future__ import annotations

import argparse
import itertools
import json
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def stage_batch(rng, batch: int, k: int, chunk: int):
    import jax
    import jax.numpy as jnp
    assert batch % 8 == 0, "batch must be a multiple of 8"
    seed_rows = min(batch, 8)
    seed = rng.integers(0, 256, size=(seed_rows, k, chunk),
                        dtype=np.uint8)
    dev = jax.device_put(seed)
    out = jnp.tile(dev, (batch // seed_rows, 1, 1))
    out.block_until_ready()
    return out


def time_fn(fn, w, xd, iters: int = 8) -> float:
    out = fn(w, xd)
    out.block_until_ready()          # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(w, xd)
    out.block_until_ready()
    return (time.perf_counter() - t0) / iters


def sweep(k: int, m: int, batch: int, chunk: int,
          budget_s: float = 600.0) -> list[dict]:
    from ..gf import gen_rs_matrix, gf_matmul
    from ..ops import gf2kernels as G
    import jax.numpy as jnp

    t_start = time.monotonic()
    gen = gen_rs_matrix(k + m, k)
    mat = np.ascontiguousarray(gen[k:], np.uint8)
    rng = np.random.default_rng(0)
    xd = stage_batch(rng, batch, k, chunk)
    # oracle slice for the parity gate
    sample = np.asarray(xd[:2, :, :512])
    want = [gf_matmul(mat, sample[i]) for i in range(2)]

    g_max = G.pick_group(k, batch)
    groups = sorted({g for g in (1, 2, 4) if g <= g_max})
    tiles = [t for t in (4096, 8192, 16384, 32768) if chunk % t == 0]
    results = []
    combos = list(itertools.product(
        groups, ("concat", "bcast"), ("int8", "bf16"), ("vpu", "mxu"),
        tiles))
    log(f"sweeping {len(combos)} configs (k={k} m={m} batch={batch} "
        f"chunk={chunk})")
    for g, unpack, mm, pack, tile in combos:
        if time.monotonic() - t_start > budget_s:
            log("budget exhausted; stopping sweep")
            break
        tag = f"g={g} unpack={unpack} mm={mm} pack={pack} tile={tile}"
        try:
            import os
            fn = G._make_pallas_batch_fn_gN(
                8 * m, k, batch, chunk, g, tile, unpack, mm, pack,
                interpret=bool(os.environ.get(
                    "CEPH_TPU_PALLAS_INTERPRET")))
            w = G._w_gN_device(mat.tobytes(), m, k, g, mm)
            out = fn(w, xd)
            got = np.asarray(out[:2, :, :512])
            if not all(np.array_equal(got[i], want[i]) for i in (0, 1)):
                log(f"  {tag}: PARITY FAIL")
                continue
            dt = time_fn(fn, w, xd)
            gibps = batch * k * chunk / dt / 2**30
            log(f"  {tag}: {gibps:.1f} GiB/s")
            results.append({"g": g, "unpack": unpack, "mm": mm,
                            "pack": pack, "tile": tile,
                            "gibps": round(gibps, 2)})
        except Exception as e:
            log(f"  {tag}: ERROR {type(e).__name__}: {str(e)[:100]}")
    return sorted(results, key=lambda r: -r["gibps"])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--m", type=int, default=3)
    ap.add_argument("--batch", type=int, default=256,
                    help="stripes per launch (rounded to a multiple of 8)")
    ap.add_argument("--chunk", type=int, default=1 << 17)
    ap.add_argument("--budget-s", type=float, default=600.0)
    ap.add_argument("--write", action="store_true",
                    help="persist the winner to gf2_tuned.json")
    args = ap.parse_args(argv)

    import jax
    log(f"backend={jax.default_backend()} devices={jax.devices()}")
    args.batch = max(8, (args.batch // 8) * 8)
    results = sweep(args.k, args.m, args.batch, args.chunk,
                    args.budget_s)
    if not results:
        log("no working config found")
        return 1
    best = results[0]
    print(json.dumps({"k": args.k, "best": best,
                      "top5": results[:5]}, indent=2))
    if args.write:
        from ..ops.gf2kernels import _TUNED_PATH
        try:
            with open(_TUNED_PATH) as f:
                tuned = json.load(f)
        except Exception:
            tuned = {}
        tuned[str(args.k)] = {kk: best[kk] for kk in
                              ("g", "unpack", "mm", "pack", "tile")}
        with open(_TUNED_PATH, "w") as f:
            json.dump(tuned, f, indent=2, sort_keys=True)
        log(f"wrote {_TUNED_PATH}: k={args.k} -> {tuned[str(args.k)]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
