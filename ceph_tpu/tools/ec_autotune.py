"""Autotune the GF(2) kernel families on the live backend.

Two sweeps, both parity-gated against the host oracle:

  * the MXU-packed family: the (unpack, mm dtype, pack, tile, group)
    space of ceph_tpu/ops/gf2kernels._make_pallas_batch_fn_gN on a
    device-resident stripe batch (TPU only -- needs pallas);
  * dense vs scheduled: the dense bit-matmul against the
    CSE-minimized XOR schedule (ops/xor_schedule.py) per (k, m,
    chunk), recording the winner under the "xor_sched" key of
    ceph_tpu/ops/gf2_tuned.json -- the cost model
    (xor_schedule.want_scheduled) serves it by default from then on.
    ``--codes lrc,pmsr`` extends this sweep to the recovery-code
    matrix families (LRC local-parity/local-repair rows, PMSR
    parity/fragment-aggregate matrices): exactly the sparse GF(2)
    shapes where the schedule should win on CPU, keyed by their
    matrix dims (the key the runtime cost model looks up; same dims
    = same kernel family, so the winner transfers).

The reference tunes its SIMD technique per-CPU at plugin load
(src/erasure-code/isa/ErasureCodeIsa.cc picks AVX2/AVX512 paths); this
is the accelerator equivalent, run once per hardware generation:

    python -m ceph_tpu.tools.ec_autotune --k 8 --m 3 --write

``--cpu-smoke`` shrinks the shapes, skips the pallas sweep and runs
the dense-vs-scheduled sweep on the CPU backend, so the sweep harness
itself is exercised by tier-1 (tests/test_xor_schedule.py) instead of
rotting as TPU-only dead code; pair it with ``--out`` to keep smoke
winners out of the real tuned file.
"""

from __future__ import annotations

import argparse
import itertools
import json
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def stage_batch(rng, batch: int, k: int, chunk: int):
    import jax
    import jax.numpy as jnp
    assert batch % 8 == 0, "batch must be a multiple of 8"
    seed_rows = min(batch, 8)
    seed = rng.integers(0, 256, size=(seed_rows, k, chunk),
                        dtype=np.uint8)
    dev = jax.device_put(seed)
    out = jnp.tile(dev, (batch // seed_rows, 1, 1))
    out.block_until_ready()
    return out


def time_fn(fn, w, xd, iters: int = 8) -> float:
    out = fn(w, xd)
    out.block_until_ready()          # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(w, xd)
    out.block_until_ready()
    return (time.perf_counter() - t0) / iters


def sweep(k: int, m: int, batch: int, chunk: int,
          budget_s: float = 600.0) -> list[dict]:
    from ..gf import gen_rs_matrix, gf_matmul
    from ..ops import gf2kernels as G
    import jax.numpy as jnp

    t_start = time.monotonic()
    gen = gen_rs_matrix(k + m, k)
    mat = np.ascontiguousarray(gen[k:], np.uint8)
    rng = np.random.default_rng(0)
    xd = stage_batch(rng, batch, k, chunk)
    # oracle slice for the parity gate
    sample = np.asarray(xd[:2, :, :512])
    want = [gf_matmul(mat, sample[i]) for i in range(2)]

    g_max = G.pick_group(k, batch)
    groups = sorted({g for g in (1, 2, 4) if g <= g_max})
    tiles = [t for t in (4096, 8192, 16384, 32768) if chunk % t == 0]
    results = []
    combos = list(itertools.product(
        groups, ("concat", "bcast"), ("int8", "bf16"), ("vpu", "mxu"),
        tiles))
    log(f"sweeping {len(combos)} configs (k={k} m={m} batch={batch} "
        f"chunk={chunk})")
    for g, unpack, mm, pack, tile in combos:
        if time.monotonic() - t_start > budget_s:
            log("budget exhausted; stopping sweep")
            break
        tag = f"g={g} unpack={unpack} mm={mm} pack={pack} tile={tile}"
        try:
            import os
            fn = G._make_pallas_batch_fn_gN(
                8 * m, k, batch, chunk, g, tile, unpack, mm, pack,
                interpret=bool(os.environ.get(
                    "CEPH_TPU_PALLAS_INTERPRET")))
            w = G._w_gN_device(mat.tobytes(), m, k, g, mm)
            out = fn(w, xd)
            got = np.asarray(out[:2, :, :512])
            if not all(np.array_equal(got[i], want[i]) for i in (0, 1)):
                log(f"  {tag}: PARITY FAIL")
                continue
            dt = time_fn(fn, w, xd)
            gibps = batch * k * chunk / dt / 2**30
            log(f"  {tag}: {gibps:.1f} GiB/s")
            results.append({"g": g, "unpack": unpack, "mm": mm,
                            "pack": pack, "tile": tile,
                            "gibps": round(gibps, 2)})
        except Exception as e:
            log(f"  {tag}: ERROR {type(e).__name__}: {str(e)[:100]}")
    return sorted(results, key=lambda r: -r["gibps"])


def sweep_engines(k: int, m: int, batch: int, chunk: int,
                  iters: int = 8) -> dict | None:
    """Dense vs scheduled on the RS (k, m) parity matrix (the
    headline family): see ``sweep_matrix_engines``."""
    from ..gf import gen_rs_matrix
    gen = gen_rs_matrix(k + m, k)
    return sweep_matrix_engines(
        np.ascontiguousarray(gen[k:], np.uint8), batch, chunk,
        iters=iters)


def code_matrices(codes: list[str],
                  smoke: bool = False) -> list[tuple[str, np.ndarray]]:
    """The recovery-code GF(2^8) matrix families worth a tuned entry:
    LRC local-parity/local-repair rows and PMSR parity/repair-
    aggregate matrices -- the sparse shapes where the CSE-minimized
    schedule should beat the dense contraction on CPU.  Tags name the
    provenance; the tuned keys are derived from the matrix dims (the
    same key ``want_scheduled`` looks up at run time).  Smoke swaps
    the pmsr shape down to k=3 so the tier-1 harness never pays the
    dense k=5 parity matrix's multi-second CSE pass."""
    from ..ec import registry
    out: list[tuple[str, np.ndarray]] = []
    if "lrc" in codes:
        lrc = registry().factory(
            "lrc", {"k": "8", "m": "4", "l": "3"})
        out.append(("lrc_k8m4l3_parity", lrc.parity_matrix))
        # single-loss local repair: the lost chunk over its group
        lost = 0
        src = tuple(sorted(
            lrc.minimum_to_decode({lost},
                                  set(range(16)) - {lost}).keys()))
        out.append(("lrc_k8m4l3_local_repair",
                    lrc.repair_matrix(src, (lost,))))
    if "pmsr" in codes:
        pk, pm = (3, 2) if smoke else (5, 4)
        pmsr = registry().factory("pmsr",
                                  {"k": str(pk), "m": str(pm)})
        out.append((f"pmsr_k{pk}m{pm}_parity", pmsr.parity_matrix))
        helpers = tuple(range(1, 1 + pmsr.d))
        out.append((f"pmsr_k{pk}m{pm}_aggregate",
                    pmsr.aggregate_matrix(0, helpers)))
    return out


def sweep_matrix_engines(mat: np.ndarray, batch: int, lane: int,
                         iters: int = 8,
                         tag: str = "") -> dict | None:
    """Dense vs scheduled on one (matrix, batch, lane) shape: time the
    dense bit-matmul family against the CSE-minimized XOR schedule on
    identical device-resident batches, byte-parity-gate both against
    the host oracle, and return the winner record the cost model
    consumes (None when the scheduled family cannot serve)."""
    import os
    from ..gf import gf_matmul
    from ..ops import gf2kernels as G
    from ..ops import xor_schedule as XS

    mat = np.ascontiguousarray(mat, np.uint8)
    m, k = mat.shape
    rng = np.random.default_rng(0)
    xd = stage_batch(rng, batch, k, lane)
    ncheck = min(512, lane)
    sample = np.asarray(xd[:1, :, :ncheck])
    want = gf_matmul(mat, sample[0])

    def timed(fn) -> tuple[float, np.ndarray]:
        out = fn()
        out.block_until_ready()
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn()
        out.block_until_ready()
        return (time.perf_counter() - t0) / iters, \
            np.asarray(out[:1, :, :ncheck])

    os.environ["CEPH_TPU_XOR_SCHED"] = "0"
    try:
        dt_dense, got_dense = timed(
            lambda: G.gf_matmul_batch_device(mat, xd))
    finally:
        os.environ.pop("CEPH_TPU_XOR_SCHED", None)
    if not np.array_equal(got_dense[0], want):
        log("engine sweep: dense PARITY FAIL")
        return None
    sched = XS.schedule_for(G.bitmatrix_i8(mat))

    def run_sched():
        out = XS.sched_matmul_batch_device(sched, mat, xd, batch, k,
                                           lane)
        if out is None:
            raise RuntimeError("scheduled kernel rejected")
        return out

    try:
        dt_sched, got_sched = timed(run_sched)
    except Exception as e:
        log(f"engine sweep: scheduled ERROR {type(e).__name__}: "
            f"{str(e)[:100]}")
        return None
    if not np.array_equal(got_sched[0], want):
        log("engine sweep: scheduled PARITY FAIL")
        return None
    gibps = lambda dt: batch * k * lane / dt / 2**30  # noqa: E731
    rec = {
        "engine": "scheduled" if dt_sched < dt_dense else "dense",
        "dense_gibps": round(gibps(dt_dense), 3),
        "sched_gibps": round(gibps(dt_sched), 3),
        "sched_terms": sched.n_terms,
        "naive_terms": sched.naive_terms,
        "reduction_pct": round(100 * sched.reduction, 1),
    }
    log(f"engine sweep {tag or f'{k},{m}'} batch={batch} "
        f"lane={lane}: dense={rec['dense_gibps']} GiB/s "
        f"sched={rec['sched_gibps']} GiB/s -> {rec['engine']} "
        f"(xor terms {sched.n_terms}/{sched.naive_terms})")
    return rec


def _write_tuned(path: str, update: dict) -> None:
    try:
        with open(path) as f:
            tuned = json.load(f)
    except Exception:
        tuned = {}
    for key, val in update.items():
        if isinstance(val, dict) and isinstance(tuned.get(key), dict):
            tuned[key].update(val)
        else:
            tuned[key] = val
    with open(path, "w") as f:
        json.dump(tuned, f, indent=2, sort_keys=True)
    log(f"wrote {path}: {sorted(update)}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--m", type=int, default=3)
    ap.add_argument("--batch", type=int, default=256,
                    help="stripes per launch (rounded to a multiple of 8)")
    ap.add_argument("--chunk", type=int, default=1 << 17)
    ap.add_argument("--budget-s", type=float, default=600.0)
    ap.add_argument("--write", action="store_true",
                    help="persist the winners to the tuned file")
    ap.add_argument("--out", default=None,
                    help="tuned-file path (default: the live "
                         "ceph_tpu/ops/gf2_tuned.json)")
    ap.add_argument("--cpu-smoke", action="store_true",
                    help="tier-1 harness mode: tiny shapes, skip the "
                         "pallas sweep, engine sweep only")
    ap.add_argument("--codes", default="",
                    help="comma list of recovery-code matrix families "
                         "to sweep into xor_sched entries (lrc,pmsr): "
                         "local-parity / repair / fragment-aggregate "
                         "matrices keyed by their matrix dims")
    args = ap.parse_args(argv)

    import jax
    log(f"backend={jax.default_backend()} devices={jax.devices()}")
    if args.cpu_smoke:
        args.batch = min(args.batch, 8)
        args.chunk = min(args.chunk, 4096)
    args.batch = max(8, (args.batch // 8) * 8)

    results = []
    if not args.cpu_smoke:
        results = sweep(args.k, args.m, args.batch, args.chunk,
                        args.budget_s)
        if not results:
            log("no working pallas config found")
    iters = 2 if args.cpu_smoke else 8
    engines = sweep_engines(args.k, args.m, args.batch, args.chunk,
                            iters=iters)
    code_recs: dict[str, dict] = {}
    codes = [c for c in args.codes.split(",") if c]
    for tag, mat in code_matrices(codes, smoke=args.cpu_smoke):
        r, c = mat.shape
        # lane at the granularity the runtime launches with: the flat
        # sub-chunk dialect reshapes chunks, so tune at a sub-lane
        lane = max(512, min(args.chunk, 4096)) if args.cpu_smoke \
            else args.chunk
        rec = sweep_matrix_engines(mat, args.batch, lane,
                                   iters=iters, tag=tag)
        if rec is not None:
            rec["tag"] = tag
            code_recs[f"{c},{r}"] = rec
    if not results and engines is None and not code_recs:
        log("no working config found")
        return 1
    report = {"k": args.k, "m": args.m, "chunk": args.chunk,
              "xor_sched": engines}
    if code_recs:
        report["xor_sched_codes"] = code_recs
    if results:
        report["best"] = results[0]
        report["top5"] = results[:5]
    print(json.dumps(report, indent=2))
    if args.write:
        from ..ops.gf2kernels import _TUNED_PATH
        path = args.out or _TUNED_PATH
        update: dict = {}
        if results:
            update[str(args.k)] = {kk: results[0][kk] for kk in
                                   ("g", "unpack", "mm", "pack",
                                    "tile")}
        sched_update = {}
        if engines is not None:
            sched_update.update({
                f"{args.k},{args.m},{args.chunk}": engines,
                f"{args.k},{args.m}": engines,
            })
        sched_update.update(code_recs)
        if sched_update:
            update["xor_sched"] = sched_update
        _write_tuned(path, update)
    return 0


if __name__ == "__main__":
    sys.exit(main())
