"""CLI tools: rados/ceph clients, ec_bench, vstart launcher."""


def parse_addr(s: str) -> tuple[str, int]:
    """'host:port' -> (host, port); bare host gets the default port."""
    host, sep, port = s.rpartition(":")
    if not sep:
        return (s or "127.0.0.1", 6789)
    try:
        return (host or "127.0.0.1", int(port))
    except ValueError:
        raise ValueError(f"bad monitor address {s!r}; want HOST:PORT")
