"""radosgw-admin + radosgw analog: user admin and gateway daemon.

    python -m ceph_tpu.tools.rgw_cli --mon 127.0.0.1:6789 \
        user create --uid alice --display-name "Alice"
    python -m ceph_tpu.tools.rgw_cli --mon 127.0.0.1:6789 \
        serve --port 7480
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from ..client import Rados
from ..rgw import Gateway, RgwStore

POOL = ".rgw"


async def open_store(rados, pg_num=16):
    pools = await rados.pool_list()
    if POOL not in pools:
        await rados.pool_create(POOL, pg_num=pg_num)
    io = await rados.open_ioctx(POOL)
    return RgwStore(io)


async def amain(args) -> int:
    host, port = args.mon.rsplit(":", 1)
    rados = await Rados((host, int(port))).connect()
    try:
        store = await open_store(rados)
        if args.cmd == "user" and args.user_cmd == "create":
            user = await store.create_user(args.uid, args.display_name,
                                           access_key=args.access_key,
                                           secret=args.secret)
            print(json.dumps(user, indent=2))
        elif args.cmd == "bucket" and args.user_cmd == "list":
            for b in await store.list_buckets():
                print(b["name"])
        elif args.cmd == "serve":
            gw = Gateway(store)
            addr = await gw.start(port=args.port)
            print(f"rgw listening on {addr[0]}:{addr[1]}", flush=True)
            stop = asyncio.Event()
            loop = asyncio.get_event_loop()
            import signal
            for s in (signal.SIGINT, signal.SIGTERM):
                loop.add_signal_handler(s, stop.set)
            await stop.wait()
            await gw.stop()
        return 0
    finally:
        await rados.shutdown()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="rgw")
    p.add_argument("--mon", default="127.0.0.1:6789")
    sub = p.add_subparsers(dest="cmd", required=True)
    sp = sub.add_parser("user")
    sp.add_argument("user_cmd", choices=["create"])
    sp.add_argument("--uid", required=True)
    sp.add_argument("--display-name", default="")
    sp.add_argument("--access-key")
    sp.add_argument("--secret")
    sp = sub.add_parser("bucket")
    sp.add_argument("user_cmd", choices=["list"])
    sp = sub.add_parser("serve")
    sp.add_argument("--port", type=int, default=7480)
    args = p.parse_args(argv)
    return asyncio.run(amain(args))


if __name__ == "__main__":
    sys.exit(main())
