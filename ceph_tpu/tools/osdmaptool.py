"""osdmaptool analog (src/tools/osdmaptool.cc): offline OSDMap
inspection and placement simulation.

    # snapshot a live map, then work offline
    python -m ceph_tpu.tools.ceph_cli -m H:P --format json osd dump > map.json
    python -m ceph_tpu.tools.osdmaptool map.json --print
    python -m ceph_tpu.tools.osdmaptool map.json --test-map-pgs
    python -m ceph_tpu.tools.osdmaptool map.json --upmap out.txt

--test-map-pgs maps every PG of every pool through the placement
pipeline and prints the per-OSD distribution (the reference's
workload-simulation mode); --upmap computes balancer upmap items and
writes the equivalent CLI commands (osdmaptool --upmap).
"""

from __future__ import annotations

import argparse
import json
import sys

from ..mon.osdmap import OSDMap


def load_map(path: str) -> OSDMap:
    with open(path) as f:
        return OSDMap.from_dict(json.load(f))


def cmd_print(m: OSDMap) -> None:
    print(f"epoch {m.epoch}")
    print(f"max_osd {m.max_osd}")
    for pid, pool in sorted(m.pools.items()):
        print(f"pool {pid} '{pool.name}' type {pool.type} "
              f"size {pool.size} min_size {pool.min_size} "
              f"pg_num {pool.pg_num}")
    for o, info in sorted(m.osds.items()):
        state = ("up" if info.up else "down") + \
                ("+in" if info.in_cluster else "+out")
        print(f"osd.{o} {state} weight "
              f"{info.weight / 0x10000:.5f} host {info.host}")
    if m.pg_temp:
        print(f"pg_temp entries: {len(m.pg_temp)}")
    if m.pg_upmap_items:
        print(f"pg_upmap_items entries: {len(m.pg_upmap_items)}")


def cmd_test_map_pgs(m: OSDMap, pool_filter: int | None) -> None:
    counts: dict[int, int] = {}
    total = 0
    sizes: dict[int, int] = {}
    # one bulk table build, then array reads -- the exact cached
    # pipeline (upmap, pg_temp, down-filter) clients are routed by
    for pid, pg, _up, acting in m.placement_cache().iter_all():
        if pool_filter is not None and pid != pool_filter:
            continue
        up = [o for o in acting if o >= 0]
        total += 1
        sizes[len(up)] = sizes.get(len(up), 0) + 1
        for o in up:
            counts[o] = counts.get(o, 0) + 1
    print(f"pool pg count: {total}")
    for size, n in sorted(sizes.items()):
        print(f"size {size}\t{n}")
    if counts:
        vals = list(counts.values())
        avg = sum(vals) / len(vals)
        dev = (sum((v - avg) ** 2 for v in vals) / len(vals)) ** 0.5
        for o in sorted(counts):
            print(f"osd.{o}\t{counts[o]}")
        print(f"avg {avg:.1f} stddev {dev:.2f} "
              f"min {min(vals)} max {max(vals)}")


def cmd_upmap(m: OSDMap, out_path: str, max_items: int) -> None:
    from ..mgr.balancer import compute_upmaps
    upmaps = compute_upmaps(m, max_moves=max_items)
    lines = []
    for pgid, items in sorted(upmaps.items()):
        pairs = " ".join(f"{a} {b}" for a, b in items)
        lines.append(f"ceph osd pg-upmap-items {pgid} {pairs}")
    out = "\n".join(lines) + ("\n" if lines else "")
    if out_path == "-":
        sys.stdout.write(out)
    else:
        with open(out_path, "w") as f:
            f.write(out)
    print(f"wrote {len(lines)} upmap item commands", file=sys.stderr)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="osdmaptool")
    p.add_argument("map", help="osdmap json (ceph osd dump output)")
    p.add_argument("--print", action="store_true", dest="do_print")
    p.add_argument("--test-map-pgs", action="store_true")
    p.add_argument("--pool", type=int)
    p.add_argument("--upmap", metavar="FILE")
    p.add_argument("--upmap-max", type=int, default=10)
    args = p.parse_args(argv)
    m = load_map(args.map)
    did = False
    if args.do_print:
        cmd_print(m)
        did = True
    if args.test_map_pgs:
        cmd_test_map_pgs(m, args.pool)
        did = True
    if args.upmap:
        cmd_upmap(m, args.upmap, args.upmap_max)
        did = True
    if not did:
        cmd_print(m)
    return 0


if __name__ == "__main__":
    sys.exit(main())
