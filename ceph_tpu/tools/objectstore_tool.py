"""ceph-objectstore-tool analog: offline surgery on an OSD's store.

Operates directly on a DBStore SQLite file (the OSD must be down, as
the reference requires):

    python -m ceph_tpu.tools.objectstore_tool --data-path osd0.db \
        --op list [--pgid 1.2]
    ... --op info --pgid 1.2 --oid obj1       # size/attrs/omap summary
    ... --op dump --pgid 1.2 --oid obj1       # full object json (data hex)
    ... --op export --pgid 1.2 --file pg.export
    ... --op import --file pg.export          # restore a PG's objects
    ... --op remove --pgid 1.2 --oid obj1
    ... --op meta --pgid 1.2                  # decode the PG's denc meta
"""

from __future__ import annotations

import argparse
import json
import sys

from ..os.store import DBStore
from ..os.transaction import Transaction


def _coll(pgid: str) -> str:
    return f"pg_{pgid}"


def op_list(store, pgid: str | None) -> int:
    for coll in sorted(store.list_collections()):
        if pgid and coll != _coll(pgid):
            continue
        for oid in sorted(store.list_objects(coll)):
            print(json.dumps([coll.removeprefix("pg_"), oid]))
    return 0


def _object_record(store, coll: str, oid: str) -> dict:
    data = store.read(coll, oid)
    return {
        "oid": oid,
        "size": len(data),
        "data": data.hex(),
        "attrs": {k: v.hex() for k, v in store.getattrs(coll,
                                                        oid).items()},
        "omap": {k: v.hex() for k, v in store.omap_get(coll,
                                                       oid).items()},
    }


def op_info(store, pgid: str, oid: str, full: bool) -> int:
    rec = _object_record(store, _coll(pgid), oid)
    if not full:
        rec = {"oid": rec["oid"], "size": rec["size"],
               "attrs": sorted(rec["attrs"]),
               "omap_keys": sorted(rec["omap"])}
    print(json.dumps(rec, indent=1))
    return 0


def op_export(store, pgid: str, path: str) -> int:
    coll = _coll(pgid)
    out = {"pgid": pgid,
           "objects": [_object_record(store, coll, oid)
                       for oid in sorted(store.list_objects(coll))]}
    blob = json.dumps(out).encode()
    if path == "-":
        sys.stdout.buffer.write(blob)
    else:
        with open(path, "wb") as f:
            f.write(blob)
    print(f"exported {len(out['objects'])} objects from pg {pgid}",
          file=sys.stderr)
    return 0


def op_import(store, path: str) -> int:
    raw = sys.stdin.buffer.read() if path == "-" \
        else open(path, "rb").read()
    dump = json.loads(raw)
    coll = _coll(dump["pgid"])
    txn = Transaction()
    if not store.collection_exists(coll):
        txn.create_collection(coll)
    for rec in dump["objects"]:
        oid = rec["oid"]
        txn.remove(coll, oid)
        txn.touch(coll, oid)
        txn.write(coll, oid, 0, bytes.fromhex(rec["data"]))
        for k, v in rec["attrs"].items():
            txn.setattr(coll, oid, k, bytes.fromhex(v))
        omap = {k: bytes.fromhex(v) for k, v in rec["omap"].items()}
        if omap:
            txn.omap_setkeys(coll, oid, omap)
    store.queue_transaction(txn)
    print(f"imported {len(dump['objects'])} objects into "
          f"pg {dump['pgid']}", file=sys.stderr)
    return 0


def op_remove(store, pgid: str, oid: str) -> int:
    txn = Transaction()
    txn.remove(_coll(pgid), oid)
    store.queue_transaction(txn)
    print(f"removed {pgid}/{oid}", file=sys.stderr)
    return 0


def op_meta(store, pgid: str) -> int:
    from ..common.denc import Decoder
    from ..osd.backend import META_OID
    from ..osd.pg_log import PGLog
    from ..osd.types import MissingSet, PGInfo
    omap = store.omap_get(_coll(pgid), META_OID)
    out = {}
    if "info" in omap:
        out["info"] = PGInfo.dedenc(Decoder(omap["info"])).to_dict()
    entry_keys = sorted(k for k in omap if k.startswith("log."))
    if entry_keys:
        # per-entry format (PR 12): one omap key per entry, bounds in
        # "logmeta" (tail/head as EVersion lists)
        head = tail = [0, 0]
        if "logmeta" in omap:
            tail, head = json.loads(omap["logmeta"])
        out["log"] = {"head": list(head), "tail": list(tail),
                      "entries": len(entry_keys)}
    elif "log" in omap:
        log = PGLog.dedenc(Decoder(omap["log"]))
        out["log"] = {"head": log.head.to_list(),
                      "tail": log.tail.to_list(),
                      "entries": len(log.entries)}
    if "missing" in omap:
        out["missing"] = MissingSet.dedenc(
            Decoder(omap["missing"])).to_dict()
    print(json.dumps(out, indent=1))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ceph-objectstore-tool")
    p.add_argument("--data-path", required=True,
                   help="DBStore sqlite file (daemon must be down)")
    p.add_argument("--op", required=True,
                   choices=["list", "info", "dump", "export", "import",
                            "remove", "meta"])
    p.add_argument("--pgid")
    p.add_argument("--oid")
    p.add_argument("--file", default="-")
    args = p.parse_args(argv)
    store = DBStore(args.data_path)
    store.mount()
    need_pg = {"info", "dump", "export", "remove", "meta"}
    if args.op in need_pg and not args.pgid:
        p.error(f"--op {args.op} requires --pgid")
    if args.op in ("info", "dump", "remove") and not args.oid:
        p.error(f"--op {args.op} requires --oid")
    if args.op == "list":
        return op_list(store, args.pgid)
    if args.op == "info":
        return op_info(store, args.pgid, args.oid, full=False)
    if args.op == "dump":
        return op_info(store, args.pgid, args.oid, full=True)
    if args.op == "export":
        return op_export(store, args.pgid, args.file)
    if args.op == "import":
        return op_import(store, args.file)
    if args.op == "remove":
        return op_remove(store, args.pgid, args.oid)
    if args.op == "meta":
        return op_meta(store, args.pgid)
    return 2


if __name__ == "__main__":
    sys.exit(main())
