"""Standalone prometheus exporter (src/exporter analog).

Scrapes every daemon admin socket in a directory (``perf dump`` +
``status``) and serves the aggregate on GET /metrics -- the
node-local exporter deployment shape, no mgr required.

    python -m ceph_tpu.tools.exporter --asok-dir /tmp/cluster \
        --port 9926
"""

from __future__ import annotations

import argparse
import asyncio
import sys
from pathlib import Path

from ..common.admin_socket import admin_command
from ..mgr.prometheus import (
    MetricsHttpServer, families_from_perf, merge_families,
    render_metrics,
)


class Exporter:
    def __init__(self, asok_dir: str) -> None:
        self.asok_dir = Path(asok_dir)

    async def render(self) -> str:
        fams = []
        up = {"help": "admin socket reachable", "type": "gauge",
              "samples": []}
        for sock in sorted(self.asok_dir.glob("*.asok")):
            daemon = sock.name[:-len(".asok")]
            try:
                dump = await asyncio.wait_for(
                    admin_command(str(sock), "perf dump"), 5)
                up["samples"].append(({"ceph_daemon": daemon}, 1))
            except (OSError, asyncio.TimeoutError, ValueError):
                up["samples"].append(({"ceph_daemon": daemon}, 0))
                continue
            for subsys, counters in (dump or {}).items():
                flat = {}
                for key, val in counters.items():
                    if isinstance(val, dict) and "avgcount" in val:
                        flat[f"{subsys}_{key}_count"] = val["avgcount"]
                        flat[f"{subsys}_{key}_sum"] = val.get("sum", 0)
                    else:
                        flat[f"{subsys}_{key}"] = val
                fams.append(families_from_perf(daemon, flat,
                                               prefix="ceph"))
        return render_metrics(merge_families({"ceph_daemon_up": up},
                                             *fams))


async def amain(args) -> int:
    exp = Exporter(args.asok_dir)
    srv = MetricsHttpServer(exp.render)
    addr = await srv.start(host=args.host, port=args.port)
    print(f"exporter listening on http://{addr[0]}:{addr[1]}/metrics",
          flush=True)
    stop = asyncio.Event()
    import signal
    loop = asyncio.get_event_loop()
    for s in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(s, stop.set)
    await stop.wait()
    await srv.stop()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ceph-exporter")
    p.add_argument("--asok-dir", required=True,
                   help="directory of daemon admin sockets")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9926)
    args = p.parse_args(argv)
    return asyncio.run(amain(args))


if __name__ == "__main__":
    sys.exit(main())
