"""Datapath bench rig: the OSD shard data spine, cached vs host path.

Drives write -> read-verify -> scrub -> degraded-read over REAL
BlockStores (one per shard, checksum-on-read, WAL group commit) with
the production primitives -- StripeInfo/CodecBatcher encode+decode
launches, fused write-time CRCs, and the DeviceShardCache
(os/device_cache.py) -- twice over identical inputs:

* **baseline** (``cached=False``): every consumer round-trips the
  store, exactly as the pre-cache pipeline did -- shard reads pay
  pread + per-block checksum verify + extent assembly, every gathered
  shard is re-hashed against its tag, scrub reads every shard back;
* **cached**: the write's encoded shards flow into residency, and the
  read-verify / scrub / degraded-decode phases serve from the cache --
  the ``datapath`` perf counters prove the steady phases move ZERO
  shard bytes through the store.

Byte-identity is asserted between the two runs (and against the
source data) before any number is reported -- a throughput without
parity is meaningless, as everywhere else in this repo.
"""

from __future__ import annotations

import asyncio
import os
import shutil
import tempfile
import time

import numpy as np

from ..ec import registry
from ..ops.crc32c_batch import PERF as INTEGRITY_PERF
from ..ops.crc32c_batch import crc32c_batch, crc32c_rows
from ..os.blockstore import BlockStore
from ..os.device_cache import DeviceShardCache, PERF as DATAPATH_PERF
from ..os.transaction import Transaction
from ..osd.codec_batcher import CodecBatcher
from ..osd.ec_util import StripeInfo

COLL = "pg_dp"
SIZE_XATTR = "_size"
CRC_XATTR = "_crc"


class _Rig:
    """k+m shard stores + a codec batcher + (optionally) shard caches:
    the single-process rendering of one EC PG's data plane."""

    def __init__(self, k: int, m: int, stripe_unit: int,
                 cached: bool, base_dir: str,
                 cache_bytes: int = 256 << 20) -> None:
        self.codec = registry().factory(
            "tpu", {"k": str(k), "m": str(m),
                    "technique": "reed_sol_van"})
        self.sinfo = StripeInfo.for_codec(self.codec,
                                          stripe_unit=stripe_unit)
        self.k, self.m = k, m
        self.batcher = CodecBatcher(max_batch=64, flush_timeout=0.05)
        self.cached = cached
        self.stores: list[BlockStore] = []
        for i in range(k + m):
            st = BlockStore(os.path.join(base_dir, f"shard{i}"))
            if cached:
                st.attach_shard_cache(DeviceShardCache(
                    max_bytes=cache_bytes))
            st.mount()
            st.queue_transaction(
                Transaction().create_collection(COLL))
            self.stores.append(st)
        # oid -> (size, shard_len, per-shard crc tags)
        self.meta: dict[str, tuple[int, int, list[int]]] = {}

    def close(self) -> None:
        self.batcher.close()
        for st in self.stores:
            st.umount()

    # -- phases ---------------------------------------------------------------
    async def write(self, objects: dict[str, bytes]) -> None:
        """Encode (fused CRC) + commit every object; the encode output
        flows into residency when caching is on.  Commits coalesce into
        one transaction per shard store (the group-commit shape)."""
        sw = self.sinfo.stripe_width

        async def enc(oid, data):
            padded = data + b"\0" * (
                self.sinfo.logical_to_next_stripe_offset(len(data))
                - len(data))
            shards, crcs = await self.sinfo.encode_async(
                self.codec, padded, batcher=self.batcher,
                with_crc=True)
            return oid, data, shards, crcs

        encoded = await asyncio.gather(
            *(enc(oid, data) for oid, data in objects.items()))
        txns = [Transaction() for _ in self.stores]
        puts = []
        for oid, data, shards, crcs in encoded:
            shard_len = self.sinfo.object_size_to_shard_size(len(data))
            self.meta[oid] = (len(data), shard_len,
                              [int(crcs[s]) for s in range(len(
                                  self.stores))])
            for s, txn in enumerate(txns):
                buf = shards[s].tobytes()
                txn.write(COLL, oid, 0, buf)
                txn.setattr(COLL, oid, SIZE_XATTR,
                            str(len(data)).encode())
                txn.setattr(COLL, oid, CRC_XATTR,
                            str(int(crcs[s])).encode())
                if self.cached:
                    puts.append((s, oid, shards[s], len(data),
                                 int(crcs[s])))
        for st, txn in zip(self.stores, txns):
            st.queue_transaction(txn)
        for s, oid, buf, size, crc in puts:
            self.stores[s].shard_cache.put(
                COLL, oid, buf, size=size, ver=(1, 1), shard=s,
                crc=crc)

    def _shard(self, s: int, oid: str) -> np.ndarray:
        """One shard's bytes: residency first, else the store's
        checksum-on-read path (counted as a host round trip).  The
        baseline also pays the identity-xattr lookups the resident
        entry carries for free -- exactly what ``_local_entry``
        replaced in the OSD read path."""
        st = self.stores[s]
        if self.cached:
            e = st.shard_cache.get(COLL, oid)
            if e is not None:
                return e.buf
        raw = st.read(COLL, oid, 0, None)
        st.getattr(COLL, oid, SIZE_XATTR)
        st.getattr(COLL, oid, CRC_XATTR)
        DATAPATH_PERF.inc("host_reads")
        DATAPATH_PERF.inc("host_bytes_read", len(raw))
        return np.frombuffer(raw, np.uint8)

    async def read_verify(self, oids: list[str]) -> dict[str, bytes]:
        """The client read path: gather the k data shards, verify tags
        (residency is trusted -- verified at write time), interleave
        logical bytes.  Objects submit CONCURRENTLY so their decode
        work coalesces in the batcher, as concurrent client ops do."""
        async def one(oid):
            bufs = {s: self._shard(s, oid) for s in range(self.k)}
            if not self.cached:
                tags = self.meta[oid][2]
                got = crc32c_batch([bufs[s] for s in range(self.k)])
                for s in range(self.k):
                    if int(got[s]) != tags[s]:
                        raise RuntimeError(f"tag mismatch {oid}/{s}")
            data = await self.sinfo.reconstruct_logical_async(
                self.codec, bufs, batcher=self.batcher)
            return oid, data[:self.meta[oid][0]]

        return dict(await asyncio.gather(*(one(o) for o in oids)))

    async def scrub(self, oids: list[str]) -> None:
        """Deep-scrub verify.

        Cached: the write-time tags were computed IN the encode launch
        that produced the parity, so verifying every resident shard's
        CRC against its tag in ONE batched pass attests the parity
        relationship transitively -- zero store reads, zero re-encode
        (the scrub_ec fast path).  Baseline: the pre-cache deep scrub
        -- read every shard back through the store, reconstruct the
        logical object, RE-ENCODE it, byte-compare every stored shard
        against the canonical encode."""
        if self.cached:
            rows, want = [], []
            for oid in oids:
                tags = self.meta[oid][2]
                for s in range(len(self.stores)):
                    rows.append(self._shard(s, oid))
                    want.append(tags[s])
            lens = {r.size for r in rows}
            if len(lens) == 1:
                got = crc32c_rows(np.stack(rows))
            else:
                got = crc32c_batch(rows)
            bad = [i for i in range(len(rows))
                   if int(got[i]) != want[i]]
            if bad:
                raise RuntimeError(f"scrub mismatch at {bad[:4]}")
            DATAPATH_PERF.inc("scrub_fast_verifies", len(oids))
            return

        async def one(oid):
            stored = {s: self._shard(s, oid)
                      for s in range(len(self.stores))}
            logical = await self.sinfo.reconstruct_logical_async(
                self.codec, {s: stored[s] for s in range(self.k)},
                batcher=self.batcher)
            canonical = await self.sinfo.encode_async(
                self.codec, logical, batcher=self.batcher)
            for s in range(len(self.stores)):
                if not np.array_equal(canonical[s], stored[s]):
                    raise RuntimeError(f"scrub mismatch {oid}/{s}")

        await asyncio.gather(*(one(o) for o in oids))

    async def degraded_read(self, oids: list[str],
                            down: int) -> dict[str, bytes]:
        """Reads with data shard ``down`` erased: decode from the k
        surviving shards minimum_to_decode picks (cache-resident when
        on) and rebuild the logical bytes.  Concurrent submission, so
        every object's reconstruction shares one decode launch."""
        keep = [s for s in range(len(self.stores)) if s != down][
            :self.k]

        async def one(oid):
            survivors = {s: self._shard(s, oid) for s in keep}
            if not self.cached:
                tags = self.meta[oid][2]
                got = crc32c_batch([survivors[s] for s in keep])
                for s, g in zip(keep, got):
                    if int(g) != tags[s]:
                        raise RuntimeError(f"tag mismatch {oid}/{s}")
            data = await self.sinfo.reconstruct_logical_async(
                self.codec, survivors, batcher=self.batcher)
            return oid, data[:self.meta[oid][0]]

        return dict(await asyncio.gather(*(one(o) for o in oids)))


async def _drive(cached: bool, *, k: int, m: int, n_objects: int,
                 obj_bytes: int, passes: int, reads_per_pass: int,
                 stripe_unit: int, base_dir: str,
                 seed: int = 7) -> dict:
    rng = np.random.default_rng(seed)
    objects = {
        f"obj-{i:04d}": rng.integers(
            0, 256, obj_bytes, dtype=np.uint8).tobytes()
        for i in range(n_objects)}
    oids = sorted(objects)
    rig = _Rig(k, m, stripe_unit, cached, base_dir)
    phases: dict[str, dict] = {}
    digests: dict[str, int] = {}
    try:
        def snap():
            return {key: DATAPATH_PERF.get(key) for key in
                    ("hits", "misses", "host_reads",
                     "host_bytes_read", "host_bytes_avoided",
                     "evictions")} | {
                "scalar_calls": INTEGRITY_PERF.get("scalar_calls")}

        async def timed(name, fn, nbytes):
            before = snap()
            t0 = time.perf_counter()
            res = fn()
            if asyncio.iscoroutine(res):
                res = await res
            dt = time.perf_counter() - t0
            after = snap()
            phases[name] = {
                "seconds": round(dt, 4),
                "GiBps": round(nbytes / dt / 2**30, 3),
                "bytes": nbytes,
                "counters": {key: after[key] - before[key]
                             for key in after}}
            return res

        logical = n_objects * obj_bytes
        stored = sum(rig.sinfo.object_size_to_shard_size(obj_bytes)
                     for _ in range(k + m)) * n_objects
        # degraded reads hit a subset: with one shard down, only the
        # objects a client actually touches during the recovery window
        # pay the decode -- not the whole population every pass
        degr_oids = oids[:max(2, len(oids) // 12)]
        await timed("write", lambda: rig.write(objects), logical)
        reads = degraded = {}
        for p in range(passes):
            # the steady-state serving mix: hot read-verifies (the
            # dominant op in a Zipf read-mostly workload), a deep-scrub
            # verify sweep, and degraded-read decodes
            for r in range(reads_per_pass):
                reads = await timed(
                    f"read_verify_{p}_{r}",
                    lambda: rig.read_verify(oids), logical)
            await timed(f"scrub_{p}", lambda: rig.scrub(oids), stored)
            degraded = await timed(
                f"degraded_read_{p}",
                lambda: rig.degraded_read(degr_oids, down=0),
                len(degr_oids) * obj_bytes)
        # byte-identity gates: reads and degraded reads must equal the
        # source bytes exactly
        for oid in oids:
            if reads[oid] != objects[oid]:
                raise RuntimeError(f"read parity failure {oid}")
        for oid in degr_oids:
            if degraded[oid] != objects[oid]:
                raise RuntimeError(
                    f"degraded-read parity failure {oid}")
        import zlib
        digests = {oid: zlib.crc32(reads[oid]) for oid in oids}
        digests.update({f"{oid}@degraded": zlib.crc32(degraded[oid])
                        for oid in degr_oids})
    finally:
        rig.close()
    total_s = sum(ph["seconds"] for ph in phases.values())
    total_b = sum(ph["bytes"] for ph in phases.values())
    steady = {key: sum(
        ph["counters"][key] for name, ph in phases.items()
        if not name.startswith("write"))
        for key in ("hits", "host_bytes_read", "host_reads",
                    "host_bytes_avoided", "scalar_calls")}
    return {"cached": cached,
            "end_to_end_GiBps": round(total_b / total_s / 2**30, 3),
            "seconds": round(total_s, 4),
            "bytes": total_b,
            "phases": phases,
            "steady_counters": steady,
            "digests": digests}


def _bench_dir() -> str:
    """Shard stores live on tmpfs when available: the bench measures
    the DATA PATH, not the container filesystem's fsync latency (which
    both sides pay identically in the write phase)."""
    for base in ("/dev/shm", None):
        try:
            return tempfile.mkdtemp(prefix="ceph_tpu_dp_", dir=base)
        except OSError:
            continue
    return tempfile.mkdtemp(prefix="ceph_tpu_dp_")


async def run_datapath_bench(*, k: int = 4, m: int = 2,
                             n_objects: int = 24,
                             obj_bytes: int = 256 << 10,
                             passes: int = 10,
                             reads_per_pass: int = 5,
                             stripe_unit: int = 4096,
                             keep_dirs: bool = False) -> dict:
    """Both drives over identical inputs + the comparison report.

    Gates (the caller turns violations into a non-zero exit):
    * byte identity: cached and baseline reads/degraded-reads return
      identical bytes (and both equal the source data);
    * cache effectiveness: hit-rate > 0 and the cached steady phases
      (read-verify / scrub / degraded-read) moved ZERO bytes through
      the store;
    * zero scalar CRC calls in the steady phases (the write phase's
      WAL record framing CRCs are metadata, not shard payload).
    """
    base_dir = _bench_dir()
    try:
        kwargs = dict(k=k, m=m, n_objects=n_objects,
                      obj_bytes=obj_bytes, passes=passes,
                      reads_per_pass=reads_per_pass,
                      stripe_unit=stripe_unit)
        # warmup: one full-shape baseline drive compiles every launch
        # family (write encode, scrub re-encode, degraded decode) at
        # the SAME batch buckets the timed drives use, so neither side
        # pays first-run jit costs -- compile asymmetry would flatter
        # whichever drive runs second
        await _drive(False, base_dir=os.path.join(base_dir, "warm"),
                     **{**kwargs, "passes": 1, "reads_per_pass": 1})
        baseline = await _drive(False, base_dir=os.path.join(
            base_dir, "base"), **kwargs)
        cached = await _drive(True, base_dir=os.path.join(
            base_dir, "cached"), **kwargs)
    finally:
        if not keep_dirs:
            shutil.rmtree(base_dir, ignore_errors=True)
    if baseline["digests"] != cached["digests"]:
        raise RuntimeError(
            "byte-identity failure: cached reads differ from the "
            "host-round-trip baseline")
    for run in (baseline, cached):
        run.pop("digests")
    steady = cached["steady_counters"]
    ratio = (cached["end_to_end_GiBps"]
             / max(baseline["end_to_end_GiBps"], 1e-9))
    return {
        "k": k, "m": m, "n_objects": n_objects,
        "obj_bytes": obj_bytes, "passes": passes,
        "reads_per_pass": reads_per_pass,
        "datapath_GiBps": cached["end_to_end_GiBps"],
        "baseline_GiBps": baseline["end_to_end_GiBps"],
        "vs_host_roundtrip": round(ratio, 2),
        "cache_hits": steady["hits"],
        "steady_host_bytes_read": steady["host_bytes_read"],
        "steady_host_reads": steady["host_reads"],
        "host_bytes_avoided": steady["host_bytes_avoided"],
        "scalar_calls_on_batched_paths": steady["scalar_calls"],
        "parity": "ok",
        "cached_run": cached,
        "baseline_run": baseline,
    }
