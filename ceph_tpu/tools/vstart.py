"""vstart-style single-host cluster launcher (src/vstart.sh analog).

Boots one monitor + N OSDs in one asyncio process and serves until
SIGINT/SIGTERM.  With --store-dir, OSDs use SQLite-backed DBStores so
the cluster survives restarts (crash-recovery via WAL).

    python -m ceph_tpu.tools.vstart --osds 3 --mon-port 6789

Multi-process deployments (the qa/standalone ceph-helpers.sh shape)
run one DAEMON per process instead:

    python -m ceph_tpu.tools.vstart --role mon --mon-port 6789 \
        --store-dir /var/lib/c1
    python -m ceph_tpu.tools.vstart --role osd \
        --mon-addr 127.0.0.1:6789 --osd-index 0 --store block \
        --store-dir /var/lib/c1
"""

from __future__ import annotations

import argparse
import asyncio
import os
import signal
import sys

from ..mon import Monitor
from ..os.store import DBStore, MemStore
from ..osd import OSD


def _make_store(args, name: str):
    if not args.store_dir or args.store == "mem":
        return MemStore()
    if args.store == "block":
        from ..os.blockstore import BlockStore
        return BlockStore(os.path.join(args.store_dir, name))
    if args.store == "kv":
        from ..os.kvstore import KVStore
        return KVStore(os.path.join(args.store_dir, f"{name}.kv.db"))
    return DBStore(os.path.join(args.store_dir, f"{name}.db"))


async def _serve_until_signal(banner: str) -> None:
    print(banner, flush=True)
    stop = asyncio.Event()
    loop = asyncio.get_event_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()


async def run_mon(args) -> None:
    """One monitor in THIS process (multi-process deployment role)."""
    mon = Monitor(rank=0,
                  store_path=(os.path.join(args.store_dir, "mon.db")
                              if args.store_dir else ":memory:"),
                  config={"mon_osd_min_down_reporters":
                          args.min_down_reporters},
                  admin_socket_path=(
                      os.path.join(args.asok_dir or args.store_dir,
                                   "mon.0.asok")
                      if (args.asok_dir or args.store_dir) else None))
    addr = await mon.start(port=args.mon_port)
    await _serve_until_signal(f"mon.0 at {addr[0]}:{addr[1]}")
    await mon.stop()


async def run_osd(args) -> None:
    """One OSD in THIS process, booting to --mon-addr."""
    host, _, port = args.mon_addr.partition(":")
    store = _make_store(args, f"osd{args.osd_index}")
    asok = args.asok_dir or args.store_dir
    osd = OSD(host=f"host{args.osd_index % args.hosts}", store=store,
              whoami=args.osd_index if args.cephx_key else None,
              config={"osd_heartbeat_interval": 0.5,
                      "osd_heartbeat_grace": 4.0},
              cephx_key=args.cephx_key,
              require_ticket=bool(args.cephx_key),
              admin_socket_path=(
                  os.path.join(asok, f"osd.{args.osd_index}.asok")
                  if asok else None))
    wid = await osd.start((host, int(port)))
    await _serve_until_signal(
        f"osd.{wid} up ({args.store} store)")
    await osd.stop()


async def run_cluster(args) -> None:
    asok_dir = args.asok_dir or args.store_dir
    mon = Monitor(rank=0,
                  store_path=(os.path.join(args.store_dir, "mon.db")
                              if args.store_dir else ":memory:"),
                  config={"mon_osd_min_down_reporters":
                          args.min_down_reporters},
                  admin_socket_path=(
                      os.path.join(asok_dir, "mon.0.asok")
                      if asok_dir else None))
    addr = await mon.start(port=args.mon_port)
    print(f"mon.0 at {addr[0]}:{addr[1]}", flush=True)
    osds = []
    for i in range(args.osds):
        store = _make_store(args, f"osd{i}")
        cephx_key = None
        if args.cephx:
            # register the OSD's entity at the mon and boot with
            # ticket enforcement (clients then need authenticate()).
            # whoami is pinned to i so the registered entity name
            # matches the identity the OSD authenticates as even when
            # a durable mon remembers earlier incarnations
            rec = await mon.handle_command(
                "auth get-or-create", {"entity": f"osd.{i}"})
            cephx_key = rec["key"]
        osd = OSD(host=f"host{i % args.hosts}", store=store,
                  whoami=i if args.cephx else None,
                  config={"osd_heartbeat_interval": 0.5,
                          "osd_heartbeat_grace": 4.0},
                  cephx_key=cephx_key,
                  require_ticket=bool(cephx_key),
                  admin_socket_path=(
                      os.path.join(asok_dir, f"osd.{i}.asok")
                      if asok_dir else None))
        wid = await osd.start(addr)
        print(f"osd.{wid} up ({'db' if args.store_dir else 'mem'} store, "
              f"host{i % args.hosts})", flush=True)
        osds.append(osd)
    mgr = None
    if args.mgr:
        from ..mgr import Mgr
        mgr = Mgr(config={"balancer_active": True})
        await mgr.start(addr)
        print("mgr.x active (balancer on)", flush=True)
    mdss = []
    for i in range(args.mds):
        from ..mds import MDS
        mds_key = None
        if args.cephx:
            rec = await mon.handle_command(
                "auth get-or-create",
                {"entity": f"mds.{chr(ord('a') + i)}"})
            mds_key = rec["key"]
        m = MDS(name=chr(ord("a") + i), cephx_key=mds_key)
        await m.start(addr)
        mdss.append(m)
        print(f"mds.{m.name} up (standby)", flush=True)
    if args.cephx:
        print("cephx REQUIRED on the osds: clients must "
              "`await rados.authenticate(entity, key)` after an "
              "`auth get-or-create` at the mon", flush=True)
    print(f"cluster ready: 1 mon, {len(osds)} osds"
          f"{', 1 mgr' if mgr else ''}"
          f"{f', {len(mdss)} mds' if mdss else ''} -- "
          f"rados -m {addr[0]}:{addr[1]} lspools", flush=True)

    stop = asyncio.Event()
    loop = asyncio.get_event_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    print("shutting down...", flush=True)
    for m in mdss:
        await m.stop()
    if mgr is not None:
        await mgr.stop()
    for osd in osds:
        await osd.stop()
    await mon.stop()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="vstart")
    p.add_argument("--osds", type=int, default=3)
    p.add_argument("--hosts", type=int, default=3,
                   help="spread OSDs over N crush hosts")
    p.add_argument("--mon-port", type=int, default=6789)
    p.add_argument("--mds", type=int, default=0,
                   help="start N metadata servers (cephfs)")
    p.add_argument("--store-dir", default=None,
                   help="directory for durable SQLite stores")
    p.add_argument("--asok-dir", default=None,
                   help="directory for admin sockets (default store-dir)")
    p.add_argument("--min-down-reporters", type=int, default=2)
    p.add_argument("--mgr", action="store_true", default=True,
                   help="start a mgr daemon (balancer active; on by "
                        "default, disable with --no-mgr)")
    p.add_argument("--no-mgr", dest="mgr", action="store_false")
    p.add_argument("--role", choices=("all", "mon", "osd"),
                   default="all",
                   help="run the whole cluster in-process (all) or "
                        "ONE daemon per process (mon/osd)")
    p.add_argument("--mon-addr", default=None,
                   help="mon address for --role osd (host:port)")
    p.add_argument("--osd-index", type=int, default=0)
    p.add_argument("--cephx", action="store_true",
                   help="OSDs enforce cephx tickets (--role all)")
    p.add_argument("--cephx-key", default=None,
                   help="--role osd: this daemon's entity key from "
                        "`auth get-or-create entity=osd.<index>`")
    p.add_argument("--store", choices=("mem", "db", "block", "kv"),
                   default="db",
                   help="store backend when --store-dir is set")
    args = p.parse_args(argv)
    if args.store_dir:
        os.makedirs(args.store_dir, exist_ok=True)
    if args.role == "osd" and not args.mon_addr:
        p.error("--role osd requires --mon-addr host:port")
    if args.cephx and args.role != "all":
        p.error("--cephx applies to --role all; per-daemon roles "
                "take --cephx-key (from `auth get-or-create`)")
    runner = {"all": run_cluster, "mon": run_mon,
              "osd": run_osd}[args.role]
    try:
        asyncio.run(runner(args))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
