"""``rados``-style CLI over the librados-shaped client.

Subcommand surface mirrors the reference's src/tools/rados/rados.cc:
lspools/mkpool/rmpool, put/get/append/rm/stat/truncate, ls,
setxattr/getxattr/rmxattr/listxattr, setomapval/listomapvals/rmomapkey,
bench.  Usage: python -m ceph_tpu.tools.rados_cli -m HOST:PORT <cmd> ...
"""

from __future__ import annotations

import argparse
import asyncio
import sys
import time

from . import parse_addr
from ..client import Rados, RadosError


async def _run(args) -> int:
    rados = Rados(parse_addr(args.mon), name="client.rados-cli")
    try:
        await rados.connect()
    except (ConnectionError, OSError, TimeoutError) as e:
        print(f"error: cannot reach monitor at {args.mon}: {e}",
              file=sys.stderr)
        return 1
    try:
        return await _dispatch(rados, args)
    except (RadosError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        await rados.shutdown()


async def _dispatch(rados: Rados, args) -> int:
    cmd = args.cmd
    if cmd == "lspools":
        for name in await rados.pool_list():
            print(name)
        return 0
    if cmd == "mkpool":
        pid = await rados.pool_create(args.pool, pg_num=args.pg_num,
                                      pool_type=args.pool_type,
                                      erasure_code_profile=args.profile)
        print(f"pool {args.pool} created (id {pid})")
        return 0
    if cmd == "rmpool":
        await rados.pool_delete(args.pool)
        print(f"pool {args.pool} removed")
        return 0

    ioctx = await rados.open_ioctx(args.pool)
    if cmd == "put":
        data = (sys.stdin.buffer.read() if args.infile == "-"
                else open(args.infile, "rb").read())
        await ioctx.write_full(args.obj, data)
        return 0
    if cmd == "get":
        data = await ioctx.read(args.obj)
        if args.outfile == "-":
            sys.stdout.buffer.write(data)
        else:
            open(args.outfile, "wb").write(data)
        return 0
    if cmd == "append":
        data = (sys.stdin.buffer.read() if args.infile == "-"
                else open(args.infile, "rb").read())
        await ioctx.append(args.obj, data)
        return 0
    if cmd == "ls":
        for oid in await ioctx.list_objects():
            print(oid)
        return 0
    if cmd == "rm":
        await ioctx.remove(args.obj)
        return 0
    if cmd == "stat":
        st = await ioctx.stat(args.obj)
        print(f"{args.pool}/{args.obj} size {st['size']}")
        return 0
    if cmd == "truncate":
        await ioctx.truncate(args.obj, args.size)
        return 0
    if cmd == "setxattr":
        await ioctx.set_xattr(args.obj, args.name, args.value.encode())
        return 0
    if cmd == "getxattr":
        sys.stdout.buffer.write(await ioctx.get_xattr(args.obj, args.name))
        print()
        return 0
    if cmd == "rmxattr":
        await ioctx.rm_xattr(args.obj, args.name)
        return 0
    if cmd == "listxattr":
        for k in sorted(await ioctx.get_xattrs(args.obj)):
            print(k)
        return 0
    if cmd == "setomapval":
        await ioctx.set_omap(args.obj, {args.name: args.value.encode()})
        return 0
    if cmd == "listomapvals":
        for k, v in sorted((await ioctx.get_omap(args.obj)).items()):
            print(f"{k}\n value ({len(v)} bytes):\n{v!r}")
        return 0
    if cmd == "rmomapkey":
        await ioctx.rm_omap_keys(args.obj, [args.name])
        return 0
    if cmd == "bench":
        return await _bench(ioctx, args)
    print(f"unknown command {cmd}", file=sys.stderr)
    return 2


async def _bench(ioctx, args) -> int:
    """radosbench-style write-throughput loop (objects cleaned up
    afterwards)."""
    size = args.obj_size
    payload = b"\xa5" * size
    t0 = time.perf_counter()
    n = 0
    deadline = t0 + args.seconds
    while time.perf_counter() < deadline:
        await ioctx.write_full(f"bench_{n}", payload)
        n += 1
    dt = time.perf_counter() - t0
    mb = n * size / 1e6
    print(f"wrote {n} x {size}B in {dt:.2f}s = {mb/dt:.2f} MB/s "
          f"({n/dt:.1f} iops)")
    for i in range(n):
        await ioctx.remove(f"bench_{i}")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="rados")
    p.add_argument("-m", "--mon", default="127.0.0.1:6789",
                   help="monitor host:port")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("lspools")
    sp = sub.add_parser("mkpool")
    sp.add_argument("pool")
    sp.add_argument("--pg-num", type=int, default=32, dest="pg_num")
    sp.add_argument("--type", default="replicated", dest="pool_type",
                    choices=["replicated", "erasure"])
    sp.add_argument("--profile", default="default")
    sp = sub.add_parser("rmpool")
    sp.add_argument("pool")
    for name, extra in [
            ("put", ["obj", "infile"]), ("get", ["obj", "outfile"]),
            ("append", ["obj", "infile"]), ("ls", []), ("rm", ["obj"]),
            ("stat", ["obj"]), ("setxattr", ["obj", "name", "value"]),
            ("getxattr", ["obj", "name"]), ("rmxattr", ["obj", "name"]),
            ("listxattr", ["obj"]),
            ("setomapval", ["obj", "name", "value"]),
            ("listomapvals", ["obj"]), ("rmomapkey", ["obj", "name"])]:
        sp = sub.add_parser(name)
        sp.add_argument("pool")
        for a in extra:
            sp.add_argument(a)
    sp = sub.add_parser("truncate")
    sp.add_argument("pool")
    sp.add_argument("obj")
    sp.add_argument("size", type=int)
    sp = sub.add_parser("bench")
    sp.add_argument("pool")
    sp.add_argument("seconds", type=int)
    sp.add_argument("--obj-size", type=int, default=65536, dest="obj_size")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return asyncio.run(_run(args))


if __name__ == "__main__":
    sys.exit(main())
