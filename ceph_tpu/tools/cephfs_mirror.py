"""cephfs-mirror daemon launcher (src/tools/cephfs_mirror analog).

    python -m ceph_tpu.tools.cephfs_mirror \
        --src-mon 127.0.0.1:6789 --dst-mon 127.0.0.1:6790 --interval 10

Configure trees on the primary first:
    python -m ceph_tpu.tools.cephfs_cli --mon ... (then fs_mirror_add
    via the library, or the `mirror add` subcommand below)
"""

from __future__ import annotations

import argparse
import asyncio
import signal
import sys

from ..mds import CephFS
from ..mds.fs_mirror import (
    FsMirrorDaemon, fs_mirror_add, fs_mirror_dirs, fs_mirror_remove,
)


async def amain(args) -> int:
    sh, sp = args.src_mon.rsplit(":", 1)
    src = dst = None
    try:
        src = await CephFS((sh, int(sp))).mount()
        if args.cmd == "add":
            await fs_mirror_add(src.meta, args.path)
            print(f"mirroring configured for {args.path}")
            return 0
        if args.cmd == "remove":
            await fs_mirror_remove(src.meta, args.path)
            print(f"mirroring removed for {args.path}")
            return 0
        if args.cmd == "ls":
            for d in await fs_mirror_dirs(src.meta):
                print(d)
            return 0
        dh, dp = args.dst_mon.rsplit(":", 1)
        dst = await CephFS((dh, int(dp))).mount()
        daemon = FsMirrorDaemon(src, dst, interval=args.interval)
        daemon.start()
        print(f"cephfs-mirror: replaying every {args.interval}s",
              flush=True)
        stop = asyncio.Event()
        loop = asyncio.get_event_loop()
        for s in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(s, stop.set)
        await stop.wait()
        await daemon.stop()
        return 0
    finally:
        if src is not None:
            await src.unmount()
        if dst is not None:
            await dst.unmount()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="cephfs-mirror")
    p.add_argument("--src-mon", required=True)
    p.add_argument("--dst-mon")
    p.add_argument("--interval", type=float, default=10.0)
    p.add_argument("cmd", nargs="?", default="run",
                   choices=["run", "add", "remove", "ls"])
    p.add_argument("path", nargs="?")
    args = p.parse_args(argv)
    if args.cmd == "run" and not args.dst_mon:
        p.error("run mode requires --dst-mon")
    if args.cmd in ("add", "remove") and not args.path:
        p.error(f"{args.cmd} requires a path")
    return asyncio.run(amain(args))


if __name__ == "__main__":
    sys.exit(main())
