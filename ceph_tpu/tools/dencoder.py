"""ceph-dencoder analog: inspect/verify versioned encodings.

    python -m ceph_tpu.tools.dencoder list_types
    python -m ceph_tpu.tools.dencoder type PGInfo decode < blob.bin
    python -m ceph_tpu.tools.dencoder type PGInfo encode_sample > blob.bin
    python -m ceph_tpu.tools.dencoder corpus_check tests/fixtures/corpus

Reference: src/tools/ceph-dencoder (type registry, decode/dump-json,
count_tests/select_test sample generators) + ceph-object-corpus
(committed encodings every build must keep decoding AND re-encode
byte-identically).
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

from ..common.denc import Decoder, denc_bytes
from ..osd.pg_log import PGLog
from ..osd.types import (
    EVersion, LogEntry, MissingSet, PastIntervals, PGInfo, ZERO,
)


def _samples_pginfo():
    yield PGInfo(pgid="1.2a", last_update=EVersion(9, 140),
                 last_complete=EVersion(9, 133),
                 log_tail=EVersion(3, 12), last_epoch_started=9,
                 same_interval_since=7, backfill_complete=False,
                 last_backfill="obj_0042")
    yield PGInfo()


def _samples_logentry():
    yield LogEntry(op="modify", oid="rbd_data.abc.0000",
                   version=EVersion(4, 77), prior_version=EVersion(4, 70),
                   mutations=[{"op": "write", "off": 0, "len": 42}],
                   reqid=("client.a:1", 9))
    yield LogEntry(op="delete", oid="gone", version=EVersion(5, 1),
                   prior_version=ZERO, mutations=[], reqid=None)


def _samples_missing():
    ms = MissingSet()
    ms.add("a", need=EVersion(2, 5), have=ZERO)
    ms.add("b", need=EVersion(3, 9), have=EVersion(1, 1))
    yield ms
    yield MissingSet()


def _samples_pastintervals():
    pi = PastIntervals()
    pi.note_interval(3, 7, [2, 0, 1])
    pi.note_interval(8, 11, [2, -1, 1])
    pi.note_interval(12, 12, [0, 3, 1], rw=False)
    yield pi
    yield PastIntervals()


def _samples_pglog():
    log = PGLog()
    for e in _samples_logentry():
        log.entries.append(e)
        log.head = e.version
    yield log


def _entry(cls, samples):
    """All registered types share denc/to_dict conventions; only the
    class and its sample generator differ."""
    return {
        "samples": samples,
        "enc": denc_bytes,
        "dec": lambda b, c=cls: c.dedenc(Decoder(b)),
        "dump": lambda o: o.to_dict(),
    }


def _samples_wire():
    """Wire frames: the denc meta envelope + typed hot-path codecs
    (msg/wire_types.py) must stay byte-stable -- a drift here breaks
    rolling upgrades mid-flight, not just on-disk state."""
    from ..msg import Message
    m = Message("osd_op", {"pgid": "1.2a", "oid": "obj-7", "tid": 42,
                           "reqid": ["client.a:ffee", 7],
                           "ops": [{"op": "write", "offset": 0,
                                    "length": 3,
                                    "data": {"seg": 0, "len": 3}}]},
                segments=[b"abc"])
    m.seq, m.from_name = 9, "client.a"
    yield m
    yield Message("osd_op_reply", {"tid": 42, "epoch": 11,
                                   "results": [{"ok": True}]})
    yield Message("osd_op_reply", {"tid": 43, "err": "ENOENT"})
    yield Message("rep_op", {"pgid": "1.2a", "tid": 5,
                             "entry": {"oid": "obj-7",
                                       "version": [9, 140]},
                             "muts": [{"op": "write", "offset": 0}]})
    yield Message("rep_op_reply", {"tid": 5, "from_osd": 3})
    yield Message("osd_ping", {"from_osd": 2, "stamp": 1234.5})
    # a generic (non-typed) message exercises the tagged-value path
    yield Message("paxos_begin", {"version": 7, "value": "v" * 20,
                                  "e": 2, "nested": {"a": [1, None],
                                                     "b": -1.5}})


def _wire_entry():
    from ..msg import Message
    return {
        "samples": _samples_wire,
        "enc": lambda m: m.encode(),
        "dec": Message.decode,
        "dump": lambda m: {"t": m.type, "seq": m.seq,
                           "from": m.from_name, "data": m.data,
                           "segs": [s.hex() for s in m.segments]},
        # frames start with 4-byte magic + u32 meta_len; the envelope
        # struct_v lives at offset 8 (default heuristic reads byte 0)
        "ver": lambda b: b[8:9],
    }


TYPES = {
    "PGInfo": _entry(PGInfo, _samples_pginfo),
    "LogEntry": _entry(LogEntry, _samples_logentry),
    "MissingSet": _entry(MissingSet, _samples_missing),
    "PastIntervals": _entry(PastIntervals, _samples_pastintervals),
    "PGLog": _entry(PGLog, _samples_pglog),
    "WireMessage": _wire_entry(),
}


def corpus_check(root: str) -> int:
    """Every committed blob must decode and re-encode byte-identically
    (the non-regression contract of ceph-object-corpus)."""
    failures = 0
    n = 0
    for tdir in sorted(Path(root).iterdir()):
        if not tdir.is_dir() or tdir.name not in TYPES:
            continue
        t = TYPES[tdir.name]
        for blob_path in sorted(tdir.glob("*.bin")):
            n += 1
            blob = blob_path.read_bytes()
            try:
                obj = t["dec"](blob)
                re = t["enc"](obj)
                if re != blob:
                    # the envelope's version byte (offset per type --
                    # wire frames carry a magic first): an OLD-version
                    # blob is decode-compat only (the reference keeps
                    # per-version corpus archives the same way); a
                    # SAME-version mismatch is a breaking format
                    # drift and fails
                    ver = t.get("ver", lambda b: b[:1])
                    if ver(blob) == ver(re):
                        print(f"FAIL {tdir.name}/{blob_path.name}: "
                              f"re-encode differs at same version "
                              f"({len(re)} vs {len(blob)} bytes)")
                        failures += 1
                        continue
                    if t["dump"](t["dec"](re)) != t["dump"](obj):
                        print(f"FAIL {tdir.name}/{blob_path.name}: "
                              f"upgraded re-encode loses semantics")
                        failures += 1
                        continue
                side = blob_path.with_suffix(".json")
                if side.exists():
                    want = json.loads(side.read_text())
                    if t["dump"](obj) != want:
                        print(f"FAIL {tdir.name}/{blob_path.name}: "
                              f"semantic dump differs")
                        failures += 1
            except Exception as e:
                print(f"FAIL {tdir.name}/{blob_path.name}: "
                      f"{type(e).__name__}: {e}")
                failures += 1
    print(f"checked {n} corpus encodings, {failures} failures")
    return 1 if failures else 0


def generate_corpus(root: str) -> int:
    for name, t in TYPES.items():
        d = Path(root) / name
        d.mkdir(parents=True, exist_ok=True)
        for i, obj in enumerate(t["samples"]()):
            (d / f"{i}.bin").write_bytes(t["enc"](obj))
            (d / f"{i}.json").write_text(
                json.dumps(t["dump"](obj), indent=1, sort_keys=True))
    print(f"corpus written under {root}")
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print(__doc__)
        return 2
    cmd = argv[0]
    if cmd == "list_types":
        for name in sorted(TYPES):
            print(name)
        return 0
    if cmd == "corpus_check":
        return corpus_check(argv[1])
    if cmd == "corpus_generate":
        return generate_corpus(argv[1])
    if cmd == "type" and len(argv) >= 3:
        t = TYPES.get(argv[1])
        if t is None:
            print(f"unknown type {argv[1]}", file=sys.stderr)
            return 2
        if argv[2] == "decode":
            obj = t["dec"](sys.stdin.buffer.read())
            print(json.dumps(t["dump"](obj), indent=1))
            return 0
        if argv[2] == "encode_sample":
            sys.stdout.buffer.write(t["enc"](next(t["samples"]())))
            return 0
        if argv[2] == "count_tests":
            print(sum(1 for _ in t["samples"]()))
            return 0
    print(__doc__)
    return 2


if __name__ == "__main__":
    sys.exit(main())
