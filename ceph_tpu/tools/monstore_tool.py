"""ceph-monstore-tool analog: offline monitor store inspection.

Operates on a MonStore SQLite file (mon down):

    python -m ceph_tpu.tools.monstore_tool mon.db dump-versions
    python -m ceph_tpu.tools.monstore_tool mon.db get-version 7
    python -m ceph_tpu.tools.monstore_tool mon.db get-osdmap
    python -m ceph_tpu.tools.monstore_tool mon.db show-kv
"""

from __future__ import annotations

import argparse
import json
import sqlite3
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ceph-monstore-tool")
    p.add_argument("store", help="MonStore sqlite file")
    p.add_argument("cmd", choices=["dump-versions", "get-version",
                                   "get-osdmap", "show-kv"])
    p.add_argument("arg", nargs="?")
    args = p.parse_args(argv)
    conn = sqlite3.connect(args.store)

    if args.cmd == "dump-versions":
        rows = conn.execute(
            "SELECT version, LENGTH(value) FROM log ORDER BY version"
        ).fetchall()
        for v, n in rows:
            print(f"version {v}\t{n} bytes")
        print(f"last_committed: {rows[-1][0] if rows else 0}")
        return 0

    if args.cmd == "get-version":
        if not args.arg:
            p.error("get-version requires a version number")
        row = conn.execute("SELECT value FROM log WHERE version=?",
                           (int(args.arg),)).fetchone()
        if row is None:
            print(f"no such version {args.arg}", file=sys.stderr)
            return 1
        print(json.dumps(json.loads(row[0]), indent=1))
        return 0

    if args.cmd == "get-osdmap":
        # replay the full committed log into the final map, exactly as
        # the mon does at boot (usable as osdmaptool input)
        from ..mon.osdmap import Incremental, OSDMap
        m = OSDMap()
        for (blob,) in conn.execute(
                "SELECT value FROM log ORDER BY version"):
            m.apply_incremental(Incremental.from_dict(json.loads(blob)))
        print(json.dumps(m.to_dict(), indent=1))
        return 0

    if args.cmd == "show-kv":
        for k, v in conn.execute("SELECT key, value FROM kv ORDER BY key"):
            print(f"{k}\t{len(v)} bytes")
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
