"""BASELINE config 5: bulk CRUSH placement throughput.

Measures the vectorized straw2 mapper (ceph_tpu/crush/vectorized.py)
computing PG->OSD mappings for a large PG population over a 1000-OSD
two-level (host/osd) crushmap -- the OSDMapMapping / ParallelPGMapper
job (src/osd/OSDMapMapping.h:175) the reference spreads over a thread
pool, here one device launch per batch.  Prints ONE JSON line:

  {"metric": "crush_bulk_mappings_per_s", "value": ..., "unit": "pg/s",
   "n_mappings": ..., "n_osds": ..., "lane_exact_vs_scalar": true}

Usage: python -m ceph_tpu.tools.crush_bench [--pgs 10000000]
       [--osds 1000] [--replicas 3] [--verify 512]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pgs", type=int, default=10_000_000)
    ap.add_argument("--osds", type=int, default=1000)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--verify", type=int, default=512,
                    help="lanes cross-checked against the scalar engine")
    ap.add_argument("--batch", type=int, default=2_000_000,
                    help="lanes per device launch")
    args = ap.parse_args(argv)

    from ..crush import crush_do_rule
    from ..crush.builder import build_hierarchy
    from ..crush.vectorized import VectorCrush

    # depth-4 (root->row->rack->host->osd), the realistic shape the
    # balancer chews on: 5 rows x 5 racks x 4 hosts x 10 osds = 1000
    osds_per_host = 10
    hosts = max(1, args.osds // osds_per_host)
    racks = max(1, hosts // 4)
    rows = max(1, racks // 5)
    cm = build_hierarchy([rows, max(1, racks // rows),
                          max(1, hosts // racks), osds_per_host])
    n = rows * max(1, racks // rows) * max(1, hosts // racks) \
        * osds_per_host
    args.osds = n
    ruleno = 0                       # replicated chooseleaf firstn
    weights = [0x10000] * args.osds
    vc = VectorCrush(cm, ruleno)

    rng = np.random.default_rng(0)
    # pps values as the balancer would feed them (hashed placement seeds)
    xs = rng.integers(0, 2**31 - 1, size=args.pgs, dtype=np.int64)

    # lane-exactness gate vs the scalar decision-level engine
    sample = xs[:args.verify]
    got = vc.map_pgs(sample, args.replicas, weights)
    for i, x in enumerate(sample):
        want = crush_do_rule(cm, ruleno, int(x), args.replicas, weights)
        if list(got[i]) != list(want):
            print(json.dumps({"metric": "crush_bulk_mappings_per_s",
                              "value": 0, "unit": "pg/s",
                              "error": f"lane {i} mismatch"}))
            return 1

    import jax
    import jax.numpy as jnp
    w = jnp.asarray(weights, jnp.int32)
    fn = vc.map_firstn if vc.firstn else vc.map_indep
    batch = min(args.batch, args.pgs)
    n_batches = args.pgs // batch
    # ALL distinct seeds staged once (the balancer's deployment shape:
    # the pg population lives in HBM); every timed launch maps a
    # different batch
    batches = [jax.device_put(jnp.asarray(
        xs[b * batch:(b + 1) * batch], jnp.int32))
        for b in range(n_batches)]
    jax.block_until_ready(batches)
    out = fn(batches[0], args.replicas, w)   # compile + warm
    jax.block_until_ready(out)

    t0 = time.perf_counter()
    outs = [fn(bx, args.replicas, w) for bx in batches]
    jax.block_until_ready(outs)
    dt = time.perf_counter() - t0
    total = batch * n_batches
    rate = total / dt
    print(json.dumps({
        "metric": "crush_bulk_mappings_per_s",
        "value": round(rate, 1),
        "unit": "pg/s",
        "n_mappings": total,
        "n_osds": args.osds, "depth": 4,
        "replicas": args.replicas,
        "batch": batch,
        "launches": n_batches,
        "elapsed_s": round(dt, 3),
        "backend": jax.default_backend(),
        "lane_exact_vs_scalar": True,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
