"""Erasure-code micro-benchmark, harness-compatible with the reference.

Mirrors ceph_erasure_code_benchmark's contract
(src/test/erasure-code/ceph_erasure_code_benchmark.cc): plugin selected by
name+profile only (:170), encode loop over a fixed buffer, output one
tab-separated line "<seconds>\t<total KiB>" (:193), decode mode with random
or exhaustive erasures and byte-for-byte verification of recovered chunks
(:234-244).

Extra (TPU-native) mode: --batch B runs the batched device pipeline --
B stripes per launch, data device-resident, which is the deployment shape
(stripes stream through HBM; the OSD EC backend batches stripes across
PGs the same way).
"""

from __future__ import annotations

import argparse
import itertools
import sys
import time

import numpy as np

from ..ec import registry


def parse_profile(args) -> dict:
    profile = {}
    for kv in args.parameter or []:
        k, _, v = kv.partition("=")
        profile[k] = v
    profile.setdefault("k", str(args.k))
    profile.setdefault("m", str(args.m))
    return profile


def run_encode(codec, size: int, iterations: int, batch: int) -> tuple[float, int]:
    k = codec.get_data_chunk_count()
    n = codec.get_chunk_count()
    want = set(range(n))
    if batch > 1:
        # device-resident batched pipeline
        chunk = codec.get_chunk_size(size)
        rng = np.random.default_rng(0)
        data = rng.integers(0, 256, size=(batch, k, chunk), dtype=np.uint8)
        # warm up compile
        out = codec.encode_batch(data)
        _block(out)
        begin = time.perf_counter()
        for _ in range(iterations):
            out = codec.encode_batch(data)
        _block(out)
        elapsed = time.perf_counter() - begin
        total_kib = batch * k * chunk * iterations // 1024
        return elapsed, total_kib
    buf = b"X" * size
    begin = time.perf_counter()
    for _ in range(iterations):
        codec.encode(want, buf)
    elapsed = time.perf_counter() - begin
    return elapsed, size * iterations // 1024


def _block(out):
    try:
        out.block_until_ready()
    except AttributeError:
        pass


def count_erasures(n: int, erasures: int):
    for combo in itertools.combinations(range(n), erasures):
        yield list(combo)


def run_decode(codec, size: int, iterations: int, erasures: int,
               exhaustive: bool, verify: bool) -> tuple[float, int]:
    k = codec.get_data_chunk_count()
    n = codec.get_chunk_count()
    rng = np.random.default_rng(42)
    raw = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
    encoded = codec.encode(set(range(n)), raw)

    if exhaustive:
        patterns = list(count_erasures(n, erasures))
    else:
        patterns = None

    begin = time.perf_counter()
    done = 0
    i = 0
    while done < iterations:
        if patterns is not None:
            erased = patterns[i % len(patterns)]
        else:
            erased = sorted(rng.choice(n, size=erasures, replace=False))
        i += 1
        avail = {j: encoded[j] for j in range(n) if j not in erased}
        decoded = codec.decode(set(range(n)), avail)
        if verify:
            for e in erased:
                if not np.array_equal(decoded[e], encoded[e]):
                    raise SystemExit(
                        f"byte parity FAILED for chunk {e} erasures {erased}")
        done += 1
    elapsed = time.perf_counter() - begin
    return elapsed, size * iterations // 1024


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ec_bench")
    p.add_argument("-P", "--parameter", action="append",
                   help="profile k=v (repeatable)")
    p.add_argument("--plugin", default="tpu")
    p.add_argument("-k", type=int, default=8)
    p.add_argument("-m", type=int, default=3)
    p.add_argument("-s", "--size", type=int, default=1 << 20,
                   help="object size per op (bytes)")
    p.add_argument("-i", "--iterations", type=int, default=10)
    p.add_argument("-w", "--workload", choices=("encode", "decode"),
                   default="encode")
    p.add_argument("-e", "--erasures", type=int, default=1)
    p.add_argument("--erasures-generation", choices=("random", "exhaustive"),
                   default="random")
    p.add_argument("--erased", type=int, action="append",
                   help="explicit chunk ids to erase")
    p.add_argument("--batch", type=int, default=1,
                   help="stripes per device launch (TPU pipeline mode)")
    p.add_argument("--verify", action="store_true")
    args = p.parse_args(argv)

    profile = parse_profile(args)
    codec = registry().factory(args.plugin, profile)

    if args.workload == "encode":
        elapsed, kib = run_encode(codec, args.size, args.iterations,
                                  args.batch)
    else:
        exhaustive = args.erasures_generation == "exhaustive"
        verify = args.verify or exhaustive
        elapsed, kib = run_decode(codec, args.size, args.iterations,
                                  args.erasures, exhaustive, verify)
    print(f"{elapsed:.6f}\t{kib}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
