"""cephfs-shell analog: drive a CephFS namespace from the command line.

    python -m ceph_tpu.tools.cephfs_cli --mon 127.0.0.1:6789 mkdir /a
    python -m ceph_tpu.tools.cephfs_cli --mon 127.0.0.1:6789 put f.txt /a/f
"""

from __future__ import annotations

import argparse
import asyncio
import sys

from ..mds import CephFS


async def amain(args) -> int:
    host, port = args.mon.rsplit(":", 1)
    fs = await CephFS((host, int(port))).mount()
    try:
        if args.cmd == "ls":
            entries = await fs.readdir(args.path)
            for name in sorted(entries):
                d = entries[name]
                kind = "d" if d["type"] == "dir" else "-"
                print(f"{kind} {d.get('size', 0):>10} {name}")
        elif args.cmd == "mkdir":
            await fs.mkdir(args.path)
        elif args.cmd == "rmdir":
            await fs.rmdir(args.path)
        elif args.cmd == "rm":
            await fs.unlink(args.path)
        elif args.cmd == "mv":
            await fs.rename(args.path, args.dst)
        elif args.cmd == "stat":
            print(await fs.stat(args.path))
        elif args.cmd == "put":
            data = (sys.stdin.buffer.read() if args.local == "-"
                    else open(args.local, "rb").read())
            await fs.write_file(args.path, data)
            print(f"wrote {len(data)} bytes to {args.path}")
        elif args.cmd == "get":
            data = await fs.read_file(args.path)
            if args.local == "-":
                sys.stdout.buffer.write(data)
            else:
                open(args.local, "wb").write(data)
        elif args.cmd == "tree":
            async for dirpath, dirs, files in fs.walk(args.path):
                print(dirpath)
                for f in files:
                    print(f"  {f}")
        return 0
    finally:
        await fs.unmount()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="cephfs")
    p.add_argument("--mon", default="127.0.0.1:6789")
    sub = p.add_subparsers(dest="cmd", required=True)
    for c in ("ls", "mkdir", "rmdir", "rm", "stat", "tree"):
        sp = sub.add_parser(c)
        sp.add_argument("path", nargs="?" if c in ("ls", "tree")
                        else None, default="/")
    sp = sub.add_parser("mv")
    sp.add_argument("path"); sp.add_argument("dst")
    sp = sub.add_parser("put")
    sp.add_argument("local"); sp.add_argument("path")
    sp = sub.add_parser("get")
    sp.add_argument("path"); sp.add_argument("local")
    args = p.parse_args(argv)
    return asyncio.run(amain(args))


if __name__ == "__main__":
    sys.exit(main())
