"""OSD-path EC benchmark: concurrent client writes through a cluster.

The raw-codec bench (bench.py headline) measures the kernel; this one
measures the SYSTEM: a vstart-style in-process cluster (mon + N OSDs),
an erasure-coded pool on the `tpu` profile, and many concurrent client
writes — the shape where per-op codec dispatch used to pay one launch
per write and the CodecBatcher now coalesces stripes across ops and
PGs into shared ``encode_batch`` launches.

Reports achieved client throughput AND batch occupancy (stripes per
launch, pad waste, flush reasons) from the per-OSD "ec_batch" perf
counters, so a round's BENCH artifact shows what batch sizes the data
path actually reached — not just what the kernel could do.

    python -m ceph_tpu.tools.ec_osd_bench --objects 64 --obj-kib 64
    python bench.py --osd-path          # same engine, bench JSON shape
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time


async def run_osd_path_bench(*, n_osds: int = 3, k: int = 2, m: int = 1,
                             n_objects: int = 48,
                             obj_bytes: int = 64 * 1024,
                             concurrency: int = 16,
                             pg_num: int = 8,
                             batch_max: int = 64,
                             batch_timeout: float = 0.002,
                             rounds: int = 2,
                             mesh: bool | None = None) -> dict:
    """Drive N concurrent EC writes; return throughput + occupancy.

    ``mesh`` forces the sharded data plane on (True) or off (False);
    None keeps the config default.  With the mesh, the report adds the
    per-OSD mesh occupancy: device launches per coalesced batch (the
    exactly-one gate), devices in the mesh, and padded stripes per
    device per launch (the sharding factor)."""
    import numpy as np
    from ..client.rados import Rados
    from ..mon import Monitor
    from ..osd import OSD

    mon = Monitor(rank=0, config={"mon_osd_min_down_reporters": 1})
    addr = await mon.start()
    osds = []
    for i in range(n_osds):
        cfg = {
            "osd_ec_batch_max": batch_max,
            "osd_ec_batch_timeout": batch_timeout,
        }
        if mesh is not None:
            cfg["osd_ec_mesh_enabled"] = bool(mesh)
        osd = OSD(host=f"host{i}", config=cfg)
        await osd.start(addr)
        osds.append(osd)
    rados = await Rados(addr).connect()
    try:
        await rados.mon_command(
            "osd erasure-code-profile set",
            {"name": "bench", "profile": {
                "plugin": "tpu", "k": str(k), "m": str(m),
                "technique": "reed_sol_van"}})
        await rados.mon_command(
            "osd pool create",
            {"name": "ecbench", "type": "erasure", "pg_num": pg_num,
             "erasure_code_profile": "bench"})
        io = await rados.open_ioctx("ecbench")
        rng = np.random.default_rng(0)
        payloads = [rng.integers(0, 256, obj_bytes,
                                 dtype=np.uint8).tobytes()
                    for _ in range(min(8, n_objects))]
        sem = asyncio.Semaphore(concurrency)

        async def one(i: int) -> None:
            async with sem:
                await io.write_full(f"obj-{i}",
                                    payloads[i % len(payloads)])

        # warm round: peering settles, codecs compile, caches fill
        await asyncio.gather(*(one(i) for i in range(n_objects)))
        t0 = time.perf_counter()
        for _ in range(rounds):
            await asyncio.gather(*(one(i) for i in range(n_objects)))
        dt = time.perf_counter() - t0
        total_bytes = rounds * n_objects * obj_bytes

        # roll up batch occupancy over every OSD's aggregation stage
        batches = stripes = pad = fallback = 0
        mesh_launches = mesh_padded = mesh_fallbacks = 0
        xor_launches = xor_fallbacks = xor_saved = 0
        n_devices = 0
        flush: dict[str, int] = {}
        for osd in osds:
            dump = osd.perf.dump().get("ec_batch", {})
            batches += dump.get("batches", 0)
            stripes += dump.get("stripes", 0)
            pad += dump.get("pad_waste_bytes", 0)
            fallback += dump.get("fallback_ops", 0)
            mesh_launches += dump.get("mesh_launches", 0)
            mesh_padded += dump.get("mesh_padded_stripes", 0)
            mesh_fallbacks += dump.get("mesh_fallbacks", 0)
            xor_launches += dump.get("xor_sched_launches", 0)
            xor_fallbacks += dump.get("xor_sched_fallbacks", 0)
            xor_saved += dump.get("xor_terms_saved", 0)
            n_devices = max(n_devices,
                            int(dump.get("mesh_devices", 0)))
        for osd in osds:
            dump = osd.perf.dump().get("ec_batch", {})
            for key, v in dump.items():
                if key.startswith("flush_"):
                    flush[key] = flush.get(key, 0) + v
        # the write-pipeline set (PR 12): staged launches, overlap
        # windows, stalls, deferred-commit overlap, coalesced flushes
        pipeline: dict[str, int] = {}
        for osd in osds:
            for key, v in osd.perf.dump().get("ec_pipeline",
                                              {}).items():
                if isinstance(v, (int, float)):
                    pipeline[key] = pipeline.get(key, 0) + v
        mesh_report = {
            "launches": mesh_launches,
            "fallbacks": mesh_fallbacks,
            "launches_per_batch": round(mesh_launches / batches, 3)
            if batches else 0.0,
            "n_devices": n_devices,
            "per_device_stripes": round(
                mesh_padded / mesh_launches / n_devices, 2)
            if mesh_launches and n_devices else 0.0,
        }
        return {
            "osd_path_GiBps": round(total_bytes / dt / 2**30, 3),
            "writes_per_s": round(rounds * n_objects / dt, 1),
            "stripes_per_launch": round(stripes / batches, 2)
            if batches else 0.0,
            "batches": batches,
            "stripes": stripes,
            "pad_waste_bytes": pad,
            "fallback_ops": fallback,
            "mesh": mesh_report,
            "xor_sched": {
                "launches": xor_launches,
                "fallbacks": xor_fallbacks,
                "terms_saved": xor_saved,
            },
            "ec_pipeline": pipeline,
            "flush_reasons": flush,
            "n_osds": n_osds, "k": k, "m": m,
            "objects": n_objects, "obj_bytes": obj_bytes,
            "concurrency": concurrency, "rounds": rounds,
        }
    finally:
        await rados.shutdown()
        for osd in osds:
            await osd.stop()
        await mon.stop()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ec_osd_bench")
    p.add_argument("--osds", type=int, default=3)
    p.add_argument("--k", type=int, default=2)
    p.add_argument("--m", type=int, default=1)
    p.add_argument("--objects", type=int, default=48)
    p.add_argument("--obj-kib", type=int, default=64)
    p.add_argument("--concurrency", type=int, default=16)
    p.add_argument("--pg-num", type=int, default=8)
    p.add_argument("--batch-max", type=int, default=64)
    p.add_argument("--rounds", type=int, default=2)
    p.add_argument("--mesh", dest="mesh", action="store_true",
                   default=None, help="force the sharded data plane on")
    p.add_argument("--no-mesh", dest="mesh", action="store_false",
                   help="force the sharded data plane off")
    args = p.parse_args(argv)
    res = asyncio.run(run_osd_path_bench(
        n_osds=args.osds, k=args.k, m=args.m, n_objects=args.objects,
        obj_bytes=args.obj_kib * 1024, concurrency=args.concurrency,
        pg_num=args.pg_num, batch_max=args.batch_max,
        rounds=args.rounds, mesh=args.mesh))
    print(json.dumps(res), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
