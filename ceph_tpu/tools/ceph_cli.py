"""``ceph``-style admin CLI: status, osd tree/dump, pool and EC-profile
management — the monitor command surface (src/ceph.in + MonCommands.h
analog).  Usage: python -m ceph_tpu.tools.ceph_cli -m HOST:PORT <cmd...>
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from . import parse_addr
from ..client import Rados, RadosError


async def _run(args) -> int:
    rados = Rados(parse_addr(args.mon), name="client.ceph-cli")
    try:
        await rados.connect()
    except (ConnectionError, OSError, TimeoutError) as e:
        print(f"error: cannot reach monitor at {args.mon}: {e}",
              file=sys.stderr)
        return 1
    try:
        words = args.words
        cmd, cargs = _parse_command(words)
        result = await rados.mon_command(cmd, cargs)
        if args.format == "json":
            print(json.dumps(result, indent=2, default=str))
        else:
            _render(cmd, result)
        return 0
    except (RadosError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        await rados.shutdown()


def _want(words: list[str], n: int, usage: str) -> None:
    if len(words) < n:
        raise ValueError(f"usage: ceph {usage}")


def _parse_command(words: list[str]) -> tuple[str, dict]:
    """Map CLI words onto monitor commands (MonCommands.h style)."""
    joined = " ".join(words)
    if joined == "status":
        return "status", {}
    if joined == "osd tree":
        return "osd tree", {}
    if joined == "osd dump":
        return "osd dump", {}
    if joined == "osd pool ls":
        return "osd pool ls", {}
    if words[:3] == ["osd", "pool", "create"]:
        _want(words, 4, "osd pool create <name> [pg_num] "
                        "[replicated|erasure [profile]]")
        args = {"name": words[3]}
        if len(words) > 4:
            args["pg_num"] = int(words[4])
        rest = words[5:]
        if rest and rest[0] in ("replicated", "erasure"):
            args["type"] = rest[0]
            if rest[0] == "erasure" and len(rest) > 1:
                args["erasure_code_profile"] = rest[1]
        return "osd pool create", args
    if words[:3] == ["osd", "pool", "rm"]:
        _want(words, 4, "osd pool rm <name>")
        return "osd pool rm", {"name": words[3]}
    if words[:2] == ["osd", "out"]:
        _want(words, 3, "osd out <id>")
        return "osd out", {"osd_id": int(words[2])}
    if words[:2] == ["osd", "in"]:
        _want(words, 3, "osd in <id>")
        return "osd in", {"osd_id": int(words[2])}
    if words[:3] == ["osd", "erasure-code-profile", "ls"]:
        return "osd erasure-code-profile ls", {}
    if words[:3] == ["osd", "erasure-code-profile", "get"]:
        _want(words, 4, "osd erasure-code-profile get <name>")
        return "osd erasure-code-profile get", {"name": words[3]}
    if words[:3] == ["osd", "erasure-code-profile", "set"]:
        _want(words, 4, "osd erasure-code-profile set <name> [k=v ...]")
        profile = {}
        for kv in words[4:]:
            k, _, v = kv.partition("=")
            profile[k] = v
        return ("osd erasure-code-profile set",
                {"name": words[3], "profile": profile})
    if words[0] == "health":
        return "health", ({"detail": True} if "detail" in words[1:]
                          else {})
    if words[:2] == ["config", "set"]:
        _want(words, 5, "config set <who> <name> <value>")
        return "config set", {"who": words[2], "name": words[3],
                              "value": words[4]}
    if words[:2] == ["config", "get"]:
        _want(words, 3, "config get <who>")
        return "config get", {"who": words[2]}
    if words[:2] == ["config", "rm"]:
        _want(words, 4, "config rm <who> <name>")
        return "config rm", {"who": words[2], "name": words[3]}
    if words[:2] == ["config", "dump"]:
        return "config dump", {}
    if words[:2] == ["auth", "get-or-create"]:
        _want(words, 3, "auth get-or-create <entity> [type=cap ...]")
        caps = {}
        for kv in words[3:]:
            k, _, v = kv.partition("=")
            caps[k] = v
        return "auth get-or-create", {"entity": words[2], "caps": caps}
    if words[:2] == ["auth", "get"]:
        _want(words, 3, "auth get <entity>")
        return "auth get", {"entity": words[2]}
    if words[:2] == ["auth", "ls"]:
        return "auth ls", {}
    if words[:2] == ["auth", "rm"]:
        _want(words, 3, "auth rm <entity>")
        return "auth rm", {"entity": words[2]}
    if words[:2] == ["log", "last"]:
        return "log last", ({"n": int(words[2])}
                            if len(words) > 2 else {})
    if words[0] == "log":
        _want(words, 2, "log <message...>")
        return "log", {"message": " ".join(words[1:])}
    raise ValueError(f"unknown command: {joined}")


def _render(cmd: str, result) -> None:
    if cmd == "status":
        print(f"  cluster epoch {result['epoch']}")
        print(f"  health: {result['health']}")
        print(f"  osd: {result['num_osds']} osds: "
              f"{result['num_up']} up, {result['num_in']} in")
        print(f"  pools: {result['pools']}")
    elif cmd == "osd tree":
        print(f"{'ID':>4} {'TYPE':<6} {'NAME':<12} {'STATUS':<8} WEIGHT")
        for row in result:
            if row["type"] == "host":
                print(f"{'':>4} {'host':<6} {row['name']:<12}")
            else:
                status = "up" if row["up"] else "down"
                print(f"{row['id']:>4} {'osd':<6} osd.{row['id']:<8} "
                      f"{status:<8} {row['weight']/65536:.4f}")
    elif isinstance(result, (list, tuple)):
        for item in result:
            print(item)
    else:
        print(json.dumps(result, indent=2, default=str))


async def _run_daemon_command(sock_path: str, words: list[str]) -> int:
    """`ceph daemon <sock> <cmd...>` — admin-socket introspection."""
    from ..common.admin_socket import admin_command
    kwargs = {}
    if words[:2] == ["config", "get"] and len(words) >= 3:
        words, kwargs = words[:2], {"name": words[2]}
    elif words[:2] == ["config", "set"] and len(words) >= 4:
        words, kwargs = words[:2], {"name": words[2], "value": words[3]}
    elif words[:1] == ["scrub"] and len(words) >= 2:
        kwargs = {"pgid": words[1],
                  "repair": "repair" in words[2:]}
        words = words[:1]
    try:
        result = await admin_command(sock_path, " ".join(words), **kwargs)
        print(json.dumps(result, indent=2, default=str))
        return 0
    except (RuntimeError, ConnectionError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="ceph")
    p.add_argument("-m", "--mon", default="127.0.0.1:6789")
    p.add_argument("-f", "--format", default="plain",
                   choices=["plain", "json"])
    p.add_argument("words", nargs="+")
    args = p.parse_args(argv)
    if args.words[0] == "daemon":
        if len(args.words) < 3:
            print("usage: ceph daemon <socket-path> <command...>",
                  file=sys.stderr)
            return 2
        return asyncio.run(
            _run_daemon_command(args.words[1], args.words[2:]))
    return asyncio.run(_run(args))


if __name__ == "__main__":
    sys.exit(main())
