"""Cluster fault-injection driver: write -> kill -> re-peer -> verify.

The qa/tasks thrasher in miniature, built on the in-process cluster
(mon + N OSDs on loopback).  Each round writes a seeded working set to
an EC pool, kills one OSD (optionally with messenger faults armed via
common/faults.py), waits for the mon to mark it down, reads EVERY
object back under a deadline and byte-compares against what was
written, then (optionally) revives the OSD and verifies recovery
converges.  Shard mislabeling, wedged degraded reads and recovery
corruption all surface as hard failures here instead of in production.

CI smoke:  python -m ceph_tpu.tools.chaos --smoke
exits non-zero on any byte mismatch, wedged read, or lost object.
"""

from __future__ import annotations

import argparse
import asyncio
import random
import sys
import time

from ..common.faults import MessageFaultInjector
from ..loadgen.cluster import SimCluster
from ..msg import Message, Messenger
from ..osd.backend import pack_mutations


class ChaosCluster(SimCluster):
    """SimCluster plus a raw client messenger for low-level op drives.

    Bring-up, kill/revive tokens, wait_down/up/clean and perf
    aggregation all come from the shared ``loadgen.cluster``
    machinery; this subclass adds the bare-messenger client the chaos
    rounds use to submit ops without librados in the way.
    """

    def __init__(self, mon, osds, client,
                 faults: MessageFaultInjector | None = None) -> None:
        super().__init__(mon, osds, faults=faults)
        self.client = client
        self._op_serial = 0

    @classmethod
    async def create(cls, n_osds: int = 3, *,
                     mon_config: dict | None = None,
                     osd_config: dict | None = None,
                     faults: MessageFaultInjector | None = None
                     ) -> "ChaosCluster":
        base = await SimCluster.create(
            n_osds, mon_config=mon_config, osd_config=osd_config,
            faults=faults)
        client = Messenger("client.chaos")
        await client.bind()
        return cls(base.mon, base.osds, client, faults)

    async def stop(self) -> None:
        for o in self.osds:
            await o.stop()
        await self.client.shutdown()
        await self.mon.stop()

    # -- control plane -------------------------------------------------------
    async def command(self, cmd: str, args: dict | None = None) -> dict:
        q: asyncio.Queue = asyncio.Queue()

        async def d(conn, msg):
            if msg.type == "mon_command_reply":
                await q.put(msg.data)

        self.client.add_dispatcher(d)
        try:
            await self.client.send(
                self.addr, "mon.0",
                Message("mon_command", {"cmd": cmd, "args": args or {}}))
            data = await asyncio.wait_for(q.get(), 10)
        finally:
            self.client.dispatchers.remove(d)
        if not data["ok"]:
            raise RuntimeError(data["error"])
        return data["result"]

    async def create_ec_pool(self, name: str, k: int, m: int,
                             pg_num: int, plugin: str = "tpu",
                             profile_extra: dict | None = None) -> None:
        """Create an EC pool through the standard registry path:
        ``plugin`` picks the codec family (tpu RS, lrc, pmsr) and
        ``profile_extra`` carries its extra parameters (l for lrc, d
        for pmsr) -- the same knobs an operator sets, no special-cased
        call sites."""
        profile = {"plugin": plugin, "k": str(k), "m": str(m)}
        if plugin == "tpu":
            profile["technique"] = "reed_sol_van"
        for key, val in (profile_extra or {}).items():
            profile[key] = str(val)
        pname = f"chaos-{plugin}-k{k}m{m}"
        await self.command("osd erasure-code-profile set", {
            "name": pname, "profile": profile})
        await self.command("osd pool create", {
            "name": name, "type": "erasure", "pg_num": pg_num,
            "erasure_code_profile": pname})

    # -- data plane ----------------------------------------------------------
    def _target_for(self, pool_name: str, oid: str):
        # the raw-messenger chaos client reads the mon's live map as
        # its map-subscription stand-in; a swarm port subscribes over
        # the wire via sub_osdmap instead
        # lint: disable=cross-daemon-state -- in-process map shortcut
        omap = self.mon.osdmap
        pool_id = omap.pool_names[pool_name]
        _, ps = omap.object_to_pg(pool_id, oid)
        up = omap.pg_to_up_acting_osds(pool_id, ps)
        return omap.pg_name(pool_id, ps), omap.pg_primary(up)

    async def osd_op(self, pool_name: str, oid: str, ops: list[dict],
                     timeout: float = 15.0, retries: int = 40):
        """One client op against the current primary, retrying through
        peering; the stable reqid keeps retries idempotent."""
        q: asyncio.Queue = asyncio.Queue()
        self._op_serial += 1
        tid = self._op_serial
        reqid = [f"{self.client.name}:{self.client.incarnation}", tid]

        async def d(conn, msg):
            if msg.type == "osd_op_reply" and msg.data.get("tid") == tid:
                await q.put(msg)

        self.client.add_dispatcher(d)
        try:
            for _ in range(retries):
                pgid, primary = self._target_for(pool_name, oid)
                if primary is None:
                    await asyncio.sleep(0.25)
                    continue
                addr = self.mon.osd_addr(primary)
                meta, segs = pack_mutations(ops)
                try:
                    await self.client.send(
                        tuple(addr), f"osd.{primary}",
                        Message("osd_op",
                                {"pgid": pgid, "oid": oid, "ops": meta,
                                 "reqid": reqid, "tid": tid},
                                segments=segs))
                    reply = await asyncio.wait_for(q.get(), timeout)
                except (ConnectionError, OSError, asyncio.TimeoutError):
                    await asyncio.sleep(0.25)
                    continue
                err = reply.data.get("err")
                if err in ("ENOTPRIMARY", "EAGAIN", "ENXIO no such pg"):
                    await asyncio.sleep(0.25)
                    continue
                return reply
            raise TimeoutError(f"osd_op on {oid} never succeeded")
        finally:
            self.client.dispatchers.remove(d)

async def recovery_round(c: ChaosCluster, *, rnd: random.Random,
                         pool: str, n_objects: int, obj_size: int,
                         kill_indices: list[int], log,
                         settle: float = 90.0) -> dict:
    """One kill -> degraded-write -> revive -> recover drive with the
    repair I/O counted: objects written while the victim(s) are down
    become missing shards, and the recovery that rebuilds them after
    the revive is measured via the ``ec_recovery`` counters
    (repair_bytes_read / repair_bytes_shipped -- the per-code repair
    ratio the recovery-optimal codes exist to shrink).  Returns the
    counter deltas, the recovery wall clock, and the post-recovery
    byte-verification result (every object read back and compared,
    with one of the ORIGINAL survivors killed so reads must use the
    recovered shards -- a recovery that pushed garbage or absence
    cannot pass)."""
    result = {"errors": [], "mismatched": [], "n_objects": n_objects}
    objs: dict[str, bytes] = {}
    for i in range(n_objects):
        data = rnd.getrandbits(8 * obj_size).to_bytes(obj_size,
                                                      "little")
        objs[f"rec-{i:04d}"] = data
    # base pass so the pool's PGs are primed, then the degraded pass
    # AFTER the kill is what creates the missing shards recovery must
    # rebuild
    for oid, data in objs.items():
        await c.osd_op(pool, oid, [{"op": "writefull", "data": data}])
    if not await c.wait_clean(settle):
        result["errors"].append("cluster never went clean pre-kill")
    tokens = []
    for idx in kill_indices:
        tok = await c.kill_osd(idx)
        tokens.append((idx, tok))
        if not await c.wait_down(tok["whoami"]):
            result["errors"].append(
                f"osd.{tok['whoami']} never marked down")
            return result
    log(f"  killed {[t['whoami'] for _, t in tokens]}; degraded "
        f"rewrite of {n_objects} objects")
    for oid, data in objs.items():
        await c.osd_op(pool, oid, [{"op": "writefull", "data": data}])
    rec0 = c.perf_counters("ec_recovery")
    t0 = time.perf_counter()
    for idx, tok in tokens:
        await c.revive_osd(idx, tok)
        if not await c.wait_up(tok["whoami"]):
            result["errors"].append(
                f"osd.{tok['whoami']} never came back")
            return result
    recovered = await c.wait_clean(settle)
    wall = time.perf_counter() - t0
    if not recovered:
        result["errors"].append("recovery never converged")
    rec1 = c.perf_counters("ec_recovery")
    deltas = {key: rec1.get(key, 0) - rec0.get(key, 0)
              for key in set(rec0) | set(rec1)}
    # the proof: kill one of the ORIGINAL survivors, so every read of
    # a degraded-phase object decodes THROUGH the recovered shards
    survivor = next(i for i in range(len(c.osds))
                    if i not in set(kill_indices))
    tok2 = await c.kill_osd(survivor)
    if not await c.wait_down(tok2["whoami"]):
        result["errors"].append("verify-kill never marked down")
    for oid, want in objs.items():
        try:
            reply = await asyncio.wait_for(
                c.osd_op(pool, oid,
                         [{"op": "read", "off": 0, "len": None}],
                         timeout=10, retries=8), timeout=60)
        except (TimeoutError, asyncio.TimeoutError):
            result["mismatched"].append(oid)
            continue
        r = reply.data["results"][0]
        data = reply.segments[r["seg"]] if "seg" in r else None
        if not r.get("ok") or data != want:
            result["mismatched"].append(oid)
    await c.revive_osd(survivor, tok2)
    await c.wait_up(tok2["whoami"])
    result.update({"recovery_wall_s": round(wall, 3),
                   "recovered_clean": recovered,
                   "repair": deltas})
    return result


async def run_round(c: ChaosCluster, *, rnd: random.Random,
                    pool: str, n_objects: int, min_size: int,
                    max_size: int, kill_index: int,
                    read_deadline: float, revive: bool,
                    log) -> dict:
    """One write -> kill -> re-peer -> read-back loop.  Returns a
    result dict with mismatch/wedge/error lists."""
    result = {"mismatched": [], "wedged": [], "refused": [],
              "errors": [], "n_objects": n_objects}
    objs: dict[str, bytes] = {}
    for i in range(n_objects):
        size = rnd.randrange(min_size, max_size + 1)
        data = rnd.getrandbits(8 * size).to_bytes(size, "little")
        oid = f"chaos-{i:04d}"
        objs[oid] = data
        # writefull: REPLACE semantics, so later rounds overwriting a
        # longer object from an earlier round don't leave a stale tail
        # that would read as a false mismatch
        await c.osd_op(pool, oid, [{"op": "writefull", "data": data}])
    # let laggard-healing re-pushes settle: every shard of every ack'd
    # write should be on disk before we pull an OSD out
    if not await c.wait_clean():
        result["errors"].append("cluster never went clean pre-kill")
    victim_id = c.osds[kill_index].whoami
    log(f"  wrote {n_objects} objects; killing osd.{victim_id}")
    token = await c.kill_osd(kill_index)
    if not await c.wait_down(victim_id):
        result["errors"].append(f"osd.{victim_id} never marked down")
        return result
    log(f"  osd.{victim_id} down; reading back under "
        f"{read_deadline:.0f}s deadline")
    for oid, want in objs.items():
        try:
            reply = await asyncio.wait_for(
                c.osd_op(pool, oid,
                         [{"op": "read", "off": 0, "len": None}],
                         timeout=10, retries=8),
                timeout=read_deadline)
        except (TimeoutError, asyncio.TimeoutError):
            result["wedged"].append(oid)
            continue
        if reply.data.get("err"):
            result["refused"].append(oid)
            continue
        r = reply.data["results"][0]
        data = reply.segments[r["seg"]] if "seg" in r else None
        if not r.get("ok"):
            # per-op error (e.g. EIO after bounded shard retries): the
            # read COMPLETED with a refusal -- bytes were never faked
            result["refused"].append(oid)
        elif data != want:
            result["mismatched"].append(oid)
    if revive:
        log(f"  reviving osd.{victim_id}")
        await c.revive_osd(kill_index, token)
        if not await c.wait_up(victim_id):
            result["errors"].append(f"osd.{victim_id} never came back")
    return result


async def repair_pin_drive(c: ChaosCluster, args, rnd: random.Random,
                           log) -> int:
    """--repair-pin: the per-code repair-byte assertion.  Kill one
    OSD, write degraded, revive, recover, and pin the measured
    ``ec_recovery`` read/shipped ratio against the code's repair
    math: LRC single-failure recovery must read <= (l+1)x the shipped
    bytes ((l+1)/k of what RS would read), pmsr must take the
    fragment path and read under k full chunks, and a second round
    with TWO victims pins the multi-failure fallback (global decodes
    engaged, still byte-correct)."""
    failures = 0
    res = await recovery_round(
        c, rnd=rnd, pool="chaospool", n_objects=args.objects,
        obj_size=args.max_size, kill_indices=[len(c.osds) - 1],
        log=log)
    rep = res.get("repair", {})
    log(f"  single-failure repair: {rep} "
        f"wall={res.get('recovery_wall_s')}s")
    if res["errors"] or res["mismatched"]:
        log(f"ERROR: {res['errors']} mismatched={res['mismatched']}")
        failures += 1
    read = rep.get("repair_bytes_read", 0)
    shipped = rep.get("repair_bytes_shipped", 0)
    if not shipped or not read:
        log("ERROR: recovery moved no counted bytes")
        failures += 1
    elif args.plugin == "lrc":
        bound = (args.l + 1) * shipped
        if read > bound:
            log(f"ERROR: lrc repair read {read} > (l+1)*shipped="
                f"{bound} (locality not engaged)")
            failures += 1
        if not rep.get("repair_local_repairs"):
            log("ERROR: no local repair recorded")
            failures += 1
    elif args.plugin == "pmsr":
        if not rep.get("repair_fragment_pulls"):
            log("ERROR: no fragment pull recorded")
            failures += 1
        if read >= args.k * shipped:
            log(f"ERROR: pmsr repair read {read} >= k*shipped="
                f"{args.k * shipped} (no better than RS)")
            failures += 1
    # the multi-failure fallback pin is the LAYERED code's contract
    # (local repair infeasible when a group loses two chunks); a
    # 2-kill on an MDS-width pmsr pool at m=2 would drop the pool
    # below min_size instead
    if args.plugin == "lrc" and len(c.osds) >= 2:
        res2 = await recovery_round(
            c, rnd=rnd, pool="chaospool", n_objects=args.objects,
            obj_size=args.max_size,
            kill_indices=[len(c.osds) - 1, len(c.osds) - 2],
            log=log)
        rep2 = res2.get("repair", {})
        log(f"  multi-failure repair: {rep2} "
            f"wall={res2.get('recovery_wall_s')}s")
        if res2["errors"] or res2["mismatched"]:
            log(f"ERROR: multi-failure {res2['errors']} "
                f"mismatched={res2['mismatched']}")
            failures += 1
        if args.plugin == "lrc" and not rep2.get(
                "repair_global_decodes"):
            log("ERROR: multi-failure recovery never fell back to "
                "global decode")
            failures += 1
    return failures


async def chaos_main(args) -> int:
    rnd = random.Random(args.seed)
    faults = None
    if args.msg_drop_p > 0 or args.msg_delay > 0:
        faults = MessageFaultInjector(seed=args.seed)
        if args.msg_drop_p > 0:
            faults.drop(peer="osd.", probability=args.msg_drop_p)
        if args.msg_delay > 0:
            faults.delay(args.msg_delay, peer="osd.",
                         probability=args.msg_delay_p)
    c = await ChaosCluster.create(
        args.osds,
        mon_config={"mon_osd_down_out_interval": 3600.0},
        osd_config={"osd_heartbeat_interval": 0.2,
                    "osd_heartbeat_grace": 3.0},
        faults=faults)
    failures = 0

    def log(msg: str) -> None:
        if not args.quiet:
            print(msg, flush=True)

    extra = {}
    if args.plugin == "lrc" and args.l:
        extra["l"] = args.l
    if args.plugin == "pmsr" and args.d:
        extra["d"] = args.d
    try:
        await c.create_ec_pool("chaospool", args.k, args.m,
                               args.pg_num, plugin=args.plugin,
                               profile_extra=extra)
        if args.repair_pin:
            failures += await repair_pin_drive(c, args, rnd, log)
            deg = c.perf_counters("ec_degraded")
            log(f"ec_degraded counters: {deg}")
            log(f"{'FAIL' if failures else 'PASS'}: "
                f"{failures} failures")
            return 1 if failures else 0
        for r in range(args.rounds):
            log(f"round {r + 1}/{args.rounds}")
            kill_index = (len(c.osds) - 1 if args.kill_last
                          else rnd.randrange(len(c.osds)))
            res = await run_round(
                c, rnd=rnd, pool="chaospool",
                n_objects=args.objects, min_size=args.min_size,
                max_size=args.max_size, kill_index=kill_index,
                read_deadline=args.read_deadline,
                revive=(r + 1 < args.rounds), log=log)
            bad = (len(res["mismatched"]) + len(res["wedged"])
                   + len(res["errors"]))
            # an EIO refusal is a failure only on a clean network: with
            # drop faults armed, a write ack'd at min_size can lose a
            # shard to the kill before the re-push lands -- the honest
            # outcome is a refused read, never fabricated bytes
            if faults is None or args.strict_reads:
                bad += len(res["refused"])
            failures += bad
            log(f"  result: {res['n_objects'] - bad}/{res['n_objects']}"
                f" clean, mismatched={res['mismatched']}, "
                f"wedged={res['wedged']}, refused={res['refused']}, "
                f"errors={res['errors']}")
        deg = c.perf_counters("ec_degraded")
        log(f"ec_degraded counters: {deg}")
        if faults is not None:
            log(f"fault_inject stats: {faults.stats}")
        if not deg.get("degraded_reads") and not args.allow_clean:
            # reading back with a dead shard holder MUST have exercised
            # reconstruction; a zero here means the drive tested nothing
            log("ERROR: no degraded reads recorded -- harness broken?")
            failures += 1
        # the client routed every op through mon.osdmap's cached table
        # and each OSD retargeted through its own; kills/re-peering
        # bump epochs, so zero bulk recomputes means the epoch-keyed
        # invalidation never fired and the drive read stale placement
        pc = c.perf_counters("placement_cache")
        mon_pc = c.mon.placement_counters()
        log(f"placement_cache counters: osds={pc} mon={mon_pc}")
        if not mon_pc.get("bulk_recomputes") or not pc.get(
                "bulk_recomputes"):
            log("ERROR: placement cache never recomputed across the "
                "kill -- invalidation broken?")
            failures += 1
    finally:
        await c.stop()
    log(f"{'FAIL' if failures else 'PASS'}: {failures} failures")
    return 1 if failures else 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="EC cluster fault-injection driver")
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--objects", type=int, default=24)
    p.add_argument("--osds", type=int, default=3)
    p.add_argument("--k", type=int, default=2)
    p.add_argument("--m", type=int, default=1)
    p.add_argument("--plugin", default="tpu",
                   choices=("tpu", "lrc", "pmsr"),
                   help="EC plugin for the pool (registry path)")
    p.add_argument("--l", type=int, default=0,
                   help="lrc locality parameter (chunks per local "
                        "group beside its parity)")
    p.add_argument("--d", type=int, default=0,
                   help="pmsr helper count (must be 2(k-1))")
    p.add_argument("--repair-pin", action="store_true",
                   help="kill/recover drive asserting the per-code "
                        "repair-byte ratio via the ec_recovery "
                        "counters instead of the read-back rounds")
    p.add_argument("--pg-num", type=int, default=16)
    p.add_argument("--min-size", type=int, default=8 << 10)
    p.add_argument("--max-size", type=int, default=32 << 10)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--kill-last", action="store_true",
                   help="always kill the last OSD (the ROADMAP repro) "
                        "instead of a seeded random victim")
    p.add_argument("--read-deadline", type=float, default=60.0,
                   help="per-object read-back deadline; exceeding it "
                        "counts as a wedged read")
    p.add_argument("--msg-drop-p", type=float, default=0.0,
                   help="drop probability for osd<->osd messages")
    p.add_argument("--msg-delay", type=float, default=0.0,
                   help="injected delay seconds for osd<->osd messages")
    p.add_argument("--msg-delay-p", type=float, default=0.2)
    p.add_argument("--allow-clean", action="store_true",
                   help="don't fail when no degraded read was recorded")
    p.add_argument("--strict-reads", action="store_true",
                   help="count EIO-refused reads as failures even "
                        "with message faults armed")
    p.add_argument("--quiet", action="store_true")
    p.add_argument("--smoke", action="store_true",
                   help="CI mode: one round, kill-last, fixed seed")
    return p


def apply_smoke_overrides(args):
    """--smoke pins the CI configuration: one deterministic kill-last
    round; any byte mismatch/wedge exits non-zero."""
    if args.smoke:
        args.rounds = 1
        args.kill_last = True
        args.seed = 7
    return args


def main(argv: list[str] | None = None) -> int:
    args = apply_smoke_overrides(build_parser().parse_args(argv))
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(chaos_main(args))
    finally:
        loop.close()


if __name__ == "__main__":
    sys.exit(main())
