"""Cluster fault-injection driver: write -> kill -> re-peer -> verify.

The qa/tasks thrasher in miniature, built on the in-process cluster
(mon + N OSDs on loopback).  Each round writes a seeded working set to
an EC pool, kills one OSD (optionally with messenger faults armed via
common/faults.py), waits for the mon to mark it down, reads EVERY
object back under a deadline and byte-compares against what was
written, then (optionally) revives the OSD and verifies recovery
converges.  Shard mislabeling, wedged degraded reads and recovery
corruption all surface as hard failures here instead of in production.

CI smoke:  python -m ceph_tpu.tools.chaos --smoke
exits non-zero on any byte mismatch, wedged read, or lost object.
"""

from __future__ import annotations

import argparse
import asyncio
import random
import sys
import time

from ..common.faults import MessageFaultInjector
from ..loadgen.cluster import SimCluster
from ..msg import Message, Messenger
from ..osd.backend import pack_mutations


class ChaosCluster(SimCluster):
    """SimCluster plus a raw client messenger for low-level op drives.

    Bring-up, kill/revive tokens, wait_down/up/clean and perf
    aggregation all come from the shared ``loadgen.cluster``
    machinery; this subclass adds the bare-messenger client the chaos
    rounds use to submit ops without librados in the way.
    """

    def __init__(self, mon, osds, client,
                 faults: MessageFaultInjector | None = None) -> None:
        super().__init__(mon, osds, faults=faults)
        self.client = client
        self._op_serial = 0

    @classmethod
    async def create(cls, n_osds: int = 3, *,
                     mon_config: dict | None = None,
                     osd_config: dict | None = None,
                     faults: MessageFaultInjector | None = None
                     ) -> "ChaosCluster":
        base = await SimCluster.create(
            n_osds, mon_config=mon_config, osd_config=osd_config,
            faults=faults)
        client = Messenger("client.chaos")
        await client.bind()
        return cls(base.mon, base.osds, client, faults)

    async def stop(self) -> None:
        for o in self.osds:
            await o.stop()
        await self.client.shutdown()
        await self.mon.stop()

    # -- control plane -------------------------------------------------------
    async def command(self, cmd: str, args: dict | None = None) -> dict:
        q: asyncio.Queue = asyncio.Queue()

        async def d(conn, msg):
            if msg.type == "mon_command_reply":
                await q.put(msg.data)

        self.client.add_dispatcher(d)
        try:
            await self.client.send(
                self.mon.msgr.addr, "mon.0",
                Message("mon_command", {"cmd": cmd, "args": args or {}}))
            data = await asyncio.wait_for(q.get(), 10)
        finally:
            self.client.dispatchers.remove(d)
        if not data["ok"]:
            raise RuntimeError(data["error"])
        return data["result"]

    async def create_ec_pool(self, name: str, k: int, m: int,
                             pg_num: int) -> None:
        await self.command("osd erasure-code-profile set", {
            "name": f"chaos-k{k}m{m}",
            "profile": {"plugin": "tpu", "k": str(k), "m": str(m),
                        "technique": "reed_sol_van"}})
        await self.command("osd pool create", {
            "name": name, "type": "erasure", "pg_num": pg_num,
            "erasure_code_profile": f"chaos-k{k}m{m}"})

    # -- data plane ----------------------------------------------------------
    def _target_for(self, pool_name: str, oid: str):
        omap = self.mon.osdmap
        pool_id = omap.pool_names[pool_name]
        _, ps = omap.object_to_pg(pool_id, oid)
        up = omap.pg_to_up_acting_osds(pool_id, ps)
        return omap.pg_name(pool_id, ps), omap.pg_primary(up)

    async def osd_op(self, pool_name: str, oid: str, ops: list[dict],
                     timeout: float = 15.0, retries: int = 40):
        """One client op against the current primary, retrying through
        peering; the stable reqid keeps retries idempotent."""
        q: asyncio.Queue = asyncio.Queue()
        self._op_serial += 1
        tid = self._op_serial
        reqid = [f"{self.client.name}:{self.client.incarnation}", tid]

        async def d(conn, msg):
            if msg.type == "osd_op_reply" and msg.data.get("tid") == tid:
                await q.put(msg)

        self.client.add_dispatcher(d)
        try:
            for _ in range(retries):
                pgid, primary = self._target_for(pool_name, oid)
                if primary is None:
                    await asyncio.sleep(0.25)
                    continue
                addr = self.mon.osdmap.osds[primary].addr
                meta, segs = pack_mutations(ops)
                try:
                    await self.client.send(
                        tuple(addr), f"osd.{primary}",
                        Message("osd_op",
                                {"pgid": pgid, "oid": oid, "ops": meta,
                                 "reqid": reqid, "tid": tid},
                                segments=segs))
                    reply = await asyncio.wait_for(q.get(), timeout)
                except (ConnectionError, OSError, asyncio.TimeoutError):
                    await asyncio.sleep(0.25)
                    continue
                err = reply.data.get("err")
                if err in ("ENOTPRIMARY", "EAGAIN", "ENXIO no such pg"):
                    await asyncio.sleep(0.25)
                    continue
                return reply
            raise TimeoutError(f"osd_op on {oid} never succeeded")
        finally:
            self.client.dispatchers.remove(d)

async def run_round(c: ChaosCluster, *, rnd: random.Random,
                    pool: str, n_objects: int, min_size: int,
                    max_size: int, kill_index: int,
                    read_deadline: float, revive: bool,
                    log) -> dict:
    """One write -> kill -> re-peer -> read-back loop.  Returns a
    result dict with mismatch/wedge/error lists."""
    result = {"mismatched": [], "wedged": [], "refused": [],
              "errors": [], "n_objects": n_objects}
    objs: dict[str, bytes] = {}
    for i in range(n_objects):
        size = rnd.randrange(min_size, max_size + 1)
        data = rnd.getrandbits(8 * size).to_bytes(size, "little")
        oid = f"chaos-{i:04d}"
        objs[oid] = data
        # writefull: REPLACE semantics, so later rounds overwriting a
        # longer object from an earlier round don't leave a stale tail
        # that would read as a false mismatch
        await c.osd_op(pool, oid, [{"op": "writefull", "data": data}])
    # let laggard-healing re-pushes settle: every shard of every ack'd
    # write should be on disk before we pull an OSD out
    if not await c.wait_clean():
        result["errors"].append("cluster never went clean pre-kill")
    victim_id = c.osds[kill_index].whoami
    log(f"  wrote {n_objects} objects; killing osd.{victim_id}")
    token = await c.kill_osd(kill_index)
    if not await c.wait_down(victim_id):
        result["errors"].append(f"osd.{victim_id} never marked down")
        return result
    log(f"  osd.{victim_id} down; reading back under "
        f"{read_deadline:.0f}s deadline")
    for oid, want in objs.items():
        try:
            reply = await asyncio.wait_for(
                c.osd_op(pool, oid,
                         [{"op": "read", "off": 0, "len": None}],
                         timeout=10, retries=8),
                timeout=read_deadline)
        except (TimeoutError, asyncio.TimeoutError):
            result["wedged"].append(oid)
            continue
        if reply.data.get("err"):
            result["refused"].append(oid)
            continue
        r = reply.data["results"][0]
        data = reply.segments[r["seg"]] if "seg" in r else None
        if not r.get("ok"):
            # per-op error (e.g. EIO after bounded shard retries): the
            # read COMPLETED with a refusal -- bytes were never faked
            result["refused"].append(oid)
        elif data != want:
            result["mismatched"].append(oid)
    if revive:
        log(f"  reviving osd.{victim_id}")
        await c.revive_osd(kill_index, token)
        if not await c.wait_up(victim_id):
            result["errors"].append(f"osd.{victim_id} never came back")
    return result


async def chaos_main(args) -> int:
    rnd = random.Random(args.seed)
    faults = None
    if args.msg_drop_p > 0 or args.msg_delay > 0:
        faults = MessageFaultInjector(seed=args.seed)
        if args.msg_drop_p > 0:
            faults.drop(peer="osd.", probability=args.msg_drop_p)
        if args.msg_delay > 0:
            faults.delay(args.msg_delay, peer="osd.",
                         probability=args.msg_delay_p)
    c = await ChaosCluster.create(
        args.osds,
        mon_config={"mon_osd_down_out_interval": 3600.0},
        osd_config={"osd_heartbeat_interval": 0.2,
                    "osd_heartbeat_grace": 3.0},
        faults=faults)
    failures = 0

    def log(msg: str) -> None:
        if not args.quiet:
            print(msg, flush=True)

    try:
        await c.create_ec_pool("chaospool", args.k, args.m, args.pg_num)
        for r in range(args.rounds):
            log(f"round {r + 1}/{args.rounds}")
            kill_index = (len(c.osds) - 1 if args.kill_last
                          else rnd.randrange(len(c.osds)))
            res = await run_round(
                c, rnd=rnd, pool="chaospool",
                n_objects=args.objects, min_size=args.min_size,
                max_size=args.max_size, kill_index=kill_index,
                read_deadline=args.read_deadline,
                revive=(r + 1 < args.rounds), log=log)
            bad = (len(res["mismatched"]) + len(res["wedged"])
                   + len(res["errors"]))
            # an EIO refusal is a failure only on a clean network: with
            # drop faults armed, a write ack'd at min_size can lose a
            # shard to the kill before the re-push lands -- the honest
            # outcome is a refused read, never fabricated bytes
            if faults is None or args.strict_reads:
                bad += len(res["refused"])
            failures += bad
            log(f"  result: {res['n_objects'] - bad}/{res['n_objects']}"
                f" clean, mismatched={res['mismatched']}, "
                f"wedged={res['wedged']}, refused={res['refused']}, "
                f"errors={res['errors']}")
        deg = c.perf_counters("ec_degraded")
        log(f"ec_degraded counters: {deg}")
        if faults is not None:
            log(f"fault_inject stats: {faults.stats}")
        if not deg.get("degraded_reads") and not args.allow_clean:
            # reading back with a dead shard holder MUST have exercised
            # reconstruction; a zero here means the drive tested nothing
            log("ERROR: no degraded reads recorded -- harness broken?")
            failures += 1
        # the client routed every op through mon.osdmap's cached table
        # and each OSD retargeted through its own; kills/re-peering
        # bump epochs, so zero bulk recomputes means the epoch-keyed
        # invalidation never fired and the drive read stale placement
        pc = c.perf_counters("placement_cache")
        mon_pc = c.mon.osdmap.placement_perf.dump()
        log(f"placement_cache counters: osds={pc} mon={mon_pc}")
        if not mon_pc.get("bulk_recomputes") or not pc.get(
                "bulk_recomputes"):
            log("ERROR: placement cache never recomputed across the "
                "kill -- invalidation broken?")
            failures += 1
    finally:
        await c.stop()
    log(f"{'FAIL' if failures else 'PASS'}: {failures} failures")
    return 1 if failures else 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="EC cluster fault-injection driver")
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--objects", type=int, default=24)
    p.add_argument("--osds", type=int, default=3)
    p.add_argument("--k", type=int, default=2)
    p.add_argument("--m", type=int, default=1)
    p.add_argument("--pg-num", type=int, default=16)
    p.add_argument("--min-size", type=int, default=8 << 10)
    p.add_argument("--max-size", type=int, default=32 << 10)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--kill-last", action="store_true",
                   help="always kill the last OSD (the ROADMAP repro) "
                        "instead of a seeded random victim")
    p.add_argument("--read-deadline", type=float, default=60.0,
                   help="per-object read-back deadline; exceeding it "
                        "counts as a wedged read")
    p.add_argument("--msg-drop-p", type=float, default=0.0,
                   help="drop probability for osd<->osd messages")
    p.add_argument("--msg-delay", type=float, default=0.0,
                   help="injected delay seconds for osd<->osd messages")
    p.add_argument("--msg-delay-p", type=float, default=0.2)
    p.add_argument("--allow-clean", action="store_true",
                   help="don't fail when no degraded read was recorded")
    p.add_argument("--strict-reads", action="store_true",
                   help="count EIO-refused reads as failures even "
                        "with message faults armed")
    p.add_argument("--quiet", action="store_true")
    p.add_argument("--smoke", action="store_true",
                   help="CI mode: one round, kill-last, fixed seed")
    return p


def apply_smoke_overrides(args):
    """--smoke pins the CI configuration: one deterministic kill-last
    round; any byte mismatch/wedge exits non-zero."""
    if args.smoke:
        args.rounds = 1
        args.kill_last = True
        args.seed = 7
    return args


def main(argv: list[str] | None = None) -> int:
    args = apply_smoke_overrides(build_parser().parse_args(argv))
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(chaos_main(args))
    finally:
        loop.close()


if __name__ == "__main__":
    sys.exit(main())
