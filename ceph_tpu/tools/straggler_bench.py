"""Straggler bench rig: hedged vs unhedged EC reads under heavy tails.

A loadgen phase (SimCluster + ClientSwarm, the same spine as
``bench.py --cluster``) driven TWICE over an identical deterministic
workload and an identical per-peer heavy-tail delay schedule (the
fault injector's straggler mode draws each peer's delay sequence from
a (seed, peer)-keyed RNG stream, so both variants race the very same
stragglers):

* **unhedged** -- ``osd_ec_hedge_enabled=false``: every degraded
  gather awaits its fixed shard set, so a straggling source sets the
  op's latency (the pre-ISSUE-11 behavior);
* **hedged** -- the HedgedGather engine arms the adaptive per-peer
  EWMA quantile, requests extra shards on fire, and decodes from the
  first sufficient set.

Reported per variant: the read latency histogram (log-bucketed, the
loadgen percentiles), total sub-reads issued + reply bytes (the
hedging cost), and the ``ec_hedge``/``ec_degraded`` counter deltas.
Gates (the ISSUE-11 acceptance set, enforced by ``bench.py
--straggler``): p99 hedged >= 2x better, extra shard reads <= 1.5x,
zero failed/wedged ops, zero leaked sub-read tasks, and every object
byte-identical to the written ground truth in BOTH variants -- the
unhedged full-set gather IS the oracle the first-k decode must match.
"""

from __future__ import annotations

import math
import time

from ..common.faults import RECV, MessageFaultInjector
from ..loadgen import ClientSwarm, SimCluster, WorkloadSpec
from ..loadgen.driver import _create_pool
from ..loadgen.spec import payload_for

# knobs shared by both variants: fast EWMA warm-up, a tight hedge
# ceiling (the straggler tail is far above it), snappy heartbeats
_OSD_CONFIG = {
    "osd_ec_hedge_delay_min": 0.005,
    "osd_ec_hedge_delay_max": 0.2,
    "osd_ec_hedge_min_samples": 2,
    "osd_ec_read_timeout": 8.0,
}


def _spec(n_osds, pg_num, n_objects, obj_bytes, n_reads, n_clients,
          seed) -> WorkloadSpec:
    return WorkloadSpec(
        n_osds=n_osds, pg_num=pg_num, pool="stragglerpool",
        pool_type="erasure", ec_k=2, ec_m=1,
        n_objects=n_objects, obj_size=obj_bytes,
        n_ops=n_reads, read_frac=1.0, write_frac=0.0, rmw_frac=0.0,
        popularity="uniform", n_clients=n_clients,
        seed=seed).validate()


def _counters(cluster, which: str) -> dict:
    return cluster.perf_counters(which)


async def _drive_variant(spec: WorkloadSpec, *, hedge: bool,
                         fault_seed: int, straggler_peers: int,
                         dist: str, dist_params: dict,
                         log=print) -> dict:
    """One full cluster lifetime: bring-up, preload, EWMA warm-up,
    straggler phase, byte verification, teardown."""
    inj = MessageFaultInjector(seed=fault_seed)
    cluster = await SimCluster.create(
        spec.n_osds,
        osd_config={**_OSD_CONFIG,
                    "osd_ec_hedge_enabled": hedge},
        faults=inj, log=log)
    swarm = None
    try:
        await _create_pool(cluster.addr, spec)
        swarm = ClientSwarm(spec, cluster.addr)
        await swarm.start()
        load = await swarm.preload()
        if load.failed or load.wedged:
            raise RuntimeError(
                f"preload failed ops: {load.errors[:4]}")
        # warm pass: healthy latencies feed every primary's per-peer
        # EWMA (and make the two variants start from identical state)
        warm = await swarm.run_phase(spec.schedule(salt="warm"),
                                     "warm")
        # arm the SAME deterministic straggler schedule either way:
        # the first `straggler_peers` OSDs' read replies go heavy-tail
        victims = sorted(o.whoami for o in cluster.osds
                         )[:straggler_peers]
        for v in victims:
            inj.straggler(f"osd.{v}", dist=dist,
                          mtype="ec_subop_read_reply",
                          direction=RECV, **dist_params)
        hedge0 = _counters(cluster, "ec_hedge")
        degr0 = _counters(cluster, "ec_degraded")
        t0 = time.perf_counter()
        phase = await swarm.run_phase(spec.schedule(salt="steady"),
                                      "straggler")
        elapsed = time.perf_counter() - t0
        hedge1 = _counters(cluster, "ec_hedge")
        degr1 = _counters(cluster, "ec_degraded")
        deltas = {k: hedge1.get(k, 0) - hedge0.get(k, 0)
                  for k in set(hedge0) | set(hedge1)}
        retries = degr1.get("gather_retries", 0) \
            - degr0.get("gather_retries", 0)
        # byte identity against the written ground truth (the payload
        # generator is pure in (spec, size)); the straggler schedule
        # stays armed -- a verify pass that only passes with the
        # faults healed would prove nothing
        io = swarm.ioctxs[0]
        mismatches = []
        for i in range(spec.n_objects):
            oid = spec.object_name(i)
            want = payload_for(spec, spec.object_size(i))
            got = await io.read(oid)
            if bytes(got) != want:
                mismatches.append(oid)
        # leak check: after the phase settles, no sub-read task
        # (OSD.start_request's ``_issue`` coroutine) may still be
        # pending -- a live one means a gather exited without
        # cancelling/reaping its stragglers
        import asyncio
        await asyncio.sleep(0.05)
        leaked = sum(
            1 for t in asyncio.all_tasks()
            if not t.done()
            and getattr(t.get_coro(), "__name__", "") == "_issue")
        waiters = sum(o.inflight_ops() for o in cluster.osds)
        lat = phase.hists["read"].summary()
        return {
            "hedge": hedge,
            "victims": victims,
            "ops": phase.ops,
            "failed_ops": phase.failed,
            "wedged_ops": phase.wedged,
            "elapsed_s": round(elapsed, 3),
            "ops_per_s": round(phase.ops / elapsed, 1)
            if elapsed else 0.0,
            "latency": lat,
            "warm_p99_s": warm.hists["read"].summary().get("p99_s"),
            "subreads": deltas.get("subreads", 0),
            "subread_bytes": deltas.get("subread_bytes", 0),
            "hedge_subreads": deltas.get("hedge_subreads", 0),
            "hedge_bytes": deltas.get("hedge_bytes", 0),
            "hedges_armed": deltas.get("hedges_armed", 0),
            "hedges_fired": deltas.get("hedges_fired", 0),
            "hedges_won": deltas.get("hedges_won", 0),
            "hedges_wasted": deltas.get("hedges_wasted", 0),
            "cancelled_subreads": deltas.get("cancelled_subreads", 0),
            "first_set_completions":
                deltas.get("first_set_completions", 0),
            "gather_retries": retries,
            "straggler_delays": inj.stats.get("straggler_delays", 0),
            "byte_mismatches": mismatches,
            "leaked_tasks": leaked,
            "pending_tid_waiters": waiters,
        }
    finally:
        if swarm is not None:
            await swarm.shutdown()
        await cluster.stop()


async def run_straggler_bench(*, n_osds: int = 5, pg_num: int = 32,
                              n_objects: int = 24,
                              obj_bytes: int = 12 << 10,
                              n_reads: int = 96, n_clients: int = 8,
                              seed: int = 7, fault_seed: int = 11,
                              straggler_peers: int = 1,
                              dist: str = "lognormal",
                              dist_params: dict | None = None,
                              log=print) -> dict:
    """The full comparison: one unhedged drive, one hedged drive,
    identical workload + identical per-peer straggler schedule."""
    if dist_params is None:
        # median ~0.5s, p99 ~1.1s, capped at 2s: a tail far above any
        # healthy sub-read but far below the read deadline, so the
        # unhedged variant measures pure straggler wait (no retries)
        dist_params = {"mu": math.log(0.5), "sigma": 0.35, "cap": 2.0}
    spec = _spec(n_osds, pg_num, n_objects, obj_bytes, n_reads,
                 n_clients, seed)
    log(f"straggler bench: {n_osds} osds, {n_objects} objects, "
        f"{n_reads} reads, {straggler_peers} straggler peer(s), "
        f"{dist} {dist_params}")
    unhedged = await _drive_variant(
        spec, hedge=False, fault_seed=fault_seed,
        straggler_peers=straggler_peers, dist=dist,
        dist_params=dist_params, log=log)
    log(f"unhedged: p99={unhedged['latency'].get('p99_s')}s "
        f"subreads={unhedged['subreads']}")
    hedged = await _drive_variant(
        spec, hedge=True, fault_seed=fault_seed,
        straggler_peers=straggler_peers, dist=dist,
        dist_params=dist_params, log=log)
    log(f"hedged:   p99={hedged['latency'].get('p99_s')}s "
        f"subreads={hedged['subreads']} "
        f"fired={hedged['hedges_fired']} won={hedged['hedges_won']}")
    p99_un = unhedged["latency"].get("p99_s") or 0.0
    p99_he = hedged["latency"].get("p99_s") or 0.0
    speedup = round(p99_un / p99_he, 2) if p99_he else 0.0
    extra = round(hedged["subreads"] / unhedged["subreads"], 3) \
        if unhedged["subreads"] else 0.0
    extra_bytes = round(
        hedged["subread_bytes"] / unhedged["subread_bytes"], 3) \
        if unhedged["subread_bytes"] else 0.0
    return {
        "spec": {"n_osds": n_osds, "pg_num": pg_num,
                 "n_objects": n_objects, "obj_bytes": obj_bytes,
                 "n_reads": n_reads, "n_clients": n_clients,
                 "seed": seed, "fault_seed": fault_seed,
                 "straggler_peers": straggler_peers,
                 "dist": dist, "dist_params": dist_params},
        "unhedged": unhedged,
        "hedged": hedged,
        "p99_unhedged_s": p99_un,
        "p99_hedged_s": p99_he,
        "p99_speedup": speedup,
        "extra_subread_ratio": extra,
        "extra_byte_ratio": extra_bytes,
        "failed_ops": unhedged["failed_ops"] + hedged["failed_ops"],
        "wedged_ops": unhedged["wedged_ops"] + hedged["wedged_ops"],
        "leaked_tasks": unhedged["leaked_tasks"]
        + hedged["leaked_tasks"],
        "byte_mismatches": unhedged["byte_mismatches"]
        + hedged["byte_mismatches"],
    }
