"""crushtool analog: crushmap text grammar + placement simulator.

Mirrors the reference's CrushCompiler text format (src/crush/
CrushCompiler.cc: tunables/devices/types/buckets/rules sections) and
`crushtool --test` (src/tools/crushtool.cc:546 / CrushTester): compile
a text map, decompile one back, and simulate mappings over an x range
with per-device utilization -- placement what-ifs with zero daemons.

Usage:
  python -m ceph_tpu.tools.crushtool -c map.txt -o map.json
  python -m ceph_tpu.tools.crushtool -d map.json
  python -m ceph_tpu.tools.crushtool --test -i map.json \
      --rule 0 --num-rep 3 --min-x 0 --max-x 1023 [--show-utilization]
      [--weight OSD W]...
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict

from ..crush import CrushMap
from ..crush.types import (
    Bucket, Rule, RuleStep, Tunables,
    CRUSH_BUCKET_UNIFORM, CRUSH_BUCKET_LIST, CRUSH_BUCKET_TREE,
    CRUSH_BUCKET_STRAW, CRUSH_BUCKET_STRAW2,
    CRUSH_RULE_TYPE_REPLICATED, CRUSH_RULE_TYPE_ERASURE,
    CRUSH_RULE_TAKE, CRUSH_RULE_EMIT,
    CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP,
    CRUSH_RULE_CHOOSELEAF_FIRSTN, CRUSH_RULE_CHOOSELEAF_INDEP,
)

ALGS = {"uniform": CRUSH_BUCKET_UNIFORM, "list": CRUSH_BUCKET_LIST,
        "tree": CRUSH_BUCKET_TREE, "straw": CRUSH_BUCKET_STRAW,
        "straw2": CRUSH_BUCKET_STRAW2}
ALG_NAMES = {v: k for k, v in ALGS.items()}
RULE_TYPES = {"replicated": CRUSH_RULE_TYPE_REPLICATED,
              "erasure": CRUSH_RULE_TYPE_ERASURE}
RULE_TYPE_NAMES = {v: k for k, v in RULE_TYPES.items()}
TUNABLE_FIELDS = {
    "choose_local_tries", "choose_local_fallback_tries",
    "choose_total_tries", "chooseleaf_descend_once",
    "chooseleaf_vary_r", "chooseleaf_stable",
}


class CompileError(ValueError):
    pass


class _Tokens:
    """Flat token stream (the grammar is token-, not line-based; the
    reference compiler uses a spirit grammar the same way)."""

    def __init__(self, text: str) -> None:
        toks: list[str] = []
        for raw in text.splitlines():
            line = raw.split("#", 1)[0]
            for word in line.replace("{", " { ").replace("}", " } ") \
                            .split():
                toks.append(word)
        self.toks = toks
        self.pos = 0

    def peek(self) -> str | None:
        return self.toks[self.pos] if self.pos < len(self.toks) else None

    def next(self, what: str = "token") -> str:
        if self.pos >= len(self.toks):
            raise CompileError(f"unexpected end of map, wanted {what}")
        t = self.toks[self.pos]
        self.pos += 1
        return t

    def expect(self, tok: str) -> None:
        got = self.next(tok)
        if got != tok:
            raise CompileError(f"expected {tok!r}, got {got!r}")

    def next_int(self, what: str) -> int:
        t = self.next(what)
        try:
            return int(t)
        except ValueError:
            raise CompileError(f"{what}: not an integer: {t!r}")


def compile_text(text: str):
    """Text crushmap -> (CrushMap, type names, device ids)."""
    ts = _Tokens(text)
    cm = CrushMap()
    tun: dict[str, int] = {}
    types: dict[str, int] = {}
    type_names: dict[int, str] = {}
    devices: dict[str, int] = {}
    names: dict[str, int] = {}     # bucket name -> id

    def item_id(name: str) -> int:
        if name in devices:
            return devices[name]
        if name in names:
            return names[name]
        raise CompileError(f"unknown item {name!r}")

    while (tok := ts.peek()) is not None:
        if tok == "tunable":
            ts.next()
            name = ts.next("tunable name")
            if name not in TUNABLE_FIELDS:
                raise CompileError(f"unknown tunable {name}")
            tun[name] = ts.next_int("tunable value")
        elif tok == "device":
            ts.next()
            did = ts.next_int("device id")
            devices[ts.next("device name")] = did
        elif tok == "type":
            ts.next()
            tid = ts.next_int("type id")
            tname = ts.next("type name")
            types[tname] = tid
            type_names[tid] = tname
        elif tok in types:
            btype = types[ts.next()]
            bname = ts.next("bucket name")
            ts.expect("{")
            bid = None
            alg = CRUSH_BUCKET_STRAW2
            bhash = 0
            items: list[int] = []
            weights: list[int] = []
            while (st := ts.next("bucket body")) != "}":
                if st == "id":
                    bid = ts.next_int("bucket id")
                elif st == "alg":
                    a = ts.next("alg")
                    if a not in ALGS:
                        raise CompileError(f"unknown alg {a}")
                    alg = ALGS[a]
                elif st == "hash":
                    bhash = ts.next_int("hash")
                elif st == "item":
                    iname = ts.next("item name")
                    w = 0x10000
                    if ts.peek() == "weight":
                        ts.next()
                        w = int(round(float(ts.next("weight"))
                                      * 0x10000))
                    items.append(item_id(iname))
                    weights.append(w)
                else:
                    raise CompileError(f"bad bucket token: {st!r}")
            if bid is None:
                raise CompileError(f"bucket {bname} has no id")
            cm.add_bucket(Bucket(id=bid, type=btype, alg=alg,
                                 hash=bhash, items=items,
                                 item_weights=weights), bname)
            names[bname] = bid
        elif tok == "rule":
            ts.next()
            ts.next("rule name")
            ts.expect("{")
            rid = None
            rtype = CRUSH_RULE_TYPE_REPLICATED
            steps: list[RuleStep] = []
            while (st := ts.next("rule body")) != "}":
                if st == "id":
                    rid = ts.next_int("rule id")
                elif st == "type":
                    tv = ts.next("rule type")
                    if tv in RULE_TYPES:
                        rtype = RULE_TYPES[tv]
                    elif tv.isdigit():
                        rtype = int(tv)
                    else:
                        raise CompileError(f"bad rule type {tv}")
                elif st in ("min_size", "max_size"):
                    ts.next()            # legacy, ignored
                elif st == "step":
                    steps.append(_parse_step(ts, names, types))
                else:
                    raise CompileError(f"bad rule token: {st!r}")
            if rid is None:
                raise CompileError("rule has no id")
            cm.add_rule(Rule(rule_id=rid, type=rtype, steps=steps))
        else:
            raise CompileError(f"unexpected token: {tok!r}")
    if tun:
        cm.tunables = Tunables(**{**cm.tunables.__dict__, **tun})
    return cm, type_names, sorted(devices.values())


def _parse_step(ts: _Tokens, names, types) -> RuleStep:
    op = ts.next("step op")
    if op == "take":
        b = ts.next("take bucket")
        if b not in names:
            raise CompileError(f"take: unknown bucket {b}")
        return RuleStep(CRUSH_RULE_TAKE, names[b])
    if op == "emit":
        return RuleStep(CRUSH_RULE_EMIT)
    ops = {("choose", "firstn"): CRUSH_RULE_CHOOSE_FIRSTN,
           ("choose", "indep"): CRUSH_RULE_CHOOSE_INDEP,
           ("chooseleaf", "firstn"): CRUSH_RULE_CHOOSELEAF_FIRSTN,
           ("chooseleaf", "indep"): CRUSH_RULE_CHOOSELEAF_INDEP}
    mode = ts.next("choose mode")
    key = (op, mode)
    if key not in ops:
        raise CompileError(f"bad step: {op} {mode}")
    n = ts.next_int("choose n")
    ts.expect("type")
    tname = ts.next("choose type")
    if tname not in types:
        raise CompileError(f"unknown type {tname}")
    return RuleStep(ops[key], n, types[tname])


def decompile(cm: CrushMap, type_names: dict[int, str] | None = None,
              devices: list[int] | None = None) -> str:
    type_names = dict(type_names or {0: "osd", 1: "host", 10: "root"})
    # every bucket/choose type needs a declaration or the emitted text
    # cannot recompile
    seen = {b.type for b in cm.buckets.values()} | {0}
    for r in cm.rules.values():
        seen |= {st.arg2 for st in r.steps
                 if st.op not in (CRUSH_RULE_TAKE, CRUSH_RULE_EMIT)}
    for t_ in sorted(seen):
        type_names.setdefault(t_, f"type{t_}")
    if devices is None:
        devices = sorted({i for b in cm.buckets.values()
                          for i in b.items if i >= 0})
    out = ["# begin crush map"]
    t = cm.tunables
    for f in sorted(TUNABLE_FIELDS):
        out.append(f"tunable {f} {int(getattr(t, f))}")
    out.append("\n# devices")
    for d in devices:
        out.append(f"device {d} osd.{d}")
    out.append("\n# types")
    for tid in sorted(type_names):
        out.append(f"type {tid} {type_names[tid]}")
    out.append("\n# buckets")

    def bname(bid: int) -> str:
        return cm.bucket_names.get(bid, f"bucket{-bid}")

    # children before parents (the compiler needs items defined first)
    emitted: set[int] = set()

    def emit_bucket(b: Bucket):
        if b.id in emitted:
            return
        for item in b.items:
            if item < 0 and item in cm.buckets:
                emit_bucket(cm.buckets[item])
        emitted.add(b.id)
        tname = type_names.get(b.type, str(b.type))
        out.append(f"{tname} {bname(b.id)} {{")
        out.append(f"\tid {b.id}")
        out.append(f"\talg {ALG_NAMES.get(b.alg, b.alg)}")
        out.append(f"\thash {b.hash}\t# rjenkins1")
        for item, w in zip(b.items, b.item_weights):
            iname = f"osd.{item}" if item >= 0 else bname(item)
            out.append(f"\titem {iname} weight {w / 0x10000:.5f}")
        out.append("}")

    for b in cm.buckets.values():
        emit_bucket(b)
    out.append("\n# rules")
    step_names = {CRUSH_RULE_CHOOSE_FIRSTN: "choose firstn",
                  CRUSH_RULE_CHOOSE_INDEP: "choose indep",
                  CRUSH_RULE_CHOOSELEAF_FIRSTN: "chooseleaf firstn",
                  CRUSH_RULE_CHOOSELEAF_INDEP: "chooseleaf indep"}
    for r in cm.rules.values():
        out.append(f"rule rule{r.rule_id} {{")
        out.append(f"\tid {r.rule_id}")
        out.append(f"\ttype {RULE_TYPE_NAMES.get(r.type, r.type)}")
        for s in r.steps:
            if s.op == CRUSH_RULE_TAKE:
                out.append(f"\tstep take {bname(s.arg1)}")
            elif s.op == CRUSH_RULE_EMIT:
                out.append("\tstep emit")
            elif s.op in step_names:
                tname = type_names.get(s.arg2, str(s.arg2))
                out.append(f"\tstep {step_names[s.op]} {s.arg1} "
                           f"type {tname}")
        out.append("}")
    out.append("\n# end crush map")
    return "\n".join(out) + "\n"


def run_test(cm: CrushMap, ruleno: int, numrep: int, min_x: int,
             max_x: int, weights: dict[int, float],
             show_utilization: bool, out=sys.stdout) -> dict:
    import numpy as np

    from ..crush.types import CRUSH_ITEM_NONE
    from ..mon.pg_mapping import bulk_crush

    n = max([i for b in cm.buckets.values() for i in b.items
             if i >= 0] + [o for o in weights], default=-1) + 1
    w = [0x10000] * n
    for osd, wf in weights.items():
        w[osd] = int(round(wf * 0x10000))
    counts: dict[int, int] = defaultdict(int)
    sizes: dict[int, int] = defaultdict(int)
    # the whole x range maps in ONE bulk launch through the same
    # helper the placement cache rides (fused when the shape compiles
    # and the range is large enough, scalar sweep otherwise) -- the
    # simulator exercises the production bulk path, not a private one
    rule = cm.rules.get(ruleno)
    firstn = rule is not None and any(
        s.op in (CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSELEAF_FIRSTN)
        for s in rule.steps)
    xs = np.arange(min_x, max_x + 1, dtype=np.int64)
    rows, _ = bulk_crush(cm, ruleno, xs, numrep, w)
    for x, row in zip(xs, rows):
        res = [int(r) for r in row]
        if firstn:
            # scalar firstn returns a compacted vector with no NONE
            # padding; strip it so output matches crush_do_rule's
            res = [r for r in res if r != CRUSH_ITEM_NONE]
        elif rule is None:
            res = []
        print(f"CRUSH rule {ruleno} x {x} {res}", file=out)
        sizes[len([r for r in res if 0 <= r < n])] += 1
        for r in res:
            if 0 <= r < n:
                counts[r] += 1
    total = max_x - min_x + 1
    for sz in sorted(sizes):
        print(f"rule {ruleno} ({ruleno}) num_rep {numrep} "
              f"result size == {sz}:\t{sizes[sz]}/{total}", file=out)
    if show_utilization:
        for osd in sorted(counts):
            print(f"  device {osd}:\t stored : {counts[osd]}", file=out)
    return {"counts": dict(counts), "sizes": dict(sizes)}


def _load_map(path: str):
    with open(path) as f:
        content = f.read()
    if content.lstrip().startswith("{"):
        from ..mon.osdmap import crush_from_dict
        d = json.loads(content)
        return crush_from_dict(d), None, None
    cm, type_names, devices = compile_text(content)
    return cm, type_names, devices


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="crushtool")
    ap.add_argument("-c", "--compile", metavar="TXT",
                    help="compile a text map")
    ap.add_argument("-d", "--decompile", metavar="MAP",
                    help="decompile a map (json or text)")
    ap.add_argument("-i", "--in-map", metavar="MAP")
    ap.add_argument("-o", "--out-file", metavar="OUT")
    ap.add_argument("--test", action="store_true")
    ap.add_argument("--rule", type=int, default=0)
    ap.add_argument("--num-rep", type=int, default=3)
    ap.add_argument("--min-x", type=int, default=0)
    ap.add_argument("--max-x", type=int, default=1023)
    ap.add_argument("--weight", nargs=2, action="append", default=[],
                    metavar=("OSD", "W"))
    ap.add_argument("--show-utilization", action="store_true")
    args = ap.parse_args(argv)

    if args.compile:
        cm, _, _ = _load_map(args.compile)
        from ..mon.osdmap import crush_to_dict
        blob = json.dumps(crush_to_dict(cm), indent=1)
        if args.out_file:
            with open(args.out_file, "w") as f:
                f.write(blob)
        else:
            print(blob)
        return 0
    if args.decompile:
        cm, type_names, devices = _load_map(args.decompile)
        text = decompile(cm, type_names, devices)
        if args.out_file:
            with open(args.out_file, "w") as f:
                f.write(text)
        else:
            print(text, end="")
        return 0
    if args.test:
        if not args.in_map:
            ap.error("--test requires -i/--in-map")
        cm, _, _ = _load_map(args.in_map)
        run_test(cm, args.rule, args.num_rep, args.min_x, args.max_x,
                 {int(o): float(w) for o, w in args.weight},
                 args.show_utilization)
        return 0
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
