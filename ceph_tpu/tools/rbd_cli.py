"""``rbd`` CLI analog (src/tools/rbd): image create/ls/info/rm/resize,
snapshots, clone/flatten, export/import, and a micro write bench.

Usage (against a vstart cluster):
    python -m ceph_tpu.tools.rbd_cli --mon 127.0.0.1:6789 \
        create -p rbd --size 64M img1
"""

from __future__ import annotations

import argparse
import asyncio
import sys
import time

from ..client import Rados
from ..rbd import RBD, Image


def parse_size(s: str) -> int:
    s = s.strip().upper()
    mult = 1
    for suf, m in (("K", 1 << 10), ("M", 1 << 20), ("G", 1 << 30),
                   ("T", 1 << 40)):
        if s.endswith(suf):
            s, mult = s[:-1], m
            break
    return int(float(s) * mult)


async def amain(args) -> int:
    host, port = args.mon.rsplit(":", 1)
    rados = await Rados((host, int(port))).connect()
    try:
        io = await rados.open_ioctx(args.pool)
        rbd = RBD()
        if args.cmd == "create":
            await rbd.create(io, args.image, parse_size(args.size),
                             order=args.order)
            print(f"created {args.image} ({args.size})")
        elif args.cmd == "ls":
            for name in await rbd.list(io):
                print(name)
        elif args.cmd == "info":
            img = await Image.open(io, args.image, read_only=True)
            st = img.stat()
            await img.close()
            print(f"rbd image '{args.image}':")
            print(f"\tsize {st['size']} bytes in {st['num_objs']} objects")
            print(f"\torder {st['order']} "
                  f"({1 << st['order']} byte objects)")
            print(f"\tid: {st['id']}")
            print(f"\tblock_name_prefix: {st['object_prefix']}")
            if st["parent"]:
                print(f"\tparent: pool {st['parent']['pool_id']} "
                      f"image {st['parent']['image_id']} "
                      f"snap {st['parent']['snap_id']}")
            for s in st["snapshots"]:
                prot = " (protected)" if s.get("protected") else ""
                print(f"\tsnap {s['name']} id {s['id']} "
                      f"size {s['size']}{prot}")
        elif args.cmd == "rm":
            await rbd.remove(io, args.image)
            print(f"removed {args.image}")
        elif args.cmd == "resize":
            img = await Image.open(io, args.image)
            await img.resize(parse_size(args.size))
            await img.close()
            print(f"resized {args.image} to {args.size}")
        elif args.cmd == "snap":
            img = await Image.open(io, args.image,
                                   read_only=args.snap_cmd == "ls")
            try:
                if args.snap_cmd == "create":
                    sid = await img.create_snap(args.snap)
                    print(f"snap {args.snap} id {sid}")
                elif args.snap_cmd == "rm":
                    await img.remove_snap(args.snap)
                elif args.snap_cmd == "ls":
                    for s in img.list_snaps():
                        print(f"{s['id']}\t{s['name']}\t{s['size']}")
                elif args.snap_cmd == "protect":
                    await img.protect_snap(args.snap)
                elif args.snap_cmd == "unprotect":
                    await img.unprotect_snap(args.snap)
                elif args.snap_cmd == "rollback":
                    await img.rollback_snap(args.snap)
            finally:
                await img.close()
        elif args.cmd == "clone":
            ppool, rest = args.parent_spec.split("/", 1)
            pname, snap = rest.split("@", 1)
            pio = await rados.open_ioctx(ppool)
            await rbd.clone(pio, pname, snap, io, args.image)
            print(f"cloned {args.parent_spec} -> {args.image}")
        elif args.cmd == "flatten":
            img = await Image.open(io, args.image)
            await img.flatten()
            await img.close()
            print(f"flattened {args.image}")
        elif args.cmd == "export":
            img = await Image.open(io, args.image, read_only=True)
            out = (sys.stdout.buffer if args.path == "-"
                   else open(args.path, "wb"))
            try:
                async for _, chunk in img.export():
                    out.write(chunk)
            finally:
                if args.path != "-":
                    out.close()
                await img.close()
        elif args.cmd == "import":
            data = (sys.stdin.buffer.read() if args.path == "-"
                    else open(args.path, "rb").read())
            await rbd.create(io, args.image, len(data), order=args.order)
            img = await Image.open(io, args.image)
            step = 1 << 22
            for off in range(0, len(data), step):
                await img.write(off, data[off:off + step])
            await img.close()
            print(f"imported {len(data)} bytes into {args.image}")
        elif args.cmd == "mirror":
            from ..rbd.mirror import (
                mirror_disable, mirror_enable, mirror_enabled,
                mirror_status,
            )
            if args.mirror_cmd != "ls" and not args.image:
                print(f"error: mirror {args.mirror_cmd} requires an "
                      f"image name", file=sys.stderr)
                return 2
            if args.mirror_cmd == "enable":
                await mirror_enable(io, args.image)
                print(f"mirroring enabled for {args.image}")
            elif args.mirror_cmd == "disable":
                await mirror_disable(io, args.image)
                print(f"mirroring disabled for {args.image}")
            elif args.mirror_cmd == "ls":
                for name in await mirror_enabled(io):
                    print(name)
            elif args.mirror_cmd == "status":
                print(await mirror_status(io, args.image))
        elif args.cmd == "bench":
            img = await Image.open(io, args.image)
            size = await img.size()
            bs = parse_size(args.io_size)
            total = parse_size(args.io_total)
            if bs > size:
                await img.close()
                print(f"error: --io-size {args.io_size} exceeds image "
                      f"size {size}", file=sys.stderr)
                return 1
            slots = size // bs          # aligned, in-bounds positions
            buf = (bytes(range(256)) * (bs // 256 + 1))[:bs]
            t0 = time.perf_counter()
            done = i = 0
            while done < total:
                await img.write((i % slots) * bs, buf)
                i += 1
                done += bs
            dt = time.perf_counter() - t0
            await img.close()
            print(f"elapsed {dt:.2f}s  ops {total // bs}  "
                  f"bytes/sec {total / dt:.0f}")
        return 0
    finally:
        await rados.shutdown()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="rbd")
    p.add_argument("--mon", default="127.0.0.1:6789")
    p.add_argument("-p", "--pool", default="rbd")
    sub = p.add_subparsers(dest="cmd", required=True)
    sp = sub.add_parser("create")
    sp.add_argument("image")
    sp.add_argument("--size", required=True)
    sp.add_argument("--order", type=int, default=22)
    sub.add_parser("ls")
    sp = sub.add_parser("info"); sp.add_argument("image")
    sp = sub.add_parser("rm"); sp.add_argument("image")
    sp = sub.add_parser("resize")
    sp.add_argument("image"); sp.add_argument("--size", required=True)
    sp = sub.add_parser("snap")
    sp.add_argument("snap_cmd", choices=["create", "rm", "ls", "protect",
                                         "unprotect", "rollback"])
    sp.add_argument("image")
    sp.add_argument("snap", nargs="?")
    sp = sub.add_parser("clone")
    sp.add_argument("parent_spec", help="pool/image@snap")
    sp.add_argument("image")
    sp = sub.add_parser("flatten"); sp.add_argument("image")
    sp = sub.add_parser("export")
    sp.add_argument("image"); sp.add_argument("path")
    sp = sub.add_parser("import")
    sp.add_argument("path"); sp.add_argument("image")
    sp.add_argument("--order", type=int, default=22)
    sp = sub.add_parser("mirror")
    sp.add_argument("mirror_cmd",
                    choices=["enable", "disable", "ls", "status"])
    sp.add_argument("image", nargs="?")
    sp = sub.add_parser("bench")
    sp.add_argument("image")
    sp.add_argument("--io-size", default="4K")
    sp.add_argument("--io-total", default="4M")
    args = p.parse_args(argv)
    return asyncio.run(amain(args))


if __name__ == "__main__":
    sys.exit(main())
