"""Recovery bench rig: repair I/O under RS vs LRC vs PMSR.

The same kill -> degraded-write -> revive -> recover drive
(``chaos.recovery_round``) run once per code family on identical
seeds and object sets, reporting what each code's recovery actually
MOVED (the ``ec_recovery`` counters: repair bytes read from surviving
shards vs bytes shipped to the rebuilt shard) and the recovery wall
clock.  RS repair reads k full chunks per lost shard; LRC reads the
local group (l chunks); product-matrix MSR gathers d beta-sized
fragments (d/alpha = 2 chunks' worth).  The measured read/shipped
ratios are the artifact -- ``bench.py --recovery`` gates on them:

  * zero failed/wedged ops and byte-identical read-back through every
    kill/recover drive (verified against a survivor kill, so reads
    MUST decode through the recovered shards);
  * LRC single-failure repair reads <= 0.5x the RS byte ratio at the
    k=8-class config (l=3 -> 3 reads vs 8);
  * PMSR helper traffic strictly under k full chunks per rebuilt
    shard (fragment pulls counted, not assumed).
"""

from __future__ import annotations

import random
import time

from .chaos import ChaosCluster, recovery_round

# heartbeats tuned like the chaos driver: fast detection, no auto-out
_MON_CONFIG = {"mon_osd_down_out_interval": 3600.0}
_OSD_CONFIG = {"osd_heartbeat_interval": 0.2,
               "osd_heartbeat_grace": 3.0}


def code_configs(smoke: bool) -> list[dict]:
    """The comparison set.  Widths differ per code (that is the
    point); every drive gets width-many OSDs and the same seed.  The
    smoke set keeps the LRC shape in the k=8 class (l/k = 3/8 <= 0.5
    is the acceptance ratio) but trims objects/sizes to tier-1 cost;
    the full set grows the working set."""
    if smoke:
        # trimmed widths (25 OSDs total instead of 41) with the SAME
        # ratio contracts: l/k = 2/4 = 0.5 (the gate boundary -- the
        # counters are deterministic, so exact equality holds) and
        # d/alpha = 4/2 = 2 < k = 3
        lrc = {"name": "lrc", "plugin": "lrc", "k": 4, "m": 4,
               "extra": {"l": 2}, "expect_read_chunks": 2}
        pmsr = {"name": "pmsr", "plugin": "pmsr", "k": 3, "m": 2,
                "extra": {}, "expect_read_chunks": 2}
        rs = {"name": "rs", "plugin": "tpu", "k": 4, "m": 4,
              "extra": {}, "expect_read_chunks": 4}
        return [rs, lrc, pmsr]
    lrc = {"name": "lrc", "plugin": "lrc", "k": 8, "m": 4,
           "extra": {"l": 3}, "expect_read_chunks": 3}
    pmsr = {"name": "pmsr", "plugin": "pmsr", "k": 5, "m": 4,
            "extra": {}, "expect_read_chunks": 2}  # d/alpha = 8/4
    rs = {"name": "rs", "plugin": "tpu", "k": 8, "m": 4,
          "extra": {}, "expect_read_chunks": 8}
    return [rs, lrc, pmsr]


async def _drive_code(code_spec: dict, *, n_objects: int, obj_size: int,
                      pg_num: int, seed: int, log) -> dict:
    k, m = code_spec["k"], code_spec["m"]
    from ..ec import registry
    profile = {"k": str(k), "m": str(m),
               **{kk: str(vv) for kk, vv in code_spec["extra"].items()}}
    width = registry().factory(code_spec["plugin"], dict(profile)) \
        .get_chunk_count()
    n_osds = width
    log(f"  [{code_spec['name']}] {code_spec['plugin']} k={k} m={m} "
        f"{code_spec['extra']} width={width} ({n_osds} osds)")
    c = await ChaosCluster.create(n_osds, mon_config=dict(_MON_CONFIG),
                                  osd_config=dict(_OSD_CONFIG))
    try:
        await c.create_ec_pool("recpool", k, m, pg_num,
                               plugin=code_spec["plugin"],
                               profile_extra=code_spec["extra"])
        t0 = time.perf_counter()
        res = await recovery_round(
            c, rnd=random.Random(seed), pool="recpool",
            n_objects=n_objects, obj_size=obj_size,
            kill_indices=[n_osds - 1], log=log)
        rep = res.get("repair", {})
        read = rep.get("repair_bytes_read", 0)
        shipped = rep.get("repair_bytes_shipped", 0)
        out = {
            "code": code_spec["name"], "plugin": code_spec["plugin"],
            "k": k, "m": m, **code_spec["extra"], "width": width,
            "n_objects": res["n_objects"],
            "repair_bytes_read": read,
            "repair_bytes_shipped": shipped,
            "repair_GiB_read": round(read / 2**30, 6),
            "repair_GiB_shipped": round(shipped / 2**30, 6),
            "read_per_shipped": round(read / shipped, 3)
            if shipped else 0.0,
            "expect_read_chunks": code_spec["expect_read_chunks"],
            "repair_reads": rep.get("repair_reads", 0),
            "repair_local_repairs": rep.get("repair_local_repairs",
                                            0),
            "repair_global_decodes": rep.get("repair_global_decodes",
                                             0),
            "repair_fragment_pulls": rep.get("repair_fragment_pulls",
                                             0),
            "recovery_wall_s": res.get("recovery_wall_s", 0.0),
            "drive_wall_s": round(time.perf_counter() - t0, 3),
            "recovered_clean": res.get("recovered_clean", False),
            "mismatched": res["mismatched"],
            "errors": res["errors"],
        }
        log(f"  [{code_spec['name']}] read/shipped="
            f"{out['read_per_shipped']}x (expect ~"
            f"{code_spec['expect_read_chunks']}), wall="
            f"{out['recovery_wall_s']}s")
        return out
    finally:
        await c.stop()


async def run_recovery_bench(*, n_objects: int = 8,
                             obj_size: int = 64 << 10,
                             pg_num: int = 8, seed: int = 7,
                             smoke: bool = False,
                             log=print) -> dict:
    codes = {}
    for code_spec in code_configs(smoke):
        codes[code_spec["name"]] = await _drive_code(
            code_spec, n_objects=n_objects, obj_size=obj_size,
            pg_num=pg_num, seed=seed, log=log)
    rs, lrc, pmsr = codes["rs"], codes["lrc"], codes["pmsr"]
    ratio = (lrc["read_per_shipped"] / rs["read_per_shipped"]
             if rs["read_per_shipped"] else 0.0)
    return {
        "spec": {"n_objects": n_objects, "obj_size": obj_size,
                 "pg_num": pg_num, "seed": seed},
        "codes": codes,
        "lrc_vs_rs_read_ratio": round(ratio, 3),
        "pmsr_read_chunks": pmsr["read_per_shipped"],
        "failed_objects": sum(len(c["mismatched"])
                              for c in codes.values()),
        "errors": sum(len(c["errors"]) for c in codes.values()),
    }
