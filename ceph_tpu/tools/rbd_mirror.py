"""rbd-mirror daemon launcher (src/tools/rbd_mirror analog).

Replays enabled images from a primary cluster's pool to a secondary:

    python -m ceph_tpu.tools.rbd_mirror \
        --src-mon 127.0.0.1:6789 --dst-mon 127.0.0.1:6790 \
        -p rbd --interval 10

Enable images on the primary first:
    python -m ceph_tpu.tools.rbd_cli --mon ... mirror enable <image>
"""

from __future__ import annotations

import argparse
import asyncio
import signal
import sys

from ..client import Rados
from ..rbd.mirror import MirrorDaemon


async def amain(args) -> int:
    sh, sp = args.src_mon.rsplit(":", 1)
    dh, dp = args.dst_mon.rsplit(":", 1)
    src = dst = None
    try:
        # both connects INSIDE the try: a dst failure must still tear
        # down the already-connected src session
        src = await Rados((sh, int(sp)),
                          name="client.rbd-mirror-src").connect()
        dst = await Rados((dh, int(dp)),
                          name="client.rbd-mirror-dst").connect()
        if args.pool not in await dst.pool_list():
            await dst.pool_create(args.pool, pg_num=args.pg_num)
        sio = await src.open_ioctx(args.pool)
        dio = await dst.open_ioctx(args.pool)
        daemon = MirrorDaemon(sio, dio, interval=args.interval)
        daemon.start()
        print(f"rbd-mirror: replaying pool '{args.pool}' every "
              f"{args.interval}s", flush=True)
        stop = asyncio.Event()
        loop = asyncio.get_event_loop()
        for s in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(s, stop.set)
        await stop.wait()
        await daemon.stop()
        return 0
    finally:
        if src is not None:
            await src.shutdown()
        if dst is not None:
            await dst.shutdown()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="rbd-mirror")
    p.add_argument("--src-mon", required=True)
    p.add_argument("--dst-mon", required=True)
    p.add_argument("-p", "--pool", default="rbd")
    p.add_argument("--pg-num", type=int, default=16)
    p.add_argument("--interval", type=float, default=10.0)
    args = p.parse_args(argv)
    return asyncio.run(amain(args))


if __name__ == "__main__":
    sys.exit(main())
